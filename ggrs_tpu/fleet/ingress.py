"""Ingress plane: stable virtual match endpoints (DESIGN.md §26).

A match's wire address used to be a port pinned by its ``socket_factory``
on whatever host admitted it — so nothing could move.  This module puts a
:class:`~ggrs_tpu.net.sockets.DispatchHub` AT THE EDGE: the ingress owns
one public UDP port (plus SO_REUSEPORT siblings) and hands every match a
*virtual endpoint* — a small integer ``vport`` demuxed by claimed peer
source address, exactly the §23 dispatch demux one level up.  Peers and
spectators talk to ``(ingress_ip, public_port)`` forever; which host
actually serves the match is a ROUTE TABLE entry the placement service
flips after a migration or a §16 journal failover.  The flip is invisible
on the public side: same address, a retransmission hiccup, not a reset.

Fencing (the §25 lesson, applied to routes): every route update carries
the placement-minted ``epoch`` and a monotonically increasing route
``version``.  The ingress refuses anything not strictly newer than the
per-vport floor it has already accepted — a stale supervisor (fenced by a
failover it slept through) can never flip a route back.  The floor
survives route deletion, so a late PUT from a dead epoch stays refused.
The same fence guards the dataplane: host→peer datagrams are accepted
only from the route's registered leg address, so a fenced incarnation
that is still breathing cannot speak AS the virtual endpoint.

Wire formats (pinned in the §20 layout contract table):

- ``FWD_HEADER`` — the forwarded-datagram header wrapping every payload
  on the ingress↔host leg: magic ``GI``, version, flags, vport, and the
  public peer's address (port + IPv4), 12 bytes.
- ``ROUTE_UPDATE`` — the route-update frame: magic, version, op
  (PUT/DEL), epoch, route version, vport, and the serving leg's address,
  28 bytes.  Travels as packed bytes over the §25 authenticated TCP link
  (the ``ingress_route`` RPC op) and through the in-process path — ONE
  decoder (:func:`decode_route_update`) judges both.

Roles:

- :class:`IngressNode` — the dataplane object (ThreadOwned): hub + route
  table + the forwarding pump.  Usable in-process (tests, single-box).
- :class:`IngressRunner` — the §17 runner harness around a node: same
  RPC/heartbeat/GOODBYE plumbing as a shard runner, serving loop selects
  on the dataplane fds, route updates arrive as RPC ops.
- :class:`IngressHandle` — the placement-side proxy over the §25
  :class:`~ggrs_tpu.fleet.transport.ShardLink`: duck-types the node's
  control surface so :class:`~ggrs_tpu.fleet.placement_service.
  PlacementService` drives local and remote ingress identically.
- :class:`VirtualEndpointSocket` — the serving-host leg: a picklable
  ``socket_factory`` product that wraps/unwraps ``FWD_HEADER`` so a
  session bank behind an ingress needs no code changes at all.
"""

from __future__ import annotations

import os
import select
import socket as _socket
import struct
import subprocess
import sys
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

from ..core.errors import InvalidRequest
from ..net.messages import Message
from ..net.sockets import (
    DispatchHub,
    DispatchSocket,
    RECV_BUFFER_SIZE,
    UdpNonBlockingSocket,
    _TRANSIENT_SEND_ERRNOS,
)
from ..net.wire import WireError
from ..obs.fleet_obs import RegistryCollector
from ..obs.registry import DEFAULT, Registry
from ..obs.timeline import (
    EV_ROUTE_FLIP,
    ZERO_TRACE_CTX,
    timeline_event,
    unpack_trace_ctx,
)
from ..utils.ownership import ThreadOwned
from ..utils.tracing import get_logger
from .proc import ShardRunner, _GracefulExit
from .rpc import KIND_CALL, KIND_HEARTBEAT, RpcConn, RpcError, RpcTimeout
from .transport import ShardLink
from .tuning import FleetTuning

_logger = get_logger("fleet")

_REPO_ROOT = Path(__file__).resolve().parents[2]
_RUNNER_SCRIPT = _REPO_ROOT / "scripts" / "shard_runner.py"


# ----------------------------------------------------------------------
# wire structs (§20 layout contract table: analysis/layout.py parses
# exactly these definitions — keep names/formats in sync with the table)
# ----------------------------------------------------------------------

INGRESS_MAGIC = b"GI"
FWD_VERSION = 1
# v2 (DESIGN.md §28): the route-update frame grew a trailing 16-byte
# trace context (obs/timeline.py TRACE_CTX) — the placement plane's
# causal stamp rides the same fenced bytes as the route itself
ROUTE_WIRE_VERSION = 2

# forwarded-datagram header (ingress<->host leg): magic, version, flags,
# vport, peer_port, peer_ipv4 — the payload follows verbatim
FWD_HEADER = struct.Struct("<2sBBHH4s")

# route-update frame: magic, version, op, epoch, route version, vport,
# dst_port, dst_ipv4, trace_ctx — refused unless (epoch, version) beats
# the floor
ROUTE_UPDATE = struct.Struct("<2sBBQQHH4s16s")

ROUTE_OP_PUT = 1
ROUTE_OP_DEL = 2


def encode_route_update(op: int, epoch: int, version: int, vport: int,
                        dst: Tuple[str, int],
                        ctx: bytes = ZERO_TRACE_CTX) -> bytes:
    """Pack one route update.  ``dst`` is the serving leg's (ipv4, port);
    for a DEL the address still rides along (it names the leg being
    retired, useful in logs) but is not required to resolve.  ``ctx`` is
    the packed 16-byte trace context (``pack_trace_ctx``; all-zero =
    no causal stamp)."""
    host, port = dst
    return ROUTE_UPDATE.pack(
        INGRESS_MAGIC, ROUTE_WIRE_VERSION, op, epoch, version, vport,
        port, _socket.inet_aton(host), ctx,
    )


def decode_route_update(
    data: bytes,
) -> Tuple[int, int, int, int, Tuple[str, int], bytes]:
    """Unpack + validate one route update; raises :class:`WireError` on
    anything malformed (the single judgment both the RPC op and the
    in-process path share).  The last element is the packed 16-byte
    trace context."""
    if len(data) != ROUTE_UPDATE.size:
        raise WireError(
            f"route update: {len(data)} bytes, want {ROUTE_UPDATE.size}")
    magic, ver, op, epoch, version, vport, port, ip4, ctx = \
        ROUTE_UPDATE.unpack(data)
    if magic != INGRESS_MAGIC:
        raise WireError(f"route update: bad magic {magic!r}")
    if ver != ROUTE_WIRE_VERSION:
        raise WireError(f"route update: unsupported version {ver}")
    if op not in (ROUTE_OP_PUT, ROUTE_OP_DEL):
        raise WireError(f"route update: unknown op {op}")
    return op, epoch, version, vport, (_socket.inet_ntoa(ip4), port), ctx


def pack_fwd(vport: int, peer: Tuple[str, int], payload: bytes,
             flags: int = 0) -> bytes:
    """Wrap one datagram for the ingress<->host leg."""
    host, port = peer
    return FWD_HEADER.pack(
        INGRESS_MAGIC, FWD_VERSION, flags, vport, port,
        _socket.inet_aton(host),
    ) + payload


def unpack_fwd(data: bytes) -> Tuple[int, Tuple[str, int], bytes]:
    """Unwrap one forwarded datagram -> (vport, peer_addr, payload)."""
    if len(data) < FWD_HEADER.size:
        raise WireError(f"fwd header: short frame ({len(data)} bytes)")
    magic, ver, _flags, vport, port, ip4 = FWD_HEADER.unpack_from(data)
    if magic != INGRESS_MAGIC:
        raise WireError(f"fwd header: bad magic {magic!r}")
    if ver != FWD_VERSION:
        raise WireError(f"fwd header: unsupported version {ver}")
    return vport, (_socket.inet_ntoa(ip4), port), data[FWD_HEADER.size:]


@dataclass
class RouteEntry:
    """One live route: the serving leg plus the fence that admitted it."""

    dst: Tuple[str, int]
    epoch: int
    version: int


# ======================================================================
# the dataplane: IngressNode
# ======================================================================


class IngressNode(ThreadOwned):
    """The ingress dataplane: one public DispatchHub, a per-vport route
    table, and the forwarding pump.  Single-owner (ThreadOwned): the
    serving loop that calls :meth:`pump` is the only thread allowed to
    mutate routes — route updates arrive through that same loop (RPC op
    or in-process call), never concurrently."""

    _DRIVING_METHODS = ("pump", "allocate_endpoint", "claim_peers",
                        "apply_route_update", "close")

    def __init__(self, *, name: str = "ingress",
                 host: str = "127.0.0.1", port: int = 0,
                 uplink_port: int = 0, siblings: int = 0,
                 metrics: Optional[Registry] = None,
                 tuning: Optional[FleetTuning] = None) -> None:
        self.name = name
        self.host = host
        self.tuning = tuning if tuning is not None else FleetTuning()
        self.metrics = metrics if metrics is not None else Registry()
        # the public face: one port, many virtual endpoints
        self.hub = DispatchHub(port=port, siblings=siblings)
        # the private face: host legs send/receive forwarded datagrams
        # here (separate from the public port so a public peer can never
        # forge a FWD_HEADER into the forwarding path)
        self._uplink = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        self._uplink.bind(("0.0.0.0", uplink_port))
        self._uplink.setblocking(False)
        try:
            self._uplink.setsockopt(
                _socket.SOL_SOCKET, _socket.SO_RCVBUF, 8 << 20)
        except OSError:
            pass
        self._views: Dict[int, DispatchSocket] = {}
        self._peers: Dict[int, Set[Tuple[str, int]]] = {}
        self._routes: Dict[int, RouteEntry] = {}
        # the per-vport (epoch, version) floor — survives DEL, so a
        # fenced writer stays fenced even after its route is retired
        self._fence: Dict[int, Tuple[int, int]] = {}
        self._next_vport = 1
        self._recv_buf = bytearray(RECV_BUFFER_SIZE)
        self._recv_view = memoryview(self._recv_buf)
        # route-flip timeline events (§28): buffered here, ferried by
        # the runner's existing heartbeat obs payload (keyed by the wire
        # trace context's hex — the ingress never learns a match id)
        self._timeline_items: List[Dict[str, Any]] = []
        # plain mirrors for info()/healthz (cheap, no registry walk)
        self.flips = 0
        self.forwarded = {"in": 0, "out": 0}
        self.forwarded_bytes = {"in": 0, "out": 0}
        self.dropped: Dict[str, int] = {}
        self.route_updates: Dict[str, int] = {}
        m = self.metrics
        self._g_routes = m.gauge(
            "ggrs_ingress_routes", "live virtual-endpoint routes")
        self._g_vports = m.gauge(
            "ggrs_ingress_vports", "allocated virtual endpoints")
        self._c_updates = m.counter(
            "ggrs_ingress_route_updates_total",
            "route updates judged, by verdict", labels=("verdict",))
        self._c_flips = m.counter(
            "ggrs_ingress_route_flips_total",
            "accepted PUTs that moved an existing route to a new leg")
        self._c_fwd = m.counter(
            "ggrs_ingress_forwarded_datagrams_total",
            "datagrams forwarded through the ingress, by direction",
            labels=("dir",))
        self._c_fwd_bytes = m.counter(
            "ggrs_ingress_forwarded_bytes_total",
            "payload bytes forwarded through the ingress, by direction",
            labels=("dir",))
        self._c_drop = m.counter(
            "ggrs_ingress_dropped_datagrams_total",
            "datagrams the forwarding pump refused, by reason",
            labels=("reason",))

    # -- addresses -----------------------------------------------------

    def public_addr(self) -> Tuple[str, int]:
        """The address peers and spectators dial — stable for the life
        of the ingress, whatever happens to the hosts behind it."""
        return (self.host, self.hub.local_port())

    def uplink_addr(self) -> Tuple[str, int]:
        """Where host legs send forwarded datagrams."""
        return (self.host, self._uplink.getsockname()[1])

    def filenos(self) -> List[int]:
        return self.hub.filenos() + [self._uplink.fileno()]

    # -- control surface -----------------------------------------------

    def allocate_endpoint(self, peers: Any = ()) -> int:
        """Mint a virtual endpoint: a fresh vport demuxed on the public
        port, optionally pre-claiming the peer source addresses that
        belong to it."""
        self._check_owner()
        vport = self._next_vport
        self._next_vport += 1
        self._views[vport] = self.hub.view()
        self._peers[vport] = set()
        if peers:
            self.claim_peers(vport, peers)
        self._g_vports.set(len(self._views))
        return vport

    def claim_peers(self, vport: int, peers: Any) -> None:
        """Bind public source addresses to a vport (the §23 demux claim,
        one level up).  Late joiners claim as they appear."""
        self._check_owner()
        view = self._views.get(vport)
        if view is None:
            raise InvalidRequest(f"no virtual endpoint {vport}")
        for addr in peers:
            addr = (addr[0], int(addr[1]))
            view.claim(addr)
            self._peers[vport].add(addr)

    def apply_route_update(self, data: bytes) -> str:
        """Judge one packed route update; returns the verdict string
        (``ok`` / ``stale-epoch`` / ``stale-version`` / ``unknown-vport``
        / ``bad-frame``).  The ONE code path both the RPC op and the
        in-process caller go through — there is no unfenced side door."""
        self._check_owner()
        try:
            op, epoch, version, vport, dst, ctx = decode_route_update(data)
        except WireError:
            return self._judge_update("bad-frame")
        if vport not in self._views:
            return self._judge_update("unknown-vport")
        floor = self._fence.get(vport)
        if floor is not None:
            f_epoch, f_version = floor
            if epoch < f_epoch:
                return self._judge_update("stale-epoch")
            if epoch == f_epoch and version <= f_version:
                return self._judge_update("stale-version")
        self._fence[vport] = (epoch, version)
        prev = self._routes.get(vport)
        if op == ROUTE_OP_DEL:
            self._routes.pop(vport, None)
        else:
            self._routes[vport] = RouteEntry(dst, epoch, version)
            if prev is not None and prev.dst != dst:
                self.flips += 1
                self._c_flips.inc()
                # §28: the flip, as witnessed at the dataplane, stamped
                # with the trace context the fenced bytes carried — the
                # cross-host join key is the trace hash, not a match id
                trace, ctx_epoch, span = (
                    unpack_trace_ctx(ctx) if ctx != ZERO_TRACE_CTX
                    else (0, 0, 0))
                ev = timeline_event(
                    EV_ROUTE_FLIP, f"trace:{trace:016x}",
                    origin=self.name, epoch=ctx_epoch, span=span,
                    detail={"vport": vport,
                            "from": f"{prev.dst[0]}:{prev.dst[1]}",
                            "to": f"{dst[0]}:{dst[1]}"},
                )
                ev["trace"] = trace
                self._timeline_items.append(ev)
                del self._timeline_items[:-64]
        self._g_routes.set(len(self._routes))
        return self._judge_update("ok")

    def _judge_update(self, verdict: str) -> str:
        self.route_updates[verdict] = self.route_updates.get(verdict, 0) + 1
        self._c_updates.labels(verdict=verdict).inc()
        return verdict

    # -- the forwarding pump -------------------------------------------

    def pump(self) -> None:
        """One non-blocking forwarding cycle: drain the public hub once,
        relay every claimed datagram to its route's serving leg; drain
        the uplink, relay every fenced-clean reply out the public port
        (so replies leave from the stable public address)."""
        self._check_owner()
        self.hub.drain()
        for vport, view in self._views.items():
            pending = view.take_pending()
            if not pending:
                continue
            route = self._routes.get(vport)
            for peer, payload in pending:
                if route is None:
                    self._drop("no-route")
                    continue
                data = pack_fwd(vport, peer, payload)
                try:
                    self._uplink.sendto(data, route.dst)
                except OSError as e:
                    if e.errno not in _TRANSIENT_SEND_ERRNOS:
                        raise
                    self._drop("uplink-send")
                    continue
                self.forwarded["in"] += 1
                self.forwarded_bytes["in"] += len(payload)
                self._c_fwd.labels(dir="in").inc()
                self._c_fwd_bytes.labels(dir="in").inc(len(payload))
        buf, view = self._recv_buf, self._recv_view
        while True:
            try:
                n, src = self._uplink.recvfrom_into(buf, RECV_BUFFER_SIZE)
            except BlockingIOError:
                break
            except ConnectionError:
                continue
            try:
                vport, peer, payload = unpack_fwd(bytes(view[:n]))
            except WireError:
                self._drop("bad-frame")
                continue
            route = self._routes.get(vport)
            if route is None:
                self._drop("no-route")
                continue
            if src != route.dst:
                # the dataplane fence: only the CURRENT route's leg may
                # speak as this virtual endpoint — a fenced incarnation
                # still breathing is dropped here, not trusted
                self._drop("fenced-sender")
                continue
            if peer not in self._peers.get(vport, ()):
                self._drop("unclaimed-peer")
                continue
            self.hub.send_datagram(payload, peer)
            self.forwarded["out"] += 1
            self.forwarded_bytes["out"] += len(payload)
            self._c_fwd.labels(dir="out").inc()
            self._c_fwd_bytes.labels(dir="out").inc(len(payload))

    def _drop(self, reason: str) -> None:
        self.dropped[reason] = self.dropped.get(reason, 0) + 1
        self._c_drop.labels(reason=reason).inc()

    def drain_timeline(self) -> List[Dict[str, Any]]:
        """Buffered route-flip timeline events, cleared — the runner's
        heartbeat payload ships these (§28 piggyback contract)."""
        out = self._timeline_items
        self._timeline_items = []
        return out

    # -- introspection / teardown --------------------------------------

    def info(self) -> Dict[str, Any]:
        return dict(
            name=self.name,
            public=list(self.public_addr()),
            uplink=list(self.uplink_addr()),
            vports=len(self._views),
            routes=len(self._routes),
            flips=self.flips,
            forwarded=dict(self.forwarded),
            forwarded_bytes=dict(self.forwarded_bytes),
            dropped=dict(self.dropped),
            route_updates=dict(self.route_updates),
            unroutable=self.hub.unroutable,
        )

    def close(self) -> None:
        self._check_owner()
        self.hub.close()
        self._uplink.close()


# ======================================================================
# the serving-host leg: VirtualEndpointSocket
# ======================================================================


class VirtualEndpointSocket:
    """The host-side leg of a virtual endpoint: a ``NonBlockingSocket``
    whose wire peer is the ingress uplink.  Outbound wraps the payload in
    ``FWD_HEADER`` (naming the real public peer); inbound unwraps, so the
    session bank above sees plain (peer_addr, payload) datagrams and
    needs no ingress awareness at all.

    ``is_dispatch`` keeps pools from attaching the leg to the in-crossing
    NetBatch path (the header wrap must happen in Python; the native
    parser would read the FWD bytes as protocol).  Binds an EPHEMERAL
    port by default — failover re-legs never fight EADDRINUSE, because
    the public address lives at the ingress, not here."""

    is_dispatch = True

    def __init__(self, uplink_host: str, uplink_port: int,
                 vport: int, port: int = 0) -> None:
        self._sock = UdpNonBlockingSocket(port)
        self._uplink = (uplink_host, int(uplink_port))
        self.vport = vport

    @property
    def stats(self):
        return self._sock.stats

    @property
    def io_syscalls(self) -> int:
        return self._sock.io_syscalls

    def fileno(self) -> int:
        return self._sock.fileno()

    def local_port(self) -> int:
        return self._sock.local_port()

    def send_to(self, msg: Message, addr: Tuple[str, int]) -> None:
        self.send_datagram(msg.encode(), addr)

    def send_datagram(self, data: bytes, addr: Tuple[str, int]) -> None:
        self._sock.send_datagram(
            pack_fwd(self.vport, addr, bytes(data)), self._uplink)

    def send_datagram_batch(
        self, items: List[Tuple[bytes, Tuple[str, int]]]
    ) -> None:
        self._sock.send_datagram_batch([
            (pack_fwd(self.vport, addr, bytes(data)), self._uplink)
            for data, addr in items
        ])

    def receive_all_messages(self) -> List[Tuple[Tuple[str, int], Message]]:
        received: List[Tuple[Tuple[str, int], Message]] = []
        for src, data in self.receive_all_datagrams():
            try:
                received.append((src, Message.decode(data)))
            except WireError:
                continue
        return received

    def receive_all_datagrams(self) -> List[Tuple[Tuple[str, int], bytes]]:
        out: List[Tuple[Tuple[str, int], bytes]] = []
        for src, data in self._sock.receive_all_datagrams():
            if src != self._uplink:
                continue  # only the ingress may speak to a leg
            try:
                vport, peer, payload = unpack_fwd(data)
            except WireError:
                continue
            if vport != self.vport:
                continue
            out.append((peer, payload))
        return out

    def close(self) -> None:
        self._sock.close()


def virtual_endpoint_socket(uplink_host: str, uplink_port: int,
                            vport: int, port: int = 0
                            ) -> VirtualEndpointSocket:
    """Picklable ``socket_factory`` for ingress-fronted matches:
    ``functools.partial(virtual_endpoint_socket, host, port, vport)`` is
    the shape the placement service admits with — the leg binds IN the
    serving process (in-process shard or runner child alike), so
    migration and failover mint a fresh leg wherever the match lands."""
    return VirtualEndpointSocket(uplink_host, uplink_port, vport,
                                 port=port)


# ======================================================================
# the §17 runner harness: IngressRunner
# ======================================================================


class IngressRunner(ShardRunner):
    """An ingress-role runner: the same framed-RPC/heartbeat/GOODBYE
    plumbing as :class:`~ggrs_tpu.fleet.proc.ShardRunner` (serve(),
    reconnect-or-exit, graceful drain), but the serving loop pumps an
    :class:`IngressNode` dataplane instead of ticking a PoolShard, and
    selects on the dataplane fds so forwarding latency is bounded by
    wire arrival, not the RPC heartbeat cadence."""

    def __init__(self, conn: RpcConn, link=None) -> None:
        super().__init__(conn, link=link)
        self.node: Optional[IngressNode] = None

    def _loop(self) -> None:
        hb_next = time.monotonic() + self.tuning.heartbeat_interval_s
        while True:
            now = time.monotonic()
            if now >= hb_next:
                hb_next = now + self.tuning.heartbeat_interval_s
                if self.node is not None:
                    payload = self._obs_payload(include_spans=False)
                    timeline = self.node.drain_timeline()
                    if timeline:
                        if payload is None:
                            payload = {"now_ns": time.perf_counter_ns()}
                        payload["timeline"] = timeline
                    try:
                        self.conn.send(KIND_HEARTBEAT, dict(
                            info=self.node.info(),
                            obs=payload,
                        ), timeout=5.0)
                    except RpcTimeout:
                        self._requeue_obs(payload)
                        if payload and payload.get("timeline"):
                            self.node._timeline_items[:0] = (
                                payload["timeline"])
                            del self.node._timeline_items[:-64]
            wait = max(0.0, hb_next - now)
            fds = [self.conn.fileno()]
            if self.node is not None:
                # bound the wait so a pump cycle runs even when neither
                # plane is readable (claims/obs mirrors stay fresh)
                wait = min(wait, self.tuning.ingress_select_timeout_s)
                fds += self.node.filenos()
            r, _, _ = select.select(fds, [], [], wait)
            if self.node is not None:
                self.node.pump()
            if self.conn.fileno() not in r:
                continue
            kind, msg = self.conn.recv(timeout=10.0)
            if kind != KIND_CALL:
                continue
            self._dispatch(msg)
            if self._exit_after_reply is not None:
                raise _GracefulExit(self._exit_after_reply)

    # -- ops -----------------------------------------------------------

    def _op_hello(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        cfg = msg["config"]
        if cfg.get("tuning"):
            self.tuning = FleetTuning.from_dict(cfg["tuning"])
            self.conn.max_frame = self.tuning.max_frame_bytes
        if self._link is not None:
            self._link.configure(self.tuning)
            self.conn.enable_retain(self.tuning.link_retain_frames)
        self.node = IngressNode(
            name=cfg.get("shard_id", "ingress"),
            host=cfg.get("host", "127.0.0.1"),
            port=cfg.get("port", 0),
            uplink_port=cfg.get("uplink_port", 0),
            siblings=cfg.get("siblings", 0),
            tuning=self.tuning,
        )
        if self.tuning.obs_harvest:
            self.collector = RegistryCollector(
                self.node.metrics, DEFAULT, gen=os.getpid(),
            )
        return dict(
            pid=os.getpid(), role="ingress", shard_id=self.node.name,
            public=list(self.node.public_addr()),
            uplink=list(self.node.uplink_addr()),
        )

    def _op_ingress_allocate(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        vport = self._require_node().allocate_endpoint(
            peers=[tuple(a) for a in msg.get("peers", ())])
        return dict(vport=vport)

    def _op_ingress_claim(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        self._require_node().claim_peers(
            msg["vport"], [tuple(a) for a in msg.get("peers", ())])
        return {}

    def _op_ingress_route(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return dict(
            verdict=self._require_node().apply_route_update(msg["update"]))

    def _op_ingress_info(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return self._require_node().info()

    def _require_node(self) -> IngressNode:
        if self.node is None:
            raise InvalidRequest("no hello received yet")
        return self.node

    # -- teardown ------------------------------------------------------

    def _graceful_exit(self, reason: str) -> None:
        try:
            super()._graceful_exit(reason)
        finally:
            if self.node is not None:
                self.node.close()

    def _quiet_exit(self, reason: str) -> None:
        try:
            super()._quiet_exit(reason)
        finally:
            if self.node is not None:
                self.node.close()


# ======================================================================
# the placement-side proxy: IngressHandle
# ======================================================================


class IngressHandle:
    """Adopt and drive a remote ``shard_runner.py --ingress --tcp`` over
    the §25 authenticated link, presenting the :class:`IngressNode`
    control surface (allocate/claim/route/info/addresses) so the
    placement service is transport-blind.  The epoch the link mints at
    adoption is the SAME fencing domain route updates ride in — one
    mint, two planes."""

    def __init__(self, name: str = "ingress", *,
                 tuning: Optional[FleetTuning] = None,
                 host: str = "127.0.0.1",
                 metrics: Optional[Registry] = None,
                 spawn_child: bool = False) -> None:
        self.name = name
        self.tuning = tuning if tuning is not None else FleetTuning.from_env()
        self.metrics = metrics if metrics is not None else Registry()
        self.link = ShardLink(name, self.tuning, host=host,
                              metrics=self.metrics)
        self._spawn_child = spawn_child
        self._proc: Optional[subprocess.Popen] = None
        self._conn: Optional[RpcConn] = None
        self._public: Optional[Tuple[str, int]] = None
        self._uplink_addr: Optional[Tuple[str, int]] = None
        self.pid: Optional[int] = None
        self.last_heartbeat: Dict[str, Any] = {}
        # armed by the placement service: heartbeat obs land here
        self.obs = None

    @property
    def address(self) -> Tuple[str, int]:
        """The TCP address an external ``--ingress --tcp`` runner dials."""
        return self.link.address

    def adopt(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Mint an epoch, (optionally) fork a local ingress runner, wait
        for the authenticated handshake, and hello it."""
        self.link.reopen()
        self.link.mint_epoch()
        if self._spawn_child:
            host, port = self.link.address
            env = dict(
                os.environ,
                GGRS_FLEET_LINK_AUTH_TOKEN=self.tuning.link_auth_token,
                GGRS_FLEET_LINK_SHARD=self.name,
            )
            self._proc = subprocess.Popen(
                [sys.executable, str(_RUNNER_SCRIPT),
                 "--ingress", "--tcp", f"{host}:{port}"],
                env=env,
            )
        sock = self.link.wait_for_runner(
            timeout if timeout is not None else self.tuning.spawn_timeout_s)
        conn = RpcConn(sock, max_frame=self.tuning.max_frame_bytes)
        conn.enable_retain(self.tuning.link_retain_frames)
        r = conn.call(
            "hello", timeout=self.tuning.spawn_timeout_s,
            config=dict(shard_id=self.name, tuning=self.tuning.as_dict()),
        )
        self.link.established(conn)
        conn.on_heartbeat = self._on_heartbeat
        self._conn = conn
        self.pid = r["pid"]
        self._public = tuple(r["public"])
        self._uplink_addr = tuple(r["uplink"])
        return r

    def _on_heartbeat(self, obj: Any) -> None:
        if not isinstance(obj, dict):
            return
        self.last_heartbeat = obj
        payload = obj.get("obs")
        if payload and self.obs is not None:
            self.obs.ingest(self.name, payload, backend="ingress")

    def _call(self, op: str, **kw: Any) -> Any:
        if self._conn is None:
            raise InvalidRequest(f"ingress {self.name!r} not adopted")
        return self._conn.call(op, timeout=self.tuning.rpc_timeout_s, **kw)

    def pump(self) -> None:
        """Drive the link's accept/handshake machinery and drain any
        heartbeat frames waiting on the conn."""
        self.link.pump()
        if self._conn is not None:
            try:
                self._conn.poll_frames()
            except RpcError:
                pass

    # -- the IngressNode control surface, by proxy ---------------------

    def public_addr(self) -> Optional[Tuple[str, int]]:
        return self._public

    def uplink_addr(self) -> Optional[Tuple[str, int]]:
        return self._uplink_addr

    def allocate_endpoint(self, peers: Any = ()) -> int:
        return self._call(
            "ingress_allocate", peers=[list(a) for a in peers])["vport"]

    def claim_peers(self, vport: int, peers: Any) -> None:
        self._call("ingress_claim", vport=vport,
                   peers=[list(a) for a in peers])

    def apply_route_update(self, data: bytes) -> str:
        return self._call("ingress_route", update=data)["verdict"]

    def info(self) -> Dict[str, Any]:
        return self._call("ingress_info")

    def close(self) -> None:
        """Graceful teardown: shutdown RPC (the runner drains + exits),
        then the link and any forked child."""
        if self._conn is not None:
            try:
                self._conn.call("shutdown", timeout=5.0,
                                reason="ingress close")
            except RpcError:
                pass
            self._conn.close()
            self._conn = None
        self.link.close()
        if self._proc is not None:
            try:
                self._proc.wait(timeout=self.tuning.drain_deadline_s)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()
            self._proc = None
