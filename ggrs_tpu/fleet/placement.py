"""Consistent-hash placement for the fleet layer (DESIGN.md §16).

Matches hash onto a ring of virtual points (``replicas`` per shard, md5 —
stable across processes and Python hash randomization), so the owner of a
match moves only when shards join or leave, and every match has a
deterministic *preference order* of fallback shards: admission walks it
when the owner refuses (full / draining / unhealthy), and failover walks
it when the owner is dead.  Placement is pure policy — it never touches a
pool; the :class:`~ggrs_tpu.fleet.supervisor.ShardSupervisor` combines it
with capacity-aware admission checks driven by the obs gauges.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Iterator, List, Tuple


def _point(key: str) -> int:
    """Stable 64-bit ring coordinate (md5 — not security, just uniform and
    identical across processes, unlike ``hash()``)."""
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over shard ids.

    ``replicas`` virtual points per shard smooth the load split; 64 keeps
    the max/min owner imbalance under ~30% for small fleets, which the
    capacity-aware admission check absorbs.
    """

    def __init__(self, shard_ids: Iterable[str] = (),
                 replicas: int = 64) -> None:
        self._replicas = replicas
        self._points: List[Tuple[int, str]] = []
        self._shards: set = set()
        for sid in shard_ids:
            self.add(sid)

    def add(self, shard_id: str) -> None:
        if shard_id in self._shards:
            return
        self._shards.add(shard_id)
        for r in range(self._replicas):
            self._points.append((_point(f"{shard_id}#{r}"), shard_id))
        self._points.sort()

    def remove(self, shard_id: str) -> None:
        if shard_id not in self._shards:
            return
        self._shards.discard(shard_id)
        self._points = [p for p in self._points if p[1] != shard_id]

    def __len__(self) -> int:
        return len(self._shards)

    def __contains__(self, shard_id: str) -> bool:
        return shard_id in self._shards

    def shards(self) -> List[str]:
        return sorted(self._shards)

    def owner(self, match_id: str) -> str:
        """The match's home shard: the first ring point at or after its
        hash (wrapping)."""
        for sid in self.preference(match_id):
            return sid
        raise LookupError("empty hash ring")

    def preference(self, match_id: str) -> Iterator[str]:
        """Every shard, ordered by the ring walk from the match's hash —
        the owner first, then the deterministic fallback order admission
        retries and failover re-placement follow."""
        if not self._points:
            return
        start = bisect.bisect_left(self._points, (_point(match_id), ""))
        seen = set()
        n = len(self._points)
        for i in range(n):
            sid = self._points[(start + i) % n][1]
            if sid not in seen:
                seen.add(sid)
                yield sid
