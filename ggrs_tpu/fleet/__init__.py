"""ggrs_tpu.fleet — sharded pool serving above ``HostSessionPool``
(DESIGN.md §16).

The layer that survives losing a shard: a :class:`ShardSupervisor` owns N
:class:`PoolShard` shards behind a consistent-hash, capacity-aware
placement front (:class:`HashRing`), and treats a running match as a
portable object — **live migration** between shards through the harvest
seam, **graceful drain** (admission off, migrate everything, retire), and
**crash failover** from the durable match journals when a shard dies.
Chaos coverage: ``scripts/chaos.py --fault shard``.
"""

from .ingress import (
    IngressHandle,
    IngressNode,
    IngressRunner,
    VirtualEndpointSocket,
    virtual_endpoint_socket,
)
from .placement import HashRing
from .placement_service import PlacementService
from .proc import ProcShard, ShardRunner, proc_match_builder, runner_clock
from .rpc import (
    FrameError,
    RpcClosed,
    RpcConn,
    RpcError,
    RpcRemoteError,
    RpcTimeout,
)
from .shard import (
    AdoptedMatch,
    PoolShard,
    SHARD_ACTIVE,
    SHARD_DEAD,
    SHARD_DRAINING,
    SHARD_RETIRED,
)
from .supervisor import FleetError, MatchRecord, ShardSupervisor
from .transport import HandshakeError, RunnerLink, ShardLink
from .tuning import FleetTuning

__all__ = [
    "AdoptedMatch",
    "FleetError",
    "FleetTuning",
    "FrameError",
    "HandshakeError",
    "HashRing",
    "IngressHandle",
    "IngressNode",
    "IngressRunner",
    "MatchRecord",
    "PlacementService",
    "PoolShard",
    "ProcShard",
    "RpcClosed",
    "RpcConn",
    "RpcError",
    "RpcRemoteError",
    "RpcTimeout",
    "RunnerLink",
    "SHARD_ACTIVE",
    "SHARD_DEAD",
    "SHARD_DRAINING",
    "SHARD_RETIRED",
    "ShardLink",
    "ShardRunner",
    "ShardSupervisor",
    "VirtualEndpointSocket",
    "proc_match_builder",
    "runner_clock",
    "virtual_endpoint_socket",
]
