"""ggrs_tpu.fleet — sharded pool serving above ``HostSessionPool``
(DESIGN.md §16).

The layer that survives losing a shard: a :class:`ShardSupervisor` owns N
:class:`PoolShard` shards behind a consistent-hash, capacity-aware
placement front (:class:`HashRing`), and treats a running match as a
portable object — **live migration** between shards through the harvest
seam, **graceful drain** (admission off, migrate everything, retire), and
**crash failover** from the durable match journals when a shard dies.
Chaos coverage: ``scripts/chaos.py --fault shard``.
"""

from .placement import HashRing
from .shard import (
    AdoptedMatch,
    PoolShard,
    SHARD_ACTIVE,
    SHARD_DEAD,
    SHARD_DRAINING,
    SHARD_RETIRED,
)
from .supervisor import FleetError, MatchRecord, ShardSupervisor

__all__ = [
    "AdoptedMatch",
    "FleetError",
    "HashRing",
    "MatchRecord",
    "PoolShard",
    "SHARD_ACTIVE",
    "SHARD_DEAD",
    "SHARD_DRAINING",
    "SHARD_RETIRED",
    "ShardSupervisor",
]
