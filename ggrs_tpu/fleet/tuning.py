"""FleetTuning: every fleet-layer timeout/backoff/jitter knob in one
dataclass (DESIGN.md §17).

Before this existed the knobs were scattered module constants
(``supervisor.READMIT_*``, ``host_bank.EVICT_MAX_PER_TICK``) plus ad-hoc
literals in the process backend.  One dataclass means:

- chaos runs can RECORD the knobs they ran with (``as_dict`` rides every
  ``scripts/chaos.py`` JSON artifact, and ``from_dict`` round-trips it);
- deployments override via environment (``GGRS_FLEET_<FIELD>``, e.g.
  ``GGRS_FLEET_HEARTBEAT_DEADLINE_S=5``) without code changes;
- tests shrink the real-time deadlines (heartbeat, drain, restart
  backoff) to keep the watchdog scenarios fast.

The module constants the defaults mirror stay where they were — they are
the documented defaults and existing imports keep working — but every
``ShardSupervisor``/``ProcShard`` instance reads its *own* ``FleetTuning``.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass
from typing import Any, Dict, Mapping, Optional

ENV_PREFIX = "GGRS_FLEET_"


@dataclass
class FleetTuning:
    """The fleet's timing/backoff policy, in one place.

    Tick-denominated knobs (``*_ticks``) ride the supervisor's logical
    tick; seconds-denominated knobs (``*_s``) are wall-clock — the process
    backend's liveness story cannot be tick-based, because a hung
    supervisor loop is exactly one of the failures it must survive.
    """

    # --- process backend: liveness + watchdog (DESIGN.md §17) ---
    # runner → supervisor heartbeat cadence while idle
    heartbeat_interval_s: float = 0.25
    # no frame of any kind from the runner for this long = hang suspect
    heartbeat_deadline_s: float = 2.0
    # per-RPC reply deadline; a tick call exceeding it marks the shard
    # hung (wedged ≠ dead: escalation, not immediate failover)
    rpc_timeout_s: float = 10.0
    # spawn → hello→ready deadline (covers the child's interpreter+jax
    # import; generous because a cold page cache is not a failure)
    spawn_timeout_s: float = 30.0
    # SIGTERM (graceful drain) → SIGKILL escalation window
    drain_deadline_s: float = 2.0

    # --- process backend: restart policy ---
    # base of the jittered exponential respawn backoff
    restart_backoff_s: float = 0.5
    # restart-storm budget: at most this many restarts of one shard
    # within restart_window_s; past it the shard stays dead (a crash
    # loop must not melt the host with respawn work)
    restart_max: int = 3
    restart_window_s: float = 60.0

    # --- RPC framing ---
    # max frame the transport accepts, either direction (oversized
    # frames are rejected loudly; resume bundles with embedded
    # checkpoints are the big payloads)
    max_frame_bytes: int = 64 << 20

    # --- fleet observability plane (DESIGN.md §18) ---
    # 1 = runners piggyback delta-encoded registry snapshots (plus span
    # rings and ferried forensics) on heartbeat/tick replies; 0 compiles
    # the runner-side harvest out entirely (the harvest-off leg of the
    # <5% p99 overhead acceptance)
    obs_harvest: int = 1
    # at most this many trace spans ship per tick reply (bounds the
    # frame size; the runner's ring keeps the rest for the next reply)
    obs_max_spans_per_reply: int = 512
    # runner-side pool.scrape() cadence in runner ticks (refreshes the
    # ggrs_io_* / per-slot gauges the snapshot then exports); 0 = off
    obs_scrape_every: int = 0

    # --- admission retry (mirrors supervisor.READMIT_*) ---
    readmit_backoff_ticks: int = 8
    readmit_max_attempts: int = 6

    # --- bank eviction storm clamp (mirrors host_bank.EVICT_MAX_PER_TICK) ---
    evict_max_per_tick: int = 4

    # --- multi-host TCP fleet link (DESIGN.md §25) ---
    # shared HMAC secret for the challenge-response handshake; empty
    # means "local trust" (fine for socketpair/uds and loopback tests,
    # wrong for anything that crosses a host boundary)
    link_auth_token: str = ""
    # a severed link may reconnect+resume for this long; past it the
    # shard is confirmed dead and §16 journal failover runs
    link_reconnect_window_s: float = 3.0
    # base of the runner's jittered exponential re-dial backoff
    link_backoff_s: float = 0.05
    # per-connection handshake deadline, both sides (slowloris bound)
    link_handshake_timeout_s: float = 2.0
    # TCP keepalive probe idle time; 0 disables SO_KEEPALIVE
    link_keepalive_s: float = 5.0
    # frames retained per direction for sequence-numbered resumption;
    # a reconnect whose gap exceeds the ring forces epoch bump+re-adopt
    link_retain_frames: int = 256
    # how long §16 failover keeps retrying a match whose wire port is
    # still bound (EADDRINUSE) — a fenced-but-alive incarnation is not
    # ours to kill, but it releases its sockets when the handshake
    # refusal lands, so the port frees within a handshake round trip
    failover_retry_s: float = 2.0

    # --- ingress & placement plane (DESIGN.md §26) ---
    # max dataplane idle before the ingress runner's serving loop runs a
    # forwarding pump cycle anyway (select() already wakes on traffic;
    # this bounds how stale the obs mirrors can get while idle)
    ingress_select_timeout_s: float = 0.05
    # placement refuses a host whose merged fleet-obs p99 tick latency
    # exceeds this budget, in milliseconds; 0 disables the p99 gate
    placement_p99_budget_ms: float = 0.0

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(f.default, str):
                if not isinstance(v, str):
                    raise ValueError(
                        f"FleetTuning.{f.name}: non-string {v!r}")
                continue
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValueError(f"FleetTuning.{f.name}: non-numeric {v!r}")
            if v < 0:
                raise ValueError(f"FleetTuning.{f.name}: negative {v!r}")

    # ------------------------------------------------------------------
    # env overrides + artifact round trip
    # ------------------------------------------------------------------

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None,
                 **overrides: Any) -> "FleetTuning":
        """Defaults, overridden by ``GGRS_FLEET_<FIELD>`` environment
        entries, overridden by explicit kwargs.  A malformed env value
        raises ``ValueError`` naming the variable — silently ignoring a
        typo'd production override would be worse than failing."""
        env = os.environ if env is None else env
        kw: Dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            key = ENV_PREFIX + f.name.upper()
            if key not in env:
                continue
            if isinstance(f.default, str):
                kw[f.name] = env[key]
                continue
            cast = int if isinstance(f.default, int) else float
            try:
                kw[f.name] = cast(env[key])
            except ValueError:
                raise ValueError(
                    f"{key}={env[key]!r}: not a valid {cast.__name__}"
                ) from None
        kw.update(overrides)
        return cls(**kw)

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe dict — embedded in every chaos artifact so a run
        records the knobs it ran with (``from_dict`` round-trips it)."""
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "FleetTuning":
        return cls(**dict(d))
