"""One fleet shard: a supervised ``HostSessionPool`` plus the per-shard
bookkeeping the :class:`~ggrs_tpu.fleet.supervisor.ShardSupervisor` drives
(DESIGN.md §16).

A shard owns two classes of matches:

- **bank matches** — admitted before the shard's first tick, stepped by the
  pool's native session bank (one ctypes crossing per tick, §8).  This is
  the steady-state serving shape: the supervisor fills a shard, it seals,
  it serves.
- **adopted matches** — arrived after the seal: live migrations in, crash
  failovers, and late admissions.  Each runs as a per-session Python
  ``P2PSession`` beside the bank (the same fallback tier eviction uses),
  ticked by the shard with the same per-match fault containment.

The shard also owns the durable side of the fleet story: per-match
``MatchJournal``s (attached through the hub so the confirmed stream rides
the tick crossing) and periodic **state checkpoints** embedded in them —
the only game state a dead process leaves behind, and therefore what crash
failover resumes from (``checkpoint_every`` must stay well under the
journal ``tail_window`` or failover cannot pair a checkpoint with the
confirmed inputs that follow it).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core.errors import (
    GgrsError,
    InvalidRequest,
    NotSynchronized,
    PredictionThreshold,
)
from ..core.types import GgrsRequest, SessionState
from ..obs.registry import Registry, default_registry
from ..obs.slo import ShardSloMeter
from ..obs.timeline import (
    EV_DESYNC,
    EV_EVICT,
    EV_QUARANTINE,
    EV_RETIRE,
    timeline_event,
)
from ..parallel.host_bank import (
    HostSessionPool,
    SLOT_DEAD,
    adopt_resume_bundle,
)
from ..utils.tracing import get_logger

_logger = get_logger("fleet")

# shard lifecycle states (the drain/failover state machine, DESIGN.md §16)
SHARD_ACTIVE = "active"        # admitting and serving
SHARD_DRAINING = "draining"    # serving, admission closed, migrating off
SHARD_RETIRED = "retired"      # drained empty; no longer ticked
SHARD_DEAD = "dead"            # failed health check; matches failed over

# The declared lifecycle transition table (DESIGN.md §16, §22): every
# assignment to a shard's ``state`` — here, in proc.py, and in
# supervisor.py — performs an edge from this table; the ggrs-model
# conformance lint proves it and the §16 lifecycle model
# (analysis/machines.py) is built from it.  RETIRED is absorbing; DEAD
# is not (a failed-over proc shard respawns empty and re-enters
# admission).
SHARD_TRANSITIONS = (
    (SHARD_ACTIVE, SHARD_DRAINING),    # drain begins (admission off)
    (SHARD_DRAINING, SHARD_ACTIVE),    # drain cancelled / re-admitted
    (SHARD_ACTIVE, SHARD_RETIRED),     # retired without a drain phase
    (SHARD_DRAINING, SHARD_RETIRED),   # drained empty
    (SHARD_ACTIVE, SHARD_DEAD),        # failed health check -> failover
    (SHARD_DRAINING, SHARD_DEAD),      # died mid-drain -> failover
    (SHARD_DEAD, SHARD_ACTIVE),        # proc respawn: fresh incarnation
)


class AdoptedMatch:
    """A match running beside the bank on its own Python session: a
    migration/failover arrival (``pending`` leads its next request list
    with the state-restoring prelude) or a post-seal late admission."""

    __slots__ = ("session", "pending", "journal_from", "replay_local")

    def __init__(self, session, pending: Optional[List[GgrsRequest]] = None,
                 journal_from: int = 0,
                 replay_local: Optional[Dict[int, Dict[int, Any]]] = None):
        self.session = session
        self.pending = list(pending or [])
        # the first frame the session's input queues can answer for — a
        # fresh session has history from 0, an adopted one only from the
        # start of its resume window (_journal_adopted must not reach back
        # past it)
        self.journal_from = journal_from
        # crash failover only: {frame: {handle: decoded input}} recovered
        # from the dead incarnation's LOCAL journal tail.  While the
        # resumed session walks back through these frames, the serving
        # loop's inputs are OVERRIDDEN with the recorded values — the dead
        # process already sent them, and re-sending different ones would
        # silently desync every peer that holds the originals.
        self.replay_local = dict(replay_local or {})


class PoolShard:
    """One pool shard behind the fleet placement front.

    Single-threaded like everything session-shaped: the supervisor (or any
    driver) calls ``add_local_input`` per match per tick and then
    ``advance_all()``, which returns ``{match_id: request_list}`` across
    bank and adopted matches alike.
    """

    # backend tag the supervisor branches on ("inproc" serves in the
    # supervisor's process; fleet.proc.ProcShard says "proc")
    backend = "inproc"

    def __init__(
        self,
        shard_id: str,
        *,
        capacity: int = 64,
        metrics: Optional[Registry] = None,
        tracer=None,
        native_io: bool = False,
        retire_dead_matches: bool = False,
        checkpoint_every: int = 32,
        p99_budget_ms: Optional[float] = None,
        stale_after_s: Optional[float] = None,
        tuning=None,
    ) -> None:
        import random
        import zlib

        from ..broadcast import SpectatorHub

        self.shard_id = shard_id
        self.capacity = capacity
        self.metrics = metrics if metrics is not None else default_registry()
        self.pool = HostSessionPool(
            metrics=self.metrics, tracer=tracer, native_io=native_io,
            retire_dead_matches=retire_dead_matches,
            evict_max_per_tick=(
                None if tuning is None else tuning.evict_max_per_tick
            ),
        )
        # seeded from the shard id: identical topologies then produce
        # identical viewer magics — the control/chaos comparison contract
        self.hub = SpectatorHub(
            self.pool, rng=random.Random(zlib.crc32(shard_id.encode()))
        )
        self.state = SHARD_ACTIVE
        self.killed = False  # chaos switch: simulated process death
        self.ticks = 0
        self.checkpoint_every = checkpoint_every
        self.p99_budget_ms = p99_budget_ms
        self.stale_after_s = stale_after_s
        self._started = False
        self._matches: Dict[str, int] = {}          # match_id -> bank slot
        self._adopted: Dict[str, AdoptedMatch] = {}
        self._dead_matches: Dict[str, str] = {}     # match_id -> reason
        self._journals: Dict[str, Any] = {}
        self._encoders: Dict[str, Any] = {}         # match_id -> input_encode
        self._pending_journals: List[Tuple[int, Any]] = []
        self._pending_viewers: List[Tuple[int, Any]] = []
        self._ckpt_next: Dict[str, int] = {}
        self._ckpt_disabled: set = set()
        self._tick_ms: deque = deque(maxlen=128)
        # matches whose journal degraded (write failure): the shard keeps
        # serving them, but failover must treat them as journal-less —
        # the durable tip stopped tracking what the match acks (§17)
        self._journal_failed: set = set()
        # the forensics ferry (DESIGN.md §18): flight-recorder dumps and
        # DesyncReports captured the moment a slot quarantines/evicts/
        # dies, held until drain_forensics() ships them — on a
        # process-backed shard that ship rides the next tick/heartbeat
        # reply, so the artifact outlives the child that produced it
        self._forensic_items: List[Dict[str, Any]] = []
        self._slot_last_state: Dict[str, str] = {}
        # the timeline ferry (DESIGN.md §28): match-lifecycle events
        # buffered exactly like forensics until drain_timeline() ships
        # them on the next tick/heartbeat reply — zero extra round trips
        self._timeline_items: List[Dict[str, Any]] = []
        # a short per-match event history kept AFTER draining, so a
        # DesyncReport captured late still embeds the match's lifecycle
        # context (§28's "every DesyncReport carries its timeline")
        self._timeline_history: Dict[str, List[Dict[str, Any]]] = {}
        # per-tier SLO budget-compliance counters (§28), fed from the
        # tick timer this loop already runs — they ride the registry
        # harvest, adding zero crossings and zero RPCs
        self.slo = ShardSloMeter(self.metrics)
        # pool-level lifecycle emissions (host_bank §28 seam): the pool
        # reports by slot, the shard translates to match ids
        self.pool.timeline_sink = self._pool_timeline_event
        m = self.metrics
        self._g_matches = m.gauge(
            "ggrs_shard_matches", "matches served per shard, by tier",
            labels=("shard", "tier"))
        self._g_p99 = m.gauge(
            "ggrs_shard_tick_p99_ms",
            "shard tick p99 over the last 128 ticks (admission signal)",
            labels=("shard",))
        self._m_journal_failures = m.counter(
            "ggrs_shard_journal_failures_total",
            "matches whose journal degraded on a write failure",
            labels=("shard",))

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    @property
    def sealed(self) -> bool:
        """The pool finalized: new matches can only be adopted (per-session
        tier), not added to the bank."""
        return self.pool._finalized

    def live_matches(self) -> int:
        return len(self._matches) + len(self._adopted)

    def match_ids(self) -> List[str]:
        return list(self._matches) + list(self._adopted)

    def has_match(self, match_id: str) -> bool:
        return match_id in self._matches or match_id in self._adopted

    def is_bank_match(self, match_id: str) -> bool:
        """Bank-tier (native-harvest-exportable) vs adopted-tier — the
        supervisor's migrate() branches on this instead of reaching into
        ``_matches`` so process-backed shards can answer from cache."""
        return match_id in self._matches

    def journal_failed_matches(self) -> List[str]:
        """Matches whose journal degraded on a write failure — the
        supervisor marks them journal-less for failover purposes."""
        return sorted(self._journal_failed)

    def match_port(self, match_id: str) -> Optional[int]:
        """The UDP port the match's host socket bound, when determinable
        (None for in-memory networks) — how a driver that admitted
        through a port-0 socket factory learns where to aim the peer."""
        sock = None
        slot = self._matches.get(match_id)
        if slot is not None and slot < len(self.pool._builders):
            sock = self.pool._builders[slot][1]
        else:
            am = self._adopted.get(match_id)
            if am is not None:
                sock = getattr(am.session, "_socket", None)
        port = getattr(sock, "local_port", None)
        return port() if callable(port) else None

    def admission_refusal(self) -> Optional[str]:
        """Why this shard refuses a new match right now, or None — the
        capacity-aware admission check, driven by the shard's own
        observables: lifecycle state, slot occupancy vs ``capacity``, the
        tick-p99 gauge vs ``p99_budget_ms``, and ``/healthz``-style
        last-tick staleness vs ``stale_after_s``."""
        if self.killed or self.state == SHARD_DEAD:
            return "dead"
        if self.state == SHARD_DRAINING:
            return "draining"
        if self.state == SHARD_RETIRED:
            return "retired"
        if self.live_matches() >= self.capacity:
            return "full"
        if self.p99_budget_ms is not None and self._tick_ms:
            if self.tick_p99_ms() > self.p99_budget_ms:
                return "overloaded"
        if self.stale_after_s is not None:
            last = self.pool.last_tick_at
            if last is not None and (
                time.monotonic() - last > self.stale_after_s
            ):
                return "stale"
        return None

    def admit(self, match_id: str, builder, socket, *,
              journal=None) -> str:
        """Admit one match.  Before the first tick it lands in the bank
        (the pool is still open); afterwards it starts as an adopted
        per-session match — the late-admission tier.  Returns ``"bank"``
        or ``"standalone"``.  ``journal``: a ``MatchJournal`` tapped on the
        confirmed stream (bank tier: from the tick crossing via the hub;
        adopted tier: through a ``JournalTap``)."""
        if self.has_match(match_id):
            raise InvalidRequest(f"match {match_id!r} already on this shard")
        refusal = self.admission_refusal()
        if refusal is not None:
            raise InvalidRequest(
                f"shard {self.shard_id} refuses admission: {refusal}"
            )
        if not self.sealed:
            slot = self.pool.add_session(builder, socket)
            self._matches[match_id] = slot
            if journal is not None:
                self._journals[match_id] = journal
                self._encoders[match_id] = builder._config.input_encode
                self._pending_journals.append((slot, journal))
            self._update_match_gauges()
            return "bank"
        session = builder.start_p2p_session(socket)
        if journal is not None:
            # adopted matches journal SYNCHRONOUSLY from the sync layer
            # after each tick (_journal_adopted), not through a
            # JournalTap: the tap rides the spectator relay, which trails
            # the confirmed watermark — and any frame acked beyond the
            # durable tip is unrecoverable after a crash (§16, the
            # durable-ack window)
            self._journals[match_id] = journal
            self._encoders[match_id] = builder._config.input_encode
        self._adopted[match_id] = AdoptedMatch(session)
        self._update_match_gauges()
        return "standalone"

    def attach_viewer(self, match_id: str, addr) -> None:
        """Register a spectator on a bank match (deferred to the shard's
        start when the pool has not finalized yet; adopted matches graft a
        live endpoint immediately through the hub's fallback path)."""
        slot = self._matches.get(match_id)
        if slot is None:
            raise InvalidRequest(
                f"match {match_id!r} is not a bank match on this shard"
            )
        if not self._started:
            self._pending_viewers.append((slot, addr))
            return
        self.hub.attach(slot, addr)

    # ------------------------------------------------------------------
    # ticking
    # ------------------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._started:
            return
        self._started = True
        self.pool.native_active  # lazy finalize (seals bank admission)
        for slot, journal in self._pending_journals:
            self.hub.attach_journal(slot, journal)
        self._pending_journals = []
        for slot, addr in self._pending_viewers:
            self.hub.attach(slot, addr)
        self._pending_viewers = []

    def add_local_input(self, match_id: str, handle: int, value) -> None:
        slot = self._matches.get(match_id)
        if slot is not None:
            self._journal_local(match_id, self.pool.current_frame(slot),
                               handle, value)
            self.pool.add_local_input(slot, handle, value)
            return
        am = self._adopted.get(match_id)
        if am is not None:
            frame = am.session.current_frame
            rep = am.replay_local
            if rep:
                # crash-failover replay window: substitute the recorded
                # value while re-walking frames the dead incarnation sent
                recorded = rep.get(frame, {})
                if handle in recorded:
                    value = recorded[handle]
                for f in [f for f in rep if f < frame]:
                    del rep[f]
            self._journal_local(match_id, frame, handle, value)
            am.session.add_local_input(handle, value)
        # dead/unknown matches swallow inputs, like dead pool slots

    def _journal_local(self, match_id: str, frame: int, handle: int,
                       value) -> None:
        """Journal a staged local input at staging time (ahead of the
        confirmed stream) — fsynced by the pre-send barrier in
        ``advance_all`` so everything the tick SENDS is durable first."""
        journal = self._journals.get(match_id)
        encode = self._encoders.get(match_id)
        if journal is None or encode is None:
            return
        try:
            journal.append_local_input(frame, handle, encode(value))
        except Exception:
            pass  # journaling must never take the serving path down

    def advance_all(self) -> Dict[str, List[GgrsRequest]]:
        """One shard tick: the pool's single crossing plus every adopted
        session's tick, with per-match containment.  Returns the per-match
        request lists; a killed/retired/dead shard returns {} (nothing
        here ticks — the supervisor fails its matches over)."""
        if self.killed or self.state in (SHARD_RETIRED, SHARD_DEAD):
            return {}
        self._ensure_started()
        t0 = time.perf_counter()
        # the durable-before-send barrier: every LOCAL input staged since
        # the last tick fsyncs BEFORE the crossing sends it — a crash can
        # then never leave the peers holding frames the journal lacks
        for journal in self._journals.values():
            journal.flush_local()
        # journal write-failure sweep: a degraded journal (ENOSPC/EIO —
        # the MatchJournal stops writing and flags itself) must degrade
        # the SHARD loudly, not silently drop records: fault counter +
        # health flag, and the supervisor marks the match journal-less
        # for failover purposes
        for match_id, journal in self._journals.items():
            if journal.failed is not None and (
                match_id not in self._journal_failed
            ):
                self._journal_failed.add(match_id)
                self._m_journal_failures.labels(shard=self.shard_id).inc()
                _logger.error(
                    "shard %s match %s: journal degraded (%s); match is "
                    "journal-less for failover until re-incarnated",
                    self.shard_id, match_id, journal.failed,
                )
        # Checkpoint BEFORE this tick steps, from last tick's fully
        # fulfilled state.  Checkpointing after the step would read save
        # cells whose corrective rollback re-saves are still unfulfilled
        # in the just-returned request lists: a rollback that fixes frame
        # F ≤ the new watermark leaves cell F stale (with cell.frame == F,
        # so the two-candidate rule cannot tell) until the caller fulfills
        # it — a checkpoint taken in that window captures mispredicted
        # state, and a journal-path migration/failover that resumes from
        # it desyncs permanently (the chaos shard_migrate desync).
        self._maybe_checkpoint()
        out: Dict[str, List[GgrsRequest]] = {}
        lists = self.pool.advance_all()
        for match_id, slot in self._matches.items():
            out[match_id] = lists[slot]
        self._sweep_slot_forensics()
        for match_id in list(self._adopted):
            out[match_id] = self._tick_adopted(match_id)
            am = self._adopted.get(match_id)
            if am is not None:
                self._journal_adopted(match_id, am)
        self.ticks += 1
        tick_ms = (time.perf_counter() - t0) * 1000.0
        self._tick_ms.append(tick_ms)
        self._g_p99.labels(shard=self.shard_id).set(self.tick_p99_ms())
        # SLO compliance (§28): the rollback tier against the frame
        # budget from the tick timer above; the lockstep tier against
        # its confirmed-lag budget, read straight off the Python-tier
        # sessions the lockstep slots already run on (no crossing)
        self.slo.observe_rollback(tick_ms)
        lockstep = self.pool.lockstep_slots()
        if lockstep:
            worst = 0
            for slot in lockstep:
                try:
                    lag = (self.pool.current_frame(slot)
                           - self.pool.last_confirmed_frame(slot))
                except Exception:
                    continue
                if lag > worst:
                    worst = lag
            self.slo.observe_lockstep(worst)
        return out

    def _sweep_slot_forensics(self) -> None:
        """Capture the post-mortem the instant a bank slot leaves native
        (quarantined / evicted / dead): flight-recorder dump, fault log
        tail, and any DesyncReport — into the ferry buffer
        ``drain_forensics`` ships (DESIGN.md §18).

        Incremental (DESIGN.md §19): driven by the pool's supervision
        transition feed instead of polling every match's slot state every
        tick — on the quiet steady state this is one empty-list drain.
        Pools without the feed (user-supplied stand-ins) keep the legacy
        full walk."""
        drain = getattr(self.pool, "drain_state_transitions", None)
        if drain is not None:
            transitions = drain()
            if not transitions:
                return  # the quiet steady state: one empty-list drain
            slot_to_match = {s: m for m, s in self._matches.items()}
            for slot, _old, state, _tick in transitions:
                match_id = slot_to_match.get(slot)
                if match_id is None:
                    continue
                self._slot_last_state[match_id] = state
                if state not in ("quarantined", "evicted", "dead"):
                    continue
                self._capture_slot_forensic(match_id, slot, state)
            return
        for match_id, slot in self._matches.items():
            try:
                state = self.pool.slot_state(slot)
            except Exception:
                continue
            prev = self._slot_last_state.get(match_id)
            self._slot_last_state[match_id] = state
            if state == prev or state not in (
                "quarantined", "evicted", "dead"
            ):
                continue
            self._capture_slot_forensic(match_id, slot, state)

    def _capture_slot_forensic(self, match_id: str, slot: int,
                               state: str) -> None:
        """Build one slot post-mortem item into the ferry buffer."""
        item: Dict[str, Any] = dict(
            kind="slot", match=match_id, slot=slot, state=state,
            tick=self.ticks,
        )
        try:
            item["dump"] = self.pool.flight_dump(slot, 32)
        except Exception:
            pass
        try:
            item["faults"] = [
                dict(tick=f.tick, code=f.code, detail=f.detail)
                for f in self.pool.fault_log(slot)[-8:]
            ]
        except Exception:
            pass
        try:
            report = self.pool.desync_report(slot)
            if report is not None:
                item["desync_report"] = report.to_dict()
        except Exception:
            pass
        if state == "quarantined":
            self._record_timeline(EV_QUARANTINE, match_id,
                                  {"slot": slot})
        if "desync_report" in item:
            self._record_timeline(EV_DESYNC, match_id, {"slot": slot})
            # every DesyncReport carries its match's lifecycle context
            # (§28) — the events that led here, late-captured included
            item["desync_report"]["timeline"] = list(
                self._timeline_history.get(match_id, ())
            )
        self._record_forensic(item)

    def _record_forensic(self, item: Dict[str, Any]) -> None:
        self._forensic_items.append(item)
        del self._forensic_items[:-32]  # bounded while undrained

    def drain_forensics(self) -> List[Dict[str, Any]]:
        """Ship-and-clear the ferry buffer (plain JSON-safe dicts)."""
        out = self._forensic_items
        self._forensic_items = []
        return out

    # ------------------------------------------------------------------
    # the timeline ferry (DESIGN.md §28)
    # ------------------------------------------------------------------

    def _record_timeline(self, etype: str, match_id: str,
                         detail: Optional[Dict[str, Any]] = None) -> None:
        ev = timeline_event(
            etype, match_id, origin=self.shard_id, tick=self.ticks,
            detail=detail,
        )
        self._timeline_items.append(ev)
        del self._timeline_items[:-64]  # bounded while undrained
        hist = self._timeline_history.setdefault(match_id, [])
        hist.append(ev)
        del hist[:-16]

    def _pool_timeline_event(self, etype: str, slot: int,
                             detail: Optional[Dict[str, Any]]) -> None:
        """The pool's §28 emission seam: translate its slot-keyed event
        to the match id this shard placed there."""
        for match_id, s in self._matches.items():
            if s == slot:
                self._record_timeline(etype, match_id, detail)
                return

    def drain_timeline(self) -> List[Dict[str, Any]]:
        """Ship-and-clear the timeline buffer — rides the same tick
        reply / heartbeat payloads as :meth:`drain_forensics`."""
        out = self._timeline_items
        self._timeline_items = []
        return out

    def scrape(self):
        """One stats scrape of the underlying pool (refreshes the
        ``ggrs_io_*`` / per-slot gauges the obs snapshot then exports);
        the runner drives this on ``FleetTuning.obs_scrape_every``."""
        return self.pool.scrape()

    def _tick_adopted(self, match_id: str) -> List[GgrsRequest]:
        am = self._adopted[match_id]
        session = am.session
        try:
            if session.current_state() is SessionState.SYNCHRONIZING:
                session.poll_remote_clients()
                if session.current_state() is SessionState.SYNCHRONIZING:
                    return []
            reqs = session.advance_frame()
        except (NotSynchronized, PredictionThreshold):
            # backpressure, not a fault: skip this match's tick (the game
            # loop's standard reaction), keep its staged inputs
            return []
        except GgrsError:
            raise
        except Exception as e:  # containment: one bad match, not the shard
            reason = f"adopted tick: {type(e).__name__}: {e}"
            self._dead_matches[match_id] = reason
            del self._adopted[match_id]
            self._update_match_gauges()
            self._record_forensic(dict(
                kind="adopted", match=match_id, reason=reason,
                tick=self.ticks,
            ))
            self._record_timeline(EV_QUARANTINE, match_id,
                                  {"reason": reason})
            _logger.error("shard %s match %s marked dead: %s",
                          self.shard_id, match_id, reason)
            return []
        if am.pending:
            # migration/failover prelude: restore (and, for failover,
            # rebuild) the resume state BEFORE this tick's own requests
            reqs = am.pending + reqs
            am.pending = []
        return reqs

    def _journal_adopted(self, match_id: str, am: AdoptedMatch) -> None:
        """Journal an adopted match's newly-confirmed frames straight from
        its sync layer — synchronous with the confirmed watermark, so the
        durable tip never trails what the session has acked to its peers
        (with ``fsync_every=1`` that makes crash failover lossless; a
        relay-based ``JournalTap`` would lag by the fan-out deferral)."""
        journal = self._journals.get(match_id)
        if journal is None:
            return
        session = am.session
        confirmed = session._sync_layer.last_confirmed_frame
        start = max(journal.next_frame, am.journal_from)
        if confirmed < start:
            return
        # a long stall can outrun the input queues; the forward jump below
        # is recorded by the journal as an explicit GAP, never papered over
        start = max(start, confirmed - 120)
        config = session._config
        isize = journal.input_size
        players = journal.num_players
        records = []
        for frame in range(start, confirmed + 1):
            flags = bytearray(players)
            parts = []
            for p in range(players):
                try:
                    pi = session._sync_layer.confirmed_input(p, frame)
                except AssertionError:
                    pi = None  # queue holds nothing for p at this frame
                if pi is None or pi.frame != frame:
                    flags[p] = 1  # disconnected below this frame
                    parts.append(bytes(isize))
                else:
                    parts.append(config.input_encode(pi.input))
            records.append((bytes(flags), b"".join(parts)))
        journal.append_frames(start, records)

    def tick_p99_ms(self) -> float:
        if not self._tick_ms:
            return 0.0
        ordered = sorted(self._tick_ms)
        return ordered[min(len(ordered) - 1, (len(ordered) * 99) // 100)]

    def events(self, match_id: str) -> List:
        slot = self._matches.get(match_id)
        if slot is not None:
            return self.pool.events(slot)
        am = self._adopted.get(match_id)
        return am.session.events() if am is not None else []

    def current_frame(self, match_id: str) -> int:
        slot = self._matches.get(match_id)
        if slot is not None:
            return self.pool.current_frame(slot)
        am = self._adopted.get(match_id)
        if am is None:
            raise InvalidRequest(f"no match {match_id!r} on this shard")
        return am.session.current_frame

    # ------------------------------------------------------------------
    # migration seam
    # ------------------------------------------------------------------

    def evict_match(self, match_id: str) -> Dict[str, Any]:
        """Export + release one bank match (the source half of live
        migration): one harvest crossing builds the portable resume
        bundle, then the slot is released (native I/O detached, journal
        tap dropped, state MIGRATED) and the match forgotten here.  The
        shard's journal for the match is closed — the destination journals
        its own incarnation."""
        slot = self._matches.get(match_id)
        if slot is None:
            raise InvalidRequest(
                f"match {match_id!r} has no bank slot on shard "
                f"{self.shard_id} (adopted matches migrate via their "
                "journal)"
            )
        self._ensure_started()
        bundle = self.pool.export_resume_state(slot)
        self.pool.release_slot(
            slot, detail=f"migrated off shard {self.shard_id}"
        )
        self._record_timeline(EV_EVICT, match_id, {"slot": slot})
        del self._matches[match_id]
        self._slot_last_state.pop(match_id, None)
        self._close_journal(match_id)
        self._update_match_gauges()
        return bundle

    def demote_match(self, match_id: str) -> int:
        """Load-shedding (DESIGN.md §27, ROADMAP item 5): demote one
        bank-tier match to the lockstep tier.  The match keeps its
        slot, its wire address, and its journal tap, but runs from
        here on as a ``max_prediction == 0`` per-session fallback —
        zero save/load work, no rollback re-simulation, confirmed
        frames only.  The cheap tier a shard answering "overloaded"
        from :meth:`admission_refusal` sheds into before refusing
        players outright.  Returns the resume frame.  One-way:
        re-promotion to the bank is a migration concern."""
        slot = self._matches.get(match_id)
        if slot is None:
            raise InvalidRequest(
                f"match {match_id!r} has no bank slot on shard "
                f"{self.shard_id} (adopted matches already run "
                "per-session; rebuild them lockstep instead)"
            )
        self._ensure_started()
        return self.pool.demote_to_lockstep(slot)

    def lockstep_matches(self) -> List[str]:
        """Bank matches demoted to the lockstep tier, by match id."""
        return sorted(
            mid for mid, slot in self._matches.items()
            if self.pool.in_lockstep(slot)
        )

    def drop_match(self, match_id: str, reason: str) -> None:
        """Forget a match without exporting (journal-path migration of an
        adopted match, or failover bookkeeping on a dead shard)."""
        slot = self._matches.pop(match_id, None)
        if slot is not None and not self.killed:
            try:
                self.pool.release_slot(slot, detail=reason)
            except Exception:
                pass
        self._adopted.pop(match_id, None)
        self._slot_last_state.pop(match_id, None)
        self._close_journal(match_id)
        self._update_match_gauges()

    def adopt_match(self, match_id: str, builder, socket,
                    bundle: Dict[str, Any], *,
                    saved_states=None,
                    prelude: Optional[List[GgrsRequest]] = None,
                    journal=None,
                    replay_local: Optional[Dict[int, Dict[int, Any]]] = None,
                    ) -> None:
        """Resume a migrated/failed-over match on this shard (destination
        half): builds the Python session through
        ``parallel.host_bank.adopt_resume_bundle`` and queues the
        state-restoring prelude as the head of the match's next request
        list.  ``prelude`` defaults to the bundle's single
        ``LoadGameState``; crash failover passes the longer
        load-checkpoint → advance-to-tip → save sequence."""
        if self.has_match(match_id):
            raise InvalidRequest(f"match {match_id!r} already on this shard")
        # journal=None to the adoption seam: the shard journals adopted
        # matches synchronously post-tick (see admit), not via JournalTap
        session, load = adopt_resume_bundle(
            builder, socket, bundle, saved_states=saved_states,
        )
        if journal is not None:
            self._journals[match_id] = journal
            self._encoders[match_id] = builder._config.input_encode
        # the new incarnation's journal can only reach back to the start
        # of the adopted input window; journaling that full window (not
        # just resume_frame+1) keeps the first post-adoption checkpoint
        # immediately pairable with in-window confirmed inputs
        starts = [
            start for start, blobs in bundle["harvest"]["player_inputs"]
            if blobs
        ]
        self._adopted[match_id] = AdoptedMatch(
            session, prelude if prelude is not None else [load],
            journal_from=(
                min(starts) if starts else bundle["resume_frame"] + 1
            ),
            replay_local=replay_local,
        )
        self._update_match_gauges()

    def wire_identity(self, match_id: str) -> Dict[str, Any]:
        """The match's peer-visible identity — endpoint/spectator magics,
        handles, liveness — refreshed into the supervisor's registry while
        the shard is healthy, so crash failover can rebuild endpoints the
        dead process can no longer describe."""
        slot = self._matches.get(match_id)
        if slot is not None and self.pool._native_active:
            m = self.pool._mirrors[slot]
            return dict(
                local_handles=list(m.local_handles),
                endpoints=[
                    dict(addr=ep.addr, handles=list(ep.handles),
                         magic=ep.magic, running=ep.running)
                    for ep in m.endpoints
                ],
                spectators=[
                    dict(addr=sp.addr, magic=sp.magic,
                         handles=list(sp.handles), running=sp.running)
                    for sp in m.spectators
                ],
            )
        session = None
        if slot is not None:
            session = self.pool.session(slot)
        else:
            am = self._adopted.get(match_id)
            if am is not None:
                session = am.session
        if session is None:
            raise InvalidRequest(f"no match {match_id!r} on this shard")
        return dict(
            local_handles=sorted(session._local_handles),
            endpoints=[
                dict(addr=addr, handles=list(ep.handles), magic=ep.magic,
                     running=ep.is_running())
                for addr, ep in session._player_reg.remotes.items()
            ],
            spectators=[
                dict(addr=addr, magic=ep.magic,
                     handles=list(getattr(ep, "handles", ())),
                     running=ep.is_running())
                for addr, ep in session._player_reg.spectators.items()
                if hasattr(ep, "_core")  # journal taps have no wire state
            ],
        )

    # ------------------------------------------------------------------
    # checkpoints (the durable half of crash failover)
    # ------------------------------------------------------------------

    def _saved_and_confirmed(self, match_id: str):
        slot = self._matches.get(match_id)
        if slot is not None:
            if self.pool._native_active:
                if self.pool.slot_state(slot) != "native":
                    return None, None
                return (self.pool._mirrors[slot].saved_states,
                        self.pool.last_confirmed_frame(slot))
            session = self.pool.session(slot)
        else:
            am = self._adopted.get(match_id)
            if am is None:
                return None, None
            session = am.session
        return (session._sync_layer.saved_states,
                session._sync_layer.last_confirmed_frame)

    def checkpoint_now(self, match_id: str) -> None:
        """Append a state checkpoint for one match NOW, cadence aside —
        the cross-host export seam (DESIGN.md §26) calls this before a
        journal-path transfer so the resume window always holds a fresh
        checkpoint (and the fast-forward prelude stays one save long)
        even when the match is younger than ``checkpoint_every``.  Same
        safety condition as the cadence path: runs between ticks, from
        last tick's fully fulfilled save cells."""
        self._maybe_checkpoint(only=match_id, force=True)

    def _maybe_checkpoint(self, only: Optional[str] = None,
                          force: bool = False) -> None:
        every = self.checkpoint_every
        if not every and not force:
            return
        for match_id, journal in self._journals.items():
            if only is not None and match_id != only:
                continue
            if match_id in self._ckpt_disabled:
                continue
            saved, confirmed = self._saved_and_confirmed(match_id)
            if saved is None or confirmed is None or confirmed < 0:
                continue
            if not force and confirmed < self._ckpt_next.get(
                    match_id, every):
                continue
            # the newest committed frame whose save the game fulfilled
            # (the same two-candidate rule the resume selection uses)
            frame = None
            for r in (confirmed, confirmed - 1):
                if r >= 0 and saved.get_cell(r).frame == r:
                    frame = r
                    break
            if frame is None:
                continue
            cell = saved.get_cell(frame)
            try:
                journal.append_checkpoint(frame, cell.data())
            except Exception as e:
                # a non-pytree game state cannot checkpoint: failover for
                # this match degrades to "unrecoverable", loudly, once
                self._ckpt_disabled.add(match_id)
                _logger.warning(
                    "shard %s match %s: state checkpoint failed (%s); "
                    "journal failover disabled for this match",
                    self.shard_id, match_id, e,
                )
                continue
            self._ckpt_next[match_id] = frame + every

    def _close_journal(self, match_id: str) -> None:
        journal = self._journals.pop(match_id, None)
        self._encoders.pop(match_id, None)
        self._ckpt_next.pop(match_id, None)
        self._ckpt_disabled.discard(match_id)
        self._journal_failed.discard(match_id)
        if journal is not None:
            try:
                journal.close()
            except Exception:
                pass

    # ------------------------------------------------------------------
    # lifecycle + health
    # ------------------------------------------------------------------

    def kill(self) -> None:
        """Chaos switch: simulate the shard process dying mid-tick.  The
        shard stops ticking instantly; nothing is flushed or released —
        recovery must come from the durable journals alone."""
        self.killed = True

    def inject_match_error(self, match_id: str,
                           code: Optional[int] = None) -> None:
        """Chaos/test seam: inject a native slot fault into one BANK
        match (the ctrl-op channel the §9 chaos harness drives) —
        reachable over the runner RPC so the forensics ferry can be
        exercised end-to-end on a process-backed shard."""
        slot = self._matches.get(match_id)
        if slot is None:
            raise InvalidRequest(
                f"match {match_id!r} is not a bank match on this shard"
            )
        self._ensure_started()
        self.pool.inject_slot_error(slot, code)

    def retire(self) -> None:
        # ggrs-model: transitions(active->retired, draining->retired)
        self.state = SHARD_RETIRED
        for match_id in self.match_ids():
            self._record_timeline(EV_RETIRE, match_id)
        for match_id in list(self._journals):
            self._close_journal(match_id)

    def flush_journals(self, close: bool = False) -> None:
        """Fsync (or close: CLOSE record + fsync) every journal — the
        shard runner's graceful-drain step, so a SIGTERM'd process leaves
        journals durable to the last served frame."""
        for match_id in list(self._journals):
            if close:
                self._close_journal(match_id)
            else:
                try:
                    self._journals[match_id].flush(fsync=True)
                except Exception:
                    pass  # a degraded journal already no-ops/flags

    def close(self) -> None:
        """Release durable resources (journal fds).  Lifecycle state is
        untouched — this is the supervisor's shutdown hook, not a drain."""
        for match_id in list(self._journals):
            self._close_journal(match_id)

    def healthz(self) -> Dict[str, Any]:
        """Per-shard health record (aggregated fleet-wide by
        ``ShardSupervisor.healthz``)."""
        last = self.pool.last_tick_at
        age = None if last is None else max(0.0, time.monotonic() - last)
        ok = (
            not self.killed
            and self.state in (SHARD_ACTIVE, SHARD_DRAINING)
        )
        if ok and self.stale_after_s is not None and age is not None:
            ok = age <= self.stale_after_s
        return dict(
            shard=self.shard_id,
            state=SHARD_DEAD if self.killed else self.state,
            ok=ok,
            matches=self.live_matches(),
            bank_matches=len(self._matches),
            adopted_matches=len(self._adopted),
            dead_matches=len(self._dead_matches),
            journal_failed=len(self._journal_failed),
            capacity=self.capacity,
            ticks=self.ticks,
            last_tick_age_s=age,
            tick_p99_ms=self.tick_p99_ms(),
        )

    def dead_slot_count(self) -> int:
        if not self.pool._finalized:
            return 0
        return sum(
            1 for i in range(len(self.pool))
            if self.pool.slot_state(i) == SLOT_DEAD
        )

    def _update_match_gauges(self) -> None:
        self._g_matches.labels(shard=self.shard_id, tier="bank").set(
            len(self._matches)
        )
        self._g_matches.labels(shard=self.shard_id, tier="adopted").set(
            len(self._adopted)
        )
