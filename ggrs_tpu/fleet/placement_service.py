"""Placement plane: multi-supervisor scheduling + cross-host live
migration behind stable virtual endpoints (DESIGN.md §26).

One level above :class:`~ggrs_tpu.fleet.supervisor.ShardSupervisor`: the
:class:`PlacementService` fronts MANY supervisors ("hosts"), lifting the
same two ideas the supervisor applies to its shards — a
:class:`~ggrs_tpu.fleet.placement.HashRing` preference walk and
capacity/p99-aware refusal — one level up, fed by each host's merged
fleet obs (per-shard ``admission_refusal`` and the harvested
``tick_p99_ms``).  Every match it admits gets a *virtual endpoint* from
the §26 ingress, so its public address survives anything the placement
plane does to it:

- **live migration** (:meth:`migrate`): ``export_transfer`` on the
  source (the §16 pickle-portable resume bundle, round-tripped through
  real ``pickle.dumps`` bytes — the cross-host contract), ``adopt_transfer``
  on the target, THEN the ingress route flip.  Flip-after-adoption is
  not a style choice: the route-flip machine in ``analysis/machines.py``
  (``route-flip:flip-before-ack``) pins the misroute counterexample, and
  every ``_Migration.phase`` edge below conforms to ``MIG_TRANSITIONS``
  under the §22 transition lint.
- **host failover**: each tick replicates every placed match's
  ``record_meta`` (the picklable description journal failover needs);
  when a host is confirmed dead (:meth:`kill_host`), survivors
  ``adopt_from_meta`` — rebuilding live sessions from the shared-storage
  journals — and the ingress flips routes to the new legs.  Peers keep
  talking to the SAME public address throughout; the §25-style fence
  (``route_epoch`` minted here, refused-if-stale at the ingress) keeps a
  supervisor that slept through the failover from ever flipping a route
  back.
"""

from __future__ import annotations

import functools
import pickle
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..core.errors import InvalidRequest
from ..obs.registry import Registry
from ..obs.timeline import (
    EV_FAILOVER,
    EV_MIGRATE_ABORT,
    EV_MIGRATE_BEGIN,
    EV_MIGRATE_COMMIT,
    EV_PLACE,
    EV_ROUTE_FLIP,
    TimelineStore,
    pack_trace_ctx,
)
from ..utils.tracing import get_logger
from .ingress import (
    ROUTE_OP_DEL,
    ROUTE_OP_PUT,
    encode_route_update,
    virtual_endpoint_socket,
)
from .placement import HashRing
from .supervisor import FleetError
from .tuning import FleetTuning

_logger = get_logger("fleet")

# ----------------------------------------------------------------------
# the migration phase machine (DESIGN.md §26, modeled in
# analysis/machines.py as route_flip_model — every ``phase`` assignment
# below is an edge of this table, proven by the §22 conformance lint)
# ----------------------------------------------------------------------

MIG_IDLE = "idle"          # no transfer in flight
MIG_EXPORTED = "exported"  # bundle off the source; nobody serves
MIG_ADOPTED = "adopted"    # target ACKED adoption; route still old
MIG_FLIPPED = "flipped"    # ingress accepted the new route

MIG_TRANSITIONS = (
    (MIG_IDLE, MIG_EXPORTED),      # export_transfer / journal pickup
    (MIG_EXPORTED, MIG_ADOPTED),   # target adoption acked
    (MIG_ADOPTED, MIG_FLIPPED),    # ingress route flip (never earlier)
    (MIG_FLIPPED, MIG_IDLE),       # settled
    (MIG_EXPORTED, MIG_IDLE),      # abort: restored on the source
)


class _Migration:
    """One in-flight transfer's phase, conformed to MIG_TRANSITIONS."""

    def __init__(self, match_id: str, src: Optional[str],
                 dst: str) -> None:
        self.match_id = match_id
        self.src = src
        self.dst = dst
        self.phase = MIG_IDLE


@dataclass
class PlacedMatch:
    """Placement-plane record: where a match serves and how the world
    reaches it.  ``meta`` is the per-tick-replicated supervisor
    ``record_meta`` — everything a survivor needs for journal failover
    when the serving host dies without a goodbye."""

    match_id: str
    host: str
    vport: int
    peers: Tuple[Tuple[str, int], ...] = ()
    meta: Optional[Dict[str, Any]] = None
    routed: bool = False
    lost: Optional[str] = None


_MIGRATION_BUCKETS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 1.0, 5.0)


class PlacementService:
    """Admission, scheduling, migration, and failover across many
    supervisors, with the ingress owning every public address."""

    def __init__(self, hosts: Dict[str, Any], *, ingress: Any,
                 host_addrs: Optional[Dict[str, str]] = None,
                 tuning: Optional[FleetTuning] = None,
                 metrics: Optional[Registry] = None) -> None:
        if not hosts:
            raise InvalidRequest("placement needs at least one host")
        self.hosts = dict(hosts)
        self.ingress = ingress
        self.host_addrs = dict(host_addrs or {})
        self.tuning = tuning if tuning is not None else FleetTuning.from_env()
        self.metrics = metrics if metrics is not None else Registry()
        self.ring = HashRing(self.hosts.keys())
        self._dead: Set[str] = set()
        self._records: Dict[str, PlacedMatch] = {}
        # the placement-minted route fence: bumped on every confirmed
        # host death, so any route a stale epoch signed is refused at
        # the ingress forever after (§25's mint, applied to routes)
        self.route_epoch = 1
        self._route_version = 0
        self._tick = 0
        # match-lifecycle timelines (§28): the placement plane is the
        # cross-host narrator — it sees every PLACE / MIGRATE_* /
        # ROUTE_FLIP / FAILOVER edge, and mints the span ids the trace
        # context carries onto the wire
        self.timelines = TimelineStore()
        self._span_seq = 0
        m = self.metrics
        self._m_admissions = m.counter(
            "ggrs_placement_admissions_total",
            "matches placed, by host", labels=("host",))
        self._m_refusals = m.counter(
            "ggrs_placement_refusals_total",
            "per-host placement refusals, by reason", labels=("reason",))
        self._m_migrations = m.counter(
            "ggrs_placement_migrations_total",
            "cross-host transfers completed, by reason",
            labels=("reason",))
        self._h_migration = m.histogram(
            "ggrs_placement_migration_seconds",
            "export -> adopt -> route-flip latency per live migration",
            buckets=_MIGRATION_BUCKETS)
        self._m_route_updates = m.counter(
            "ggrs_placement_route_updates_total",
            "route updates pushed to the ingress, by verdict",
            labels=("verdict",))
        self._m_host_failovers = m.counter(
            "ggrs_placement_host_failovers_total",
            "matches journal-failed-over off a dead host")
        self._m_lost = m.counter(
            "ggrs_placement_matches_lost_total",
            "matches the placement plane could not recover")
        self._g_hosts = m.gauge(
            "ggrs_placement_hosts", "hosts per state", labels=("state",))
        self._update_host_gauge()

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------

    def _host_addr(self, hid: str) -> str:
        return self.host_addrs.get(hid, "127.0.0.1")

    def _next_span(self) -> int:
        self._span_seq += 1
        return self._span_seq

    def _record_timeline(self, etype: str, match_id: str,
                         span: Optional[int] = None,
                         detail: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        return self.timelines.record(
            etype, match_id, origin="placement", tick=self._tick,
            epoch=self.route_epoch,
            span=span if span is not None else self._next_span(),
            detail=detail,
        )

    def host_refusal(self, hid: str) -> Optional[str]:
        """Why this host cannot take a match right now (None = it can):
        dead, every shard refusing (the reason the first one gives), or
        over the placement p99 budget (``tuning.placement_p99_budget_ms``,
        fed by the harvested per-shard tick p99)."""
        if hid in self._dead:
            return "dead"
        sup = self.hosts[hid]
        first_reason: Optional[str] = None
        any_accepts = False
        for shard in sup.shards.values():
            r = shard.admission_refusal()
            if r is None:
                any_accepts = True
                break
            if first_reason is None:
                first_reason = r
        if not any_accepts:
            return first_reason or "dead"
        budget = self.tuning.placement_p99_budget_ms
        if budget:
            h = sup.healthz()
            p99s = [
                s.get("tick_p99_ms") for s in h["shards"].values()
                if s.get("tick_p99_ms") is not None
            ]
            if p99s and max(p99s) > budget:
                return "overloaded"
        return None

    def choose_host(self, match_id: str,
                    exclude: Tuple[str, ...] = ()) -> str:
        """The ring's preference walk with capacity/p99-aware refusal —
        the supervisor's §16 placement policy, one level up."""
        for hid in self.ring.preference(match_id):
            if hid in exclude:
                continue
            reason = self.host_refusal(hid)
            if reason is None:
                return hid
            self._m_refusals.labels(reason=reason).inc()
        raise FleetError(
            f"no host accepts match {match_id!r}")

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def admit(self, match_id: str,
              builder_factory: Callable[[], Any],
              socket_factory: Optional[Callable[[], Any]] = None,
              *, peer_addrs: Any = (),
              journal: Optional[bool] = None,
              state_template: Any = None,
              game_factory: Optional[Callable[[], Any]] = None,
              host: Optional[str] = None,
              shard: Optional[str] = None) -> str:
        """Place one match behind a fresh virtual endpoint.  With no
        ``socket_factory`` the match serves through a
        :func:`~ggrs_tpu.fleet.ingress.virtual_endpoint_socket` leg —
        the normal ingress-fronted shape; passing one keeps direct-wire
        matches placeable too (they just cannot migrate invisibly).
        ``peer_addrs`` pre-claims the public peer source addresses.
        Returns the serving host id."""
        if match_id in self._records:
            raise InvalidRequest(f"match {match_id!r} already placed")
        peers = tuple((a[0], int(a[1])) for a in peer_addrs)
        vport = self.ingress.allocate_endpoint(peers=peers)
        if socket_factory is None:
            up = self.ingress.uplink_addr()
            socket_factory = functools.partial(
                virtual_endpoint_socket, up[0], up[1], vport)
        hid = host if host is not None else self.choose_host(match_id)
        sup = self.hosts[hid]
        placed = sup.admit(
            match_id, builder_factory, socket_factory,
            journal=journal, state_template=state_template,
            shard=shard, game_factory=game_factory,
        )
        rec = PlacedMatch(match_id, hid, vport, peers)
        self._records[match_id] = rec
        self._m_admissions.labels(host=hid).inc()
        self._record_timeline(
            EV_PLACE, match_id,
            detail={"host": hid, "vport": vport, "shard": placed})
        if placed is not None:
            self._push_route(rec)
        return hid

    def claim_peers(self, match_id: str, peers: Any) -> None:
        """Late joiners: claim more public source addresses for a
        match's virtual endpoint."""
        rec = self._record(match_id)
        peers = tuple((a[0], int(a[1])) for a in peers)
        self.ingress.claim_peers(rec.vport, peers)
        rec.peers = tuple(dict.fromkeys(rec.peers + peers))

    def _record(self, match_id: str) -> PlacedMatch:
        rec = self._records.get(match_id)
        if rec is None:
            raise InvalidRequest(f"no placed match {match_id!r}")
        return rec

    # ------------------------------------------------------------------
    # the route plane
    # ------------------------------------------------------------------

    def _push_route(self, rec: PlacedMatch) -> bool:
        """Point the match's virtual endpoint at its current serving
        leg.  Every push carries the placement epoch and a fresh
        monotonic version; the ingress refuses anything stale — pushes
        go through :func:`~ggrs_tpu.fleet.ingress.encode_route_update`
        bytes even in-process, so the fenced path is the ONLY path."""
        port = self.hosts[rec.host].match_port(rec.match_id)
        if port is None:
            return False  # parked/pending: routed once actually placed
        self._route_version += 1
        was_routed = rec.routed
        # the §28 causal stamp rides the fenced route bytes themselves:
        # the ingress re-emits the flip keyed by this exact context
        span = self._next_span()
        update = encode_route_update(
            ROUTE_OP_PUT, self.route_epoch, self._route_version,
            rec.vport, (self._host_addr(rec.host), port),
            ctx=pack_trace_ctx(rec.match_id, self.route_epoch, span),
        )
        verdict = self.ingress.apply_route_update(update)
        self._m_route_updates.labels(verdict=verdict).inc()
        if verdict != "ok":
            raise FleetError(
                f"route update for {rec.match_id!r} refused: {verdict}")
        if was_routed:
            # a re-point of an already-live route IS the flip peers feel
            self._record_timeline(
                EV_ROUTE_FLIP, rec.match_id, span=span,
                detail={"host": rec.host, "port": port,
                        "vport": rec.vport})
        rec.routed = True
        return True

    def _drop_route(self, rec: PlacedMatch) -> None:
        self._route_version += 1
        update = encode_route_update(
            ROUTE_OP_DEL, self.route_epoch, self._route_version,
            rec.vport, (self._host_addr(rec.host), 0),
            ctx=pack_trace_ctx(rec.match_id, self.route_epoch,
                               self._next_span()),
        )
        verdict = self.ingress.apply_route_update(update)
        self._m_route_updates.labels(verdict=verdict).inc()
        rec.routed = False

    # ------------------------------------------------------------------
    # cross-host live migration
    # ------------------------------------------------------------------

    def migrate(self, match_id: str, dst_host: Optional[str] = None,
                *, reason: str = "manual") -> str:
        """Move a live match to another host: export the §16 resume
        bundle, round-trip it through pickle bytes (what the TCP frame
        carries), adopt on the target, and only THEN flip the ingress
        route — ``MIG_TRANSITIONS`` order, peers none the wiser.  On
        adoption failure the same bytes restore the match on the source
        (the EXPORTED→IDLE abort edge) and the error propagates."""
        rec = self._record(match_id)
        if rec.lost is not None:
            raise InvalidRequest(f"match {match_id!r} is lost")
        src = rec.host
        if dst_host is None:
            dst_host = self.choose_host(match_id, exclude=(src,))
        if dst_host == src:
            raise InvalidRequest(
                f"match {match_id!r} already serves on {src!r}")
        t0 = time.perf_counter()
        mig = _Migration(match_id, src, dst_host)
        self._record_timeline(
            EV_MIGRATE_BEGIN, match_id,
            detail={"from": src, "to": dst_host, "reason": reason})
        blob = self.hosts[src].export_transfer(match_id)
        # ggrs-model: transitions(idle->exported)
        mig.phase = MIG_EXPORTED
        # the cross-host contract: the bundle must survive real bytes
        # (module-level factories, plain-data state) — enforced on every
        # migration, not just the ones that actually cross a machine
        wire = pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            self.hosts[dst_host].adopt_transfer(
                match_id, pickle.loads(wire))
        except Exception as adopt_err:
            self._restore_on_source(rec, mig, wire, adopt_err)
            raise
        # ggrs-model: transitions(exported->adopted)
        mig.phase = MIG_ADOPTED
        rec.host = dst_host
        rec.meta = None  # stale source meta must not drive a failover
        self._push_route(rec)
        # ggrs-model: transitions(adopted->flipped)
        mig.phase = MIG_FLIPPED
        self._m_migrations.labels(reason=reason).inc()
        self._h_migration.observe(time.perf_counter() - t0)
        self._record_timeline(
            EV_MIGRATE_COMMIT, match_id,
            detail={"from": src, "to": dst_host})
        # ggrs-model: transitions(flipped->idle)
        mig.phase = MIG_IDLE
        return dst_host

    def _restore_on_source(self, rec: PlacedMatch, mig: _Migration,
                           wire: bytes, cause: Exception) -> None:
        """The abort edge: target refused/failed adoption, so the same
        exported bytes restore the match where it was (a fresh unpickle
        — the failed target may have half-consumed its copy)."""
        self._record_timeline(
            EV_MIGRATE_ABORT, rec.match_id,
            detail={"to": mig.dst, "cause": str(cause)})
        try:
            self.hosts[rec.host].adopt_transfer(
                rec.match_id, pickle.loads(wire))
            # ggrs-model: transitions(exported->idle)
            mig.phase = MIG_IDLE
            self._push_route(rec)  # the restored leg has a new port
        except Exception:
            rec.lost = (
                f"migration to {mig.dst!r} failed ({cause}) and the "
                f"source restore failed too")
            self._m_lost.inc()
            self._drop_route(rec)
            _logger.error("match %s lost in migration: %s",
                          rec.match_id, rec.lost)

    # ------------------------------------------------------------------
    # host death + cross-host journal failover
    # ------------------------------------------------------------------

    def kill_host(self, hid: str) -> None:
        """Confirm a whole machine dead (chaos / ops verdict — the
        placement analogue of the §17 watchdog's CONFIRMED-dead rule):
        stop scheduling to it, stop ticking it, and mint a fresh route
        epoch so anything the dead incarnation's supervisor signed is
        refused at the ingress.  Its matches failover on the next
        :meth:`advance_all` from their replicated meta + shared-storage
        journals."""
        if hid not in self.hosts or hid in self._dead:
            return
        self._dead.add(hid)
        self.ring.remove(hid)
        self.route_epoch += 1
        self._update_host_gauge()
        _logger.warning("host %s confirmed dead; route epoch now %d",
                        hid, self.route_epoch)

    def _failover_dead(self) -> None:
        for mid, rec in list(self._records.items()):
            if rec.host not in self._dead or rec.lost is not None:
                continue
            self._failover_match(rec)

    def _failover_match(self, rec: PlacedMatch) -> None:
        mig = _Migration(rec.match_id, None, "?")
        dead_host = rec.host
        meta = rec.meta
        if meta is None:
            rec.lost = "no replicated meta survived the host"
            self._m_lost.inc()
            self._drop_route(rec)
            return
        # the journal on shared storage IS the export (§16): same
        # machine edge, no source to ask
        # ggrs-model: transitions(idle->exported)
        mig.phase = MIG_EXPORTED
        excluded = tuple(self._dead)
        last_err: Optional[Exception] = None
        while True:
            try:
                dst = self.choose_host(rec.match_id, exclude=excluded)
            except FleetError:
                break
            mig.dst = dst
            try:
                self.hosts[dst].adopt_from_meta(
                    pickle.loads(pickle.dumps(
                        meta, protocol=pickle.HIGHEST_PROTOCOL)))
            except Exception as e:
                last_err = e
                excluded = excluded + (dst,)
                continue
            # ggrs-model: transitions(exported->adopted)
            mig.phase = MIG_ADOPTED
            rec.host = dst
            rec.meta = None
            self._push_route(rec)
            # ggrs-model: transitions(adopted->flipped)
            mig.phase = MIG_FLIPPED
            self._m_host_failovers.inc()
            self._record_timeline(
                EV_FAILOVER, rec.match_id,
                detail={"from": dead_host, "to": dst})
            # ggrs-model: transitions(flipped->idle)
            mig.phase = MIG_IDLE
            return
        rec.lost = f"no survivor could adopt: {last_err}"
        self._m_lost.inc()
        self._drop_route(rec)
        _logger.error("match %s lost in host failover: %s",
                      rec.match_id, rec.lost)

    # ------------------------------------------------------------------
    # the tick
    # ------------------------------------------------------------------

    def advance_all(self) -> Dict[str, Dict[str, List[Any]]]:
        """One placement tick: tick every live host, route any
        backoff-parked match that finally placed, replicate failover
        meta, and run cross-host failover for dead hosts' matches.
        The ingress dataplane is pumped by its OWN loop (the runner's
        select loop, or the test driver for an in-process node) — this
        method owns only the control plane."""
        self._tick += 1
        out: Dict[str, Dict[str, List[Any]]] = {}
        for hid, sup in self.hosts.items():
            if hid in self._dead:
                continue
            out[hid] = sup.advance_all()
        for rec in self._records.values():
            if (not rec.routed and rec.lost is None
                    and rec.host not in self._dead):
                self._push_route(rec)
        self._refresh_meta()
        self._failover_dead()
        return out

    def _refresh_meta(self) -> None:
        """Replicate every placed match's failover description — cheap
        plain-data dicts, refreshed each tick so a host can die between
        any two ticks and lose at most one tick of identity drift."""
        for mid, rec in self._records.items():
            if rec.host in self._dead or rec.lost is not None:
                continue
            try:
                rec.meta = self.hosts[rec.host].record_meta(mid)
            except Exception:
                pass  # mid-transfer gap: last good meta stands

    # ------------------------------------------------------------------
    # the serving surface (routed to the serving host)
    # ------------------------------------------------------------------

    def add_local_input(self, match_id: str, handle: int, value) -> None:
        rec = self._record(match_id)
        self.hosts[rec.host].add_local_input(match_id, handle, value)

    def events(self, match_id: str) -> List[Any]:
        rec = self._record(match_id)
        return self.hosts[rec.host].events(match_id)

    def current_frame(self, match_id: str) -> int:
        rec = self._record(match_id)
        return self.hosts[rec.host].current_frame(match_id)

    def match_host(self, match_id: str) -> Optional[str]:
        rec = self._records.get(match_id)
        return None if rec is None else rec.host

    def virtual_endpoint(self, match_id: str) -> Tuple[Tuple[str, int], int]:
        """The match's public truth: (ingress public address, vport) —
        what never changes, whatever happens behind the ingress."""
        rec = self._record(match_id)
        return tuple(self.ingress.public_addr()), rec.vport

    def lost_matches(self) -> Dict[str, str]:
        lost: Dict[str, str] = {
            mid: rec.lost for mid, rec in self._records.items()
            if rec.lost is not None
        }
        for hid, sup in self.hosts.items():
            if hid in self._dead:
                continue
            for mid, why in sup.lost_matches().items():
                lost.setdefault(mid, f"{hid}: {why}")
        return lost

    # ------------------------------------------------------------------
    # obs
    # ------------------------------------------------------------------

    def _update_host_gauge(self) -> None:
        live = len(self.hosts) - len(self._dead)
        self._g_hosts.labels(state="live").set(live)
        self._g_hosts.labels(state="dead").set(len(self._dead))

    def healthz(self) -> Dict[str, Any]:
        """Fleet-of-fleets aggregate: every host's shard records under
        ``host/shard`` keys (each carrying its ``ingress_routes`` count
        for the fleet_top INGRESS column), the ingress info block, and
        one top-level verdict."""
        routes_by_loc: Dict[Tuple[str, str], int] = {}
        for mid, rec in self._records.items():
            if rec.lost is not None or rec.host in self._dead:
                continue
            sid = self.hosts[rec.host].match_location(mid)
            if sid is not None:
                key = (rec.host, sid)
                routes_by_loc[key] = routes_by_loc.get(key, 0) + 1
        shards: Dict[str, Any] = {}
        hosts: Dict[str, Any] = {}
        pending = 0
        ok = True
        for hid, sup in self.hosts.items():
            if hid in self._dead:
                hosts[hid] = dict(ok=False, state="dead")
                for sid in sup.shards:
                    shards[f"{hid}/{sid}"] = dict(
                        ok=False, state="dead", backend="-", matches=0,
                        ingress_routes=0)
                continue
            h = sup.healthz()
            ok = ok and bool(h["ok"])
            pending += h.get("pending_admissions", 0)
            hosts[hid] = dict(ok=h["ok"], state="live",
                              matches=h["matches"], tick=h["tick"],
                              slo=h.get("slo"))
            for sid, sh in h["shards"].items():
                sh = dict(sh)
                sh["ingress_routes"] = routes_by_loc.get((hid, sid), 0)
                shards[f"{hid}/{sid}"] = sh
        lost = self.lost_matches()
        try:
            ing = self.ingress.info()
        except Exception as e:
            ing = dict(error=str(e))
        # §28 rollup: the fleet-of-fleets SLO verdict is the worst
        # host's — one level to page on, per-host detail kept under hosts
        rank = {"ok": 0, "warn": 1, "critical": 2}
        host_levels = {
            hid: (hinfo.get("slo") or {}).get("level")
            for hid, hinfo in hosts.items()
            if (hinfo.get("slo") or {}).get("level")
        }
        slo = None
        if host_levels:
            worst = max(host_levels.values(),
                        key=lambda lv: rank.get(lv, 0))
            slo = dict(level=worst, ok=worst != "critical",
                       hosts=host_levels)
        return dict(
            ok=ok and not lost and bool(hosts),
            tick=self._tick,
            hosts=hosts,
            shards=shards,
            matches=len(self._records) - len(lost),
            pending_admissions=pending,
            lost_matches=len(lost),
            route_epoch=self.route_epoch,
            slo=slo,
            ingress=ing,
        )

    # ------------------------------------------------------------------
    # teardown
    # ------------------------------------------------------------------

    def close(self) -> None:
        for hid, sup in self.hosts.items():
            if hid in self._dead:
                continue
            try:
                sup.close()
            except Exception:
                pass
