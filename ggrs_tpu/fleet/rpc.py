"""Supervisor ↔ shard-runner frame protocol (DESIGN.md §17).

A deliberately small transport for the out-of-process shard backend: the
supervisor and its shard runners share one stream socket (a socketpair
for spawned runners, a UNIX socket for adopted ones) and speak
length-prefixed, crc32-checked, version-tagged frames:

  ``magic u16 "GR" | version u8 | kind u8 | payload_len u32 | crc u32``
  followed by ``payload_len`` payload bytes (pickled message object).
  ``crc = crc32(header[:8] + payload)`` — the crc covers the header
  fields too, so a corrupted length cannot silently resync the stream.

Properties the fleet layer leans on, each pinned adversarially by
``tests/test_fleet_rpc.py``:

- **max-frame clamp** both directions: an oversized frame is refused at
  send time and rejected at receive time (:class:`FrameError`), never
  buffered to OOM.
- **typed failures**: garbage magic, wrong version, crc mismatch,
  oversized length, undecodable payload → :class:`FrameError`; orderly
  EOF / reset / mid-frame close → :class:`RpcClosed`; deadline →
  :class:`RpcTimeout`.  A supervisor can always tell "the peer is gone"
  from "the stream is poisoned" from "the peer is slow" — the three have
  different watchdog consequences.
- **poisoned-stream containment**: after any :class:`FrameError` the
  connection refuses further traffic (there is no way to resync a
  corrupted length-prefixed stream); the caller must tear down and
  reconnect/failover — never retry-parse into a wedge.
- **partial-read tolerance**: frames arrive in arbitrary chunkings
  (slow sockets, interleaved heartbeats); the parser buffers across
  reads and never blocks past its deadline.

The payload codec is pickle: both ends are the same codebase on the same
machine (the trust boundary is the process, not the network), and the
fleet's migration bundles are already pinned pickle-portable by the PR 7
tests — the RPC layer inherits that contract instead of inventing a
second serialization.
"""

from __future__ import annotations

import pickle
import socket
import struct
import time
import zlib
from typing import Any, List, Optional, Tuple

from ..core.errors import GgrsError

MAGIC = b"GR"
VERSION = 1

# Pinned pickle protocol for every fleet serialization seam (RPC
# payloads, migration bundles): the runner may be a different
# interpreter build than the supervisor, and a cross-host fleet may mix
# Python versions, so HIGHEST_PROTOCOL (interpreter-dependent) and the
# version-dependent default are both wire hazards — ggrs-verify's
# det/pickle-protocol rule rejects them.  Protocol 4 is supported
# everywhere ≥ 3.4 and is the newest one whose frames every supported
# peer can read.
PICKLE_PROTOCOL = 4

# frame kinds
KIND_CALL = 1       # supervisor → runner: {op: ..., **args}
KIND_REPLY = 2      # runner → supervisor: the op's result
KIND_ERR = 3        # runner → supervisor: {type, msg, traceback}
KIND_HEARTBEAT = 4  # runner → supervisor, unsolicited liveness
KIND_GOODBYE = 5    # runner → supervisor: graceful exit notice

_KINDS = (KIND_CALL, KIND_REPLY, KIND_ERR, KIND_HEARTBEAT, KIND_GOODBYE)

_HEADER = struct.Struct("<2sBBII")  # magic, version, kind, len, crc
HEADER_SIZE = _HEADER.size

DEFAULT_MAX_FRAME = 64 << 20


class RpcError(GgrsError):
    """Base of every supervisor↔runner transport failure."""


class FrameError(RpcError):
    """Malformed frame (bad magic/version/crc/size, undecodable
    payload).  The stream cannot be resynced: close and reconnect."""


class RpcClosed(RpcError):
    """The peer is gone: orderly EOF, reset, or close mid-frame."""


class RpcTimeout(RpcError):
    """The deadline elapsed before a complete frame arrived."""


class RpcRemoteError(RpcError):
    """The runner executed the call and raised: carries the remote
    exception's type name, message, and traceback text."""

    def __init__(self, type_name: str, msg: str, traceback_text: str = ""):
        super().__init__(f"{type_name}: {msg}")
        self.type_name = type_name
        self.msg = msg
        self.traceback_text = traceback_text


def encode_frame(kind: int, payload: bytes,
                 max_frame: int = DEFAULT_MAX_FRAME) -> bytes:
    """One wire frame.  Refuses oversized payloads at the SENDER — the
    receiver's clamp is the backstop, not the policy."""
    if kind not in _KINDS:
        raise FrameError(f"unknown frame kind {kind}")
    if len(payload) > max_frame:
        raise FrameError(
            f"frame of {len(payload)} bytes exceeds the "
            f"{max_frame}-byte clamp"
        )
    head = struct.pack("<2sBBI", MAGIC, VERSION, kind, len(payload))
    crc = zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF
    return head + struct.pack("<I", crc) + payload


class RpcConn:
    """One framed connection over a stream socket.

    Single-threaded like everything session-shaped: one reader, one
    writer, no interleaved calls.  ``recv`` returns ``(kind, obj)`` and
    transparently buffers partial frames; ``call`` is the supervisor's
    request/response helper (heartbeats arriving mid-call update
    ``last_frame_at`` and are skipped).  Every received frame of any
    kind refreshes ``last_frame_at`` — any traffic proves liveness.
    """

    def __init__(self, sock: socket.socket, *,
                 max_frame: int = DEFAULT_MAX_FRAME) -> None:
        self._sock = sock
        self._buf = bytearray()
        self.max_frame = max_frame
        self.closed = False
        self._poisoned: Optional[str] = None
        self.last_frame_at: float = time.monotonic()
        self.goodbye: Optional[Any] = None  # payload of a received GOODBYE
        # observer for heartbeat payloads (the fleet obs harvest rides
        # them, DESIGN.md §18): called for every parsed HEARTBEAT frame
        # whether it arrives mid-call, in recv, or in poll_frames —
        # without this hook, call() would consume and DROP the payload.
        # Exceptions are swallowed: telemetry must never poison a stream.
        self.on_heartbeat: Optional[Any] = None
        # frame sequence numbers for the TCP resume seam (DESIGN.md
        # §25): tx_seq counts frames handed to sendall (whether or not
        # the bytes survived the wire), rx_seq counts frames fully
        # parsed.  On reconnect each side presents its rx_seq as a
        # cursor and the peer replays retained frames past it.
        self.tx_seq = 0
        self.rx_seq = 0
        self._retain: Optional[Any] = None  # deque[(seq, frame bytes)]
        self._call_id = 0
        self.stale_replies = 0  # correlation-mismatched replies dropped

    def fileno(self) -> int:
        return self._sock.fileno()

    @property
    def poisoned(self) -> Optional[str]:
        """The poison reason, or None.  A poisoned stream must never be
        resumed — the byte stream itself is corrupt."""
        return self._poisoned

    # ------------------------------------------------------------------
    # reconnect / resume seam (used by fleet.transport, DESIGN.md §25)
    # ------------------------------------------------------------------

    def enable_retain(self, n: int) -> None:
        """Keep the last ``n`` sent frames (by sequence) for replay
        after a reconnect.  Without it, resume is only possible when
        the peer has already received everything we ever sent."""
        import collections

        self._retain = collections.deque(maxlen=max(1, int(n)))

    def can_resume(self, peer_rx_seq: int) -> bool:
        """Whether our retained frames cover everything the peer has
        not received — i.e. every frame in ``(peer_rx_seq, tx_seq]`` is
        still in the ring."""
        if peer_rx_seq > self.tx_seq:
            return False  # peer claims frames we never sent
        if peer_rx_seq == self.tx_seq:
            return True
        if self._retain is None:
            return False
        # the ring is contiguous by construction: coverage == the
        # oldest retained seq reaches back to the peer's cursor
        return self._retain[0][0] <= peer_rx_seq + 1

    def replay_from(self, peer_rx_seq: int,
                    timeout: Optional[float] = 30.0) -> int:
        """Resend every retained frame past the peer's cursor, in
        order.  Returns the number replayed; raises :class:`RpcClosed`
        when the gap is not coverable or the socket dies mid-replay."""
        if not self.can_resume(peer_rx_seq):
            raise RpcClosed(
                f"cannot resume: peer cursor {peer_rx_seq}, tx_seq "
                f"{self.tx_seq}, retain floor "
                f"{self._retain[0][0] if self._retain else 'none'}"
            )
        n = 0
        self._sock.settimeout(timeout)
        for seq, frame in list(self._retain or ()):
            if seq <= peer_rx_seq:
                continue
            try:
                self._sock.sendall(frame)
            except OSError as e:
                self.closed = True
                raise RpcClosed(f"resume replay failed: {e}") from None
            n += 1
        return n

    def reattach(self, sock: socket.socket) -> None:
        """Swap in a fresh socket after a reconnect handshake.  Partial
        frame bytes buffered from the severed socket are discarded —
        the unparsed frame was never counted in ``rx_seq``, so the
        peer's replay delivers it whole.  Refused on a poisoned stream:
        corruption is not a link failure."""
        if self._poisoned:
            raise FrameError(f"stream poisoned: {self._poisoned}")
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = sock
        self._buf.clear()
        self.closed = False
        self.goodbye = None
        self.last_frame_at = time.monotonic()

    def chaos_sever(self, how: str = "rdwr") -> None:
        """Test/chaos hook: shut the underlying socket down without
        marking the conn closed — the next send/recv on either end
        surfaces EOF exactly like a cut cable (``how="wr"``/``"rd"``
        emulate a half-open link)."""
        flags = {"rdwr": socket.SHUT_RDWR, "wr": socket.SHUT_WR,
                 "rd": socket.SHUT_RD}[how]
        try:
            self._sock.shutdown(flags)
        except OSError:
            pass

    # ------------------------------------------------------------------
    # sending
    # ------------------------------------------------------------------

    def send(self, kind: int, obj: Any,
             timeout: Optional[float] = 30.0) -> None:
        """Pickle + frame + sendall.  A send timeout raises
        :class:`RpcTimeout` — a SIGSTOPped peer with a full socket
        buffer must wedge the WATCHDOG path, not the supervisor."""
        payload = pickle.dumps(obj, protocol=PICKLE_PROTOCOL)
        frame = encode_frame(kind, payload, self.max_frame)
        self._check_usable()
        # sequence + retain BEFORE the write: if sendall dies midway the
        # peer may or may not have the frame — its resume cursor decides,
        # and the ring must hold the frame either way
        self.tx_seq += 1
        if self._retain is not None:
            self._retain.append((self.tx_seq, frame))
        self._sock.settimeout(timeout)
        try:
            self._sock.sendall(frame)
        except socket.timeout:
            # an unknown prefix of the frame may be on the wire: the
            # stream can never be resynced — poison it so the next use
            # fails loudly instead of feeding the peer a torn frame
            self._poisoned = "send timed out mid-frame"
            raise RpcTimeout(
                f"send of a {len(frame)}-byte frame timed out "
                "(stream poisoned)"
            ) from None
        except OSError as e:
            self.closed = True
            raise RpcClosed(f"send failed: {e}") from None

    # ------------------------------------------------------------------
    # receiving
    # ------------------------------------------------------------------

    def recv(self, timeout: Optional[float] = None) -> Tuple[int, Any]:
        """The next frame, blocking up to ``timeout`` seconds."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            frame = self._parse_one()
            if frame is not None:
                return frame
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RpcTimeout("no complete frame before the deadline")
            self._check_usable()
            self._sock.settimeout(remaining)
            try:
                chunk = self._sock.recv(65536)
            except socket.timeout:
                raise RpcTimeout(
                    "no complete frame before the deadline"
                ) from None
            except OSError as e:
                self.closed = True
                raise RpcClosed(f"recv failed: {e}") from None
            if not chunk:
                self.closed = True
                if self._buf:
                    raise RpcClosed(
                        f"connection closed mid-frame "
                        f"({len(self._buf)} buffered bytes)"
                    )
                raise RpcClosed("connection closed")
            self._buf += chunk

    def poll_frames(self) -> List[Tuple[int, Any]]:
        """Drain whatever frames are already readable without blocking —
        the supervisor's control plane calls this each tick to pick up
        heartbeats/goodbyes between RPCs.  EOF is recorded (``closed``),
        not raised; a malformed frame still raises :class:`FrameError`."""
        if self.closed or self._poisoned:
            return []
        try:
            self._sock.settimeout(0)
            while True:
                chunk = self._sock.recv(65536)
                if not chunk:
                    self.closed = True
                    break
                self._buf += chunk
        except (BlockingIOError, socket.timeout):
            pass
        except OSError:
            self.closed = True
        out = []
        while True:
            frame = self._parse_one()
            if frame is None:
                return out
            out.append(frame)

    def _check_usable(self) -> None:
        if self._poisoned:
            raise FrameError(f"stream poisoned: {self._poisoned}")
        if self.closed:
            raise RpcClosed("connection already closed")

    def _poison(self, why: str) -> "FrameError":
        self._poisoned = why
        return FrameError(why)

    def _parse_one(self) -> Optional[Tuple[int, Any]]:
        """One frame from the buffer, or None when incomplete.  Any
        malformation poisons the connection and raises."""
        if self._poisoned:
            raise FrameError(f"stream poisoned: {self._poisoned}")
        if len(self._buf) < HEADER_SIZE:
            return None
        magic, version, kind, plen, crc = _HEADER.unpack_from(self._buf)
        if magic != MAGIC:
            raise self._poison(f"bad magic {bytes(magic)!r}")
        if version != VERSION:
            raise self._poison(
                f"frame version {version} != supported {VERSION}"
            )
        if kind not in _KINDS:
            raise self._poison(f"unknown frame kind {kind}")
        if plen > self.max_frame:
            raise self._poison(
                f"frame of {plen} bytes exceeds the "
                f"{self.max_frame}-byte clamp"
            )
        if len(self._buf) < HEADER_SIZE + plen:
            return None
        payload = bytes(self._buf[HEADER_SIZE : HEADER_SIZE + plen])
        expect = zlib.crc32(payload, zlib.crc32(bytes(self._buf[:8])))
        if (expect & 0xFFFFFFFF) != crc:
            raise self._poison("frame crc mismatch")
        self.rx_seq += 1  # a fully-validated frame: the resume cursor
        del self._buf[: HEADER_SIZE + plen]
        try:
            obj = pickle.loads(payload)
        except Exception as e:
            raise self._poison(f"undecodable frame payload: {e}")
        self.last_frame_at = time.monotonic()
        if kind == KIND_GOODBYE:
            self.goodbye = obj
        elif kind == KIND_HEARTBEAT and self.on_heartbeat is not None:
            try:
                self.on_heartbeat(obj)
            except Exception:
                pass
        return kind, obj

    # ------------------------------------------------------------------
    # the supervisor's request/response helper
    # ------------------------------------------------------------------

    def call(self, op: str, timeout: float, **kw: Any) -> Any:
        """Send ``{op, **kw}`` and wait for the matching reply.
        Heartbeats arriving first are consumed (they refresh
        ``last_frame_at``); a GOODBYE means the runner exited before
        answering (:class:`RpcClosed`).

        Calls carry a correlation id (``_cid``): a reply to an EARLIER
        call — possible after a TCP resume replays a reply whose call
        was abandoned to an :class:`RpcClosed` — is dropped and counted
        (``stale_replies``) instead of being mistaken for this call's
        answer.  Replies without an id (bare test servers) pass."""
        self._call_id += 1
        cid = self._call_id
        self.send(KIND_CALL, dict(kw, op=op, _cid=cid), timeout=timeout)
        deadline = time.monotonic() + timeout
        while True:
            try:
                kind, obj = self.recv(
                    timeout=max(0.0, deadline - time.monotonic())
                )
            except RpcTimeout:
                # the reply is abandoned but may still arrive later;
                # correlation ids make a later call drop it, but the
                # conservative contract stands: an intact same-socket
                # stream with an unconsumed reply in flight is torn
                # down, not trusted
                self._poisoned = (
                    f"reply to {op!r} abandoned after timeout"
                )
                raise
            if kind == KIND_HEARTBEAT:
                continue
            if kind == KIND_REPLY:
                if isinstance(obj, dict) and "_cid" in obj:
                    if obj["_cid"] != cid:
                        self.stale_replies += 1
                        continue
                    return obj.get("_r")
                return obj
            if kind == KIND_ERR:
                if (isinstance(obj, dict) and "_cid" in obj
                        and obj["_cid"] != cid):
                    self.stale_replies += 1
                    continue
                raise RpcRemoteError(
                    obj.get("type", "Exception"), obj.get("msg", ""),
                    obj.get("traceback", ""),
                )
            if kind == KIND_GOODBYE:
                raise RpcClosed(f"runner said goodbye: {obj!r}")
            raise self._poison(f"unexpected frame kind {kind} mid-call")

    def close(self) -> None:
        if not self.closed:
            self.closed = True
        try:
            self._sock.close()
        except OSError:
            pass
