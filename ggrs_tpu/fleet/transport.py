"""The multi-host fleet link: authenticated TCP transport for the
supervisor↔runner RPC (DESIGN.md §25).

``fleet/rpc.py``'s crc32-framed protocol is transport-agnostic bytes;
this module gives it an AF_INET carrier with the three properties a
cross-host link needs that a socketpair gets for free:

- **authentication** — an HMAC-SHA256 challenge-response handshake
  (shared token from :class:`FleetTuning`) so a runner port exposed on a
  fleet network only talks to its supervisor;
- **reconnect ≠ failover** — a severed link opens a bounded reconnect
  window (jittered-backoff redial + sequence-numbered frame resumption)
  during which failover is FORBIDDEN; only a closed window, a fenced
  goodbye, or a reaped process confirms death (the §25 model's
  invariant: "no failover while a reconnect window is open");
- **split-brain fencing** — every runner incarnation holds an epoch
  token MINTED BY THE SUPERVISOR at handshake; after a failover the
  epoch is bumped, so a resurrected old runner is refused at handshake
  (``HS_REFUSED_FENCE``) and can never ack a tick again.

The supervisor side listens (:class:`ShardLink`, one listener per
``ProcShard``) and the runner dials (:class:`RunnerLink`, behind
``ShardRunner --tcp host:port``): runners dialing in is the natural
direction once runners live on other hosts behind NAT/ingress.  The
server half of the handshake is a non-blocking state machine
(:class:`PendingHandshake`) with a per-connection deadline, so a
slowloris dribble or garbage-before-magic scanner can never wedge the
supervisor's tick loop.

Every ``link_state`` assignment below performs an edge declared in
``LINK_TRANSITIONS`` — the §22 conformance lint proves it, and the
reconnect-vs-failover model (``analysis/machines.py``) validates its
actions against the same parsed table.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import random
import select
import socket
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .rpc import RpcConn, RpcError

_logger = logging.getLogger("ggrs_tpu.fleet.transport")

# ----------------------------------------------------------------------
# the link state machine (DESIGN.md §25, §22)
# ----------------------------------------------------------------------

LINK_CONNECTING = "connecting"      # listener armed, no authed runner yet
LINK_UP = "up"                      # authed conn serving frames
LINK_RECONNECTING = "reconnecting"  # severed; reconnect window open
LINK_DOWN = "down"                  # window expired / fenced / torn down

# The declared link transition table: every ``link_state`` assignment
# performs one of these edges (the ggrs-model conformance lint proves
# it), and ``link_model`` in analysis/machines.py validates its action
# edges against this tuple.  DOWN is the only state failover may be
# declared from — RECONNECTING is explicitly NOT confirmed death.
LINK_TRANSITIONS = (
    (LINK_CONNECTING, LINK_UP),        # fresh handshake granted
    (LINK_CONNECTING, LINK_DOWN),      # teardown before any runner
    (LINK_UP, LINK_RECONNECTING),      # sever: EOF while process lives
    (LINK_RECONNECTING, LINK_UP),      # resume inside the window
    (LINK_RECONNECTING, LINK_DOWN),    # window expired / resume fenced
    (LINK_UP, LINK_DOWN),              # goodbye / teardown
    (LINK_DOWN, LINK_CONNECTING),      # re-adoption after failover
)

# ----------------------------------------------------------------------
# handshake wire format (layout contract §20 — mirrored in
# analysis/layout.py, skew-tested in tests/test_verify_layout.py)
# ----------------------------------------------------------------------

HS_VERSION = 1
HS_MAGIC_CHALLENGE = b"GC"
HS_MAGIC_AUTH = b"GA"
HS_MAGIC_VERDICT = b"GV"

NONCE_BYTES = 16
MAC_BYTES = 32
SHARD_ID_BYTES = 16

# server → client: magic, advertised version, flags, nonce
CHALLENGE = struct.Struct("<2sBB16s")
# client → server, pre-MAC prefix: magic, chosen version, flags,
# epoch (supervisor-minted token held by this runner incarnation),
# resume cursor (the client's rx frame sequence), shard id
AUTH_PREFIX = struct.Struct("<2sBBQQ16s")
# the full auth record: prefix + HMAC-SHA256(token, nonce ‖ prefix)
AUTH = struct.Struct("<2sBBQQ16s32s")
# server → client: magic, version, verdict code, granted/current epoch,
# server's rx frame sequence (the client replays retained tx past it)
VERDICT = struct.Struct("<2sBBQQ")

# §28 trace context, as carried inside fleet-link RPC payloads and the
# ingress ROUTE_UPDATE tail: match-id hash u64, placement epoch u32,
# span id u32.  This is a LITERAL mirror of obs/timeline.py TRACE_CTX —
# the §20 layout check parses both definitions and pins them equal.
TRACE_CTX = struct.Struct("<QII")
TRACE_CTX_BYTES = 16

AUTH_FLAG_RESUME = 0x01

# verdict codes
HS_OK_FRESH = 0        # accepted; epoch field is the granted token
HS_OK_RESUME = 1       # accepted; replay retained frames past cursor
HS_REFUSED_AUTH = 2    # bad MAC / wrong shard
HS_REFUSED_VERSION = 3 # unsupported protocol version
HS_REFUSED_FENCE = 4   # stale epoch: a newer incarnation owns the shard
HS_REFUSED_RESUME = 5  # resume impossible (frame gap / no session)
HS_REFUSED_BUSY = 6    # fresh connect while another runner is attached


class HandshakeError(Exception):
    """The handshake could not complete: protocol garbage, a refusal
    verdict, or the peer vanished mid-exchange."""


def handshake_mac(token: str, nonce: bytes, prefix: bytes) -> bytes:
    """HMAC-SHA256 over ``nonce ‖ auth-record-prefix``: binding the MAC
    to the server's fresh nonce makes a captured record worthless on a
    new connection (the replayed-handshake test pins it)."""
    return hmac.new(
        token.encode("utf-8"), nonce + prefix, hashlib.sha256,
    ).digest()


def pack_auth(token: str, nonce: bytes, *, epoch: int, cursor: int,
              shard_id: str, resume: bool) -> bytes:
    flags = AUTH_FLAG_RESUME if resume else 0
    prefix = AUTH_PREFIX.pack(
        HS_MAGIC_AUTH, HS_VERSION, flags, epoch, cursor,
        shard_id.encode("utf-8")[:SHARD_ID_BYTES],
    )
    return prefix + handshake_mac(token, nonce, prefix)


def tune_tcp_socket(sock: socket.socket, keepalive_s: float = 0.0) -> None:
    """TCP_NODELAY always (the frames are latency-bound ticks, not
    throughput), SO_KEEPALIVE when armed so a silently-dead peer
    surfaces as an error instead of an eternal hang."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # AF_UNIX in tests
    if keepalive_s and keepalive_s > 0:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        idle = max(1, int(keepalive_s))
        for opt, val in (("TCP_KEEPIDLE", idle),
                         ("TCP_KEEPINTVL", max(1, idle // 3)),
                         ("TCP_KEEPCNT", 3)):
            if hasattr(socket, opt):
                try:
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    getattr(socket, opt), val)
                except OSError:
                    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise HandshakeError(
                f"peer closed mid-handshake ({len(buf)}/{n} bytes)"
            )
        buf += chunk
    return buf


def client_handshake(sock: socket.socket, *, token: str, shard_id: str,
                     epoch: int, cursor: int, resume: bool,
                     timeout: float = 2.0) -> Tuple[int, int, int]:
    """The dialing (runner) half: read challenge, answer with the
    MAC'd auth record, read the verdict.  Returns ``(code, epoch,
    server_cursor)``; raises :class:`HandshakeError` on wire garbage or
    a dropped connection (refusals come back as codes, not raises — the
    caller decides whether a fence is fatal)."""
    sock.settimeout(timeout)
    raw = _recv_exact(sock, CHALLENGE.size)
    magic, version, _flags, nonce = CHALLENGE.unpack(raw)
    if magic != HS_MAGIC_CHALLENGE:
        raise HandshakeError(f"bad challenge magic {magic!r}")
    if version != HS_VERSION:
        # version negotiation, v1 edition: one version exists; a client
        # that only speaks it must bail loudly on anything else
        raise HandshakeError(f"server speaks handshake v{version}, "
                             f"this runner speaks v{HS_VERSION}")
    sock.sendall(pack_auth(token, nonce, epoch=epoch, cursor=cursor,
                           shard_id=shard_id, resume=resume))
    raw = _recv_exact(sock, VERDICT.size)
    magic, _version, code, granted_epoch, srv_cursor = VERDICT.unpack(raw)
    if magic != HS_MAGIC_VERDICT:
        raise HandshakeError(f"bad verdict magic {magic!r}")
    return code, granted_epoch, srv_cursor


class PendingHandshake:
    """The accepting (supervisor) half of one in-flight handshake, as a
    non-blocking state machine: the challenge goes out at accept, then
    :meth:`pump` drains whatever bytes have arrived toward one complete
    auth record, against a hard deadline.  A slowloris that dribbles a
    byte a second, or a scanner that sends garbage, costs the
    supervisor one fd until the deadline — never a blocked tick loop."""

    def __init__(self, sock: socket.socket, *, token: str,
                 deadline: float, started: float) -> None:
        self.sock = sock
        self.token = token
        self.deadline = deadline
        self.started = started
        self.nonce = os.urandom(NONCE_BYTES)
        self.auth: Optional[Dict[str, Any]] = None
        self.failed: Optional[str] = None
        self._buf = bytearray()
        try:
            # 20 bytes into a fresh send buffer: never blocks in practice
            sock.settimeout(0.5)
            sock.sendall(CHALLENGE.pack(
                HS_MAGIC_CHALLENGE, HS_VERSION, 0, self.nonce))
            sock.setblocking(False)
        except OSError:
            self.failed = "eof"

    def pump(self, now: float) -> Optional[str]:
        """Returns ``None`` while still reading, ``"auth"`` once a
        well-formed record is parsed (MAC verdict in ``self.auth``), or
        a failure reason (``timeout`` / ``eof`` / ``garbage``)."""
        if self.failed is not None:
            return self.failed
        if self.auth is not None:
            return "auth"
        if now >= self.deadline:
            self.failed = "timeout"
            return self.failed
        while len(self._buf) < AUTH.size:
            try:
                chunk = self.sock.recv(AUTH.size - len(self._buf))
            except (BlockingIOError, InterruptedError):
                return None
            except OSError:
                self.failed = "eof"
                return self.failed
            if not chunk:
                self.failed = "eof"
                return self.failed
            self._buf += chunk
            # fail garbage as soon as the magic is readable — a scanner
            # spraying junk should not hold the fd until its deadline
            if len(self._buf) >= 2 and bytes(self._buf[:2]) != HS_MAGIC_AUTH:
                self.failed = "garbage"
                return self.failed
        prefix = bytes(self._buf[:AUTH_PREFIX.size])
        (_magic, version, flags, epoch, cursor,
         shard_raw, mac) = AUTH.unpack(bytes(self._buf))
        self.auth = dict(
            version=version, flags=flags, epoch=epoch, cursor=cursor,
            shard=shard_raw.rstrip(b"\0").decode("utf-8", "replace"),
            mac_ok=hmac.compare_digest(
                mac, handshake_mac(self.token, self.nonce, prefix)),
        )
        return "auth"

    def _send_verdict(self, code: int, epoch: int, cursor: int) -> bool:
        try:
            self.sock.settimeout(2.0)
            self.sock.sendall(VERDICT.pack(
                HS_MAGIC_VERDICT, HS_VERSION, code, epoch, cursor))
            return True
        except OSError:
            return False

    def grant(self, code: int, epoch: int,
              cursor: int) -> Optional[socket.socket]:
        """Send an accepting verdict and hand the socket over (blocking
        mode restored).  ``None`` if the peer died first."""
        if self._send_verdict(code, epoch, cursor):
            self.sock.setblocking(True)
            return self.sock
        self.close()
        return None

    def refuse(self, code: int, epoch: int = 0) -> None:
        self._send_verdict(code, epoch, 0)
        self.close()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class ShardLink:
    """Supervisor-side link endpoint for one proc shard: the listener,
    the in-flight handshakes, the epoch mint, and the link state
    machine.  Owns every ``link_state`` assignment in the tree (the
    conformance lint scans exactly this file)."""

    # how many concurrent half-open handshakes we will hold fds for;
    # beyond it new connects are dropped at accept (slowloris clamp)
    MAX_PENDING = 8

    def __init__(self, shard_id: str, tuning: Any, *,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics: Any = None) -> None:
        self.shard_id = shard_id
        self.tuning = tuning
        self.link_state = LINK_CONNECTING
        self.epoch = 0
        self.window_deadline: Optional[float] = None
        self.conn: Optional[RpcConn] = None
        self.reconnects = 0
        self.window_expiries = 0
        self.refusals: Dict[str, int] = {}
        self._fresh_granted = False
        self._pending: List[PendingHandshake] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(self.MAX_PENDING)
        self._listener.setblocking(False)
        self.address: Tuple[str, int] = self._listener.getsockname()[:2]
        if metrics is None:
            from ..obs.registry import Registry
            metrics = Registry()
        self._m_reconnects = metrics.counter(
            "ggrs_fleet_link_reconnects_total",
            "severed links resumed inside the reconnect window",
            labels=("shard",))
        self._m_refusals = metrics.counter(
            "ggrs_fleet_link_refusals_total",
            "handshakes refused or abandoned, by reason",
            labels=("shard", "reason"))
        self._m_expiries = metrics.counter(
            "ggrs_fleet_link_window_expiries_total",
            "reconnect windows that closed without a resume",
            labels=("shard",))
        self._h_handshake = metrics.histogram(
            "ggrs_fleet_link_handshake_seconds",
            "accept → verdict latency per handshake attempt",
            buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
            labels=("shard",))
        self._g_epoch = metrics.gauge(
            "ggrs_fleet_link_epoch",
            "current supervisor-minted epoch per shard link",
            labels=("shard",))

    # -- epoch mint + state verbs --------------------------------------

    def mint_epoch(self) -> int:
        """Supervisor-minted fencing token: bumped on every (re)spawn
        and every confirmed-death teardown, so any runner holding an
        older epoch is refused at handshake."""
        self.epoch += 1
        self._g_epoch.labels(shard=self.shard_id).set(self.epoch)
        return self.epoch

    def established(self, conn: RpcConn) -> None:
        """A fresh handshake's conn passed hello: the link is serving."""
        self.conn = conn
        self._fresh_granted = False
        self.window_deadline = None
        # ggrs-model: transitions(connecting->up)
        self.link_state = LINK_UP

    def sever(self, now: Optional[float] = None) -> None:
        """EOF while the process (for all we know) lives: open the
        reconnect window.  Failover is forbidden until it closes."""
        now = time.monotonic() if now is None else now
        self.window_deadline = now + self.tuning.link_reconnect_window_s
        # ggrs-model: transitions(up->reconnecting)
        self.link_state = LINK_RECONNECTING
        _logger.warning(
            "shard %s link severed; reconnect window %.2fs (epoch %d)",
            self.shard_id, self.tuning.link_reconnect_window_s, self.epoch,
        )

    def expire(self, now: Optional[float] = None) -> None:
        """The window closed without a resume: the runner is CONFIRMED
        unreachable — count it, fence it, and let failover proceed."""
        self.window_expiries += 1
        self._m_expiries.labels(shard=self.shard_id).inc()
        self.down("reconnect window expired")

    def down(self, reason: str) -> None:
        """Terminal for this incarnation: drop pending handshakes,
        forget the conn, bump the epoch so the old runner stays fenced."""
        for hs in self._pending:
            hs.close()
        self._pending = []
        self.conn = None
        self._fresh_granted = False
        self.window_deadline = None
        if self.link_state != LINK_DOWN:
            # ggrs-model: transitions(connecting->down, reconnecting->down, up->down)
            self.link_state = LINK_DOWN
            self.mint_epoch()
            _logger.info("shard %s link down (%s); epoch now %d",
                         self.shard_id, reason, self.epoch)

    def reopen(self) -> None:
        """Arm for the next incarnation (respawn / re-adoption)."""
        if self.link_state == LINK_DOWN:
            # ggrs-model: transitions(down->connecting)
            self.link_state = LINK_CONNECTING
        self._fresh_granted = False

    # -- the accept/handshake pump -------------------------------------

    def pump(self, now: Optional[float] = None) -> Optional[Tuple[str, Any]]:
        """One non-blocking step: accept new connections, advance every
        in-flight handshake, judge the completed ones.  Returns the
        first significant event — ``("fresh", sock)`` for a granted
        fresh handshake (caller builds the conn + hello), ``("resumed",
        None)`` after an in-place resume — else ``None``."""
        now = time.monotonic() if now is None else now
        while True:
            try:
                s, _addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                break
            if len(self._pending) >= self.MAX_PENDING:
                s.close()
                self._count_refusal("overflow")
                continue
            tune_tcp_socket(s, self.tuning.link_keepalive_s)
            self._pending.append(PendingHandshake(
                s, token=self.tuning.link_auth_token,
                deadline=now + self.tuning.link_handshake_timeout_s,
                started=now))
        event: Optional[Tuple[str, Any]] = None
        still: List[PendingHandshake] = []
        for hs in self._pending:
            r = hs.pump(now)
            if r is None:
                still.append(hs)
                continue
            if r != "auth":
                # timeout / eof / garbage: no verdict owed — close and
                # count (feeding scanners a protocol answer helps them)
                hs.close()
                self._count_refusal(r)
                continue
            ev = self._judge(hs, now)
            if event is None and ev is not None:
                event = ev
        self._pending = still
        return event

    def _count_refusal(self, reason: str) -> None:
        self.refusals[reason] = self.refusals.get(reason, 0) + 1
        self._m_refusals.labels(shard=self.shard_id, reason=reason).inc()

    def _judge(self, hs: PendingHandshake,
               now: float) -> Optional[Tuple[str, Any]]:
        a = hs.auth or {}
        self._h_handshake.labels(shard=self.shard_id).observe(
            max(0.0, now - hs.started))
        if a.get("version") != HS_VERSION:
            hs.refuse(HS_REFUSED_VERSION)
            self._count_refusal("version")
            return None
        if not a.get("mac_ok"):
            hs.refuse(HS_REFUSED_AUTH)
            self._count_refusal("auth")
            return None
        if a["shard"] and a["shard"] != self.shard_id[:SHARD_ID_BYTES]:
            hs.refuse(HS_REFUSED_AUTH)
            self._count_refusal("auth")
            return None
        if a["flags"] & AUTH_FLAG_RESUME:
            # THE fencing rule: an epoch that is not the current mint is
            # a dead incarnation talking — refuse before any state moves
            if a["epoch"] != self.epoch:
                hs.refuse(HS_REFUSED_FENCE, self.epoch)
                self._count_refusal("fence")
                _logger.warning(
                    "shard %s: fenced stale runner (epoch %d, current "
                    "%d)", self.shard_id, a["epoch"], self.epoch)
                return None
            if self.link_state == LINK_UP:
                # half-open: the runner saw an EOF we have not — its
                # authed, epoch-current resume IS the sever signal
                self.sever(now)
            if self.link_state != LINK_RECONNECTING or self.conn is None:
                hs.refuse(HS_REFUSED_RESUME, self.epoch)
                self._count_refusal("resume")
                return None
            if not self.conn.can_resume(a["cursor"]):
                # resume impossible: explicit epoch bump + full
                # re-adopt (down() mints) instead of a silent gap
                hs.refuse(HS_REFUSED_RESUME, self.epoch)
                self._count_refusal("resume")
                self.down("resume impossible: frame gap past the "
                          "retain ring")
                return None
            sock = hs.grant(HS_OK_RESUME, self.epoch, self.conn.rx_seq)
            if sock is None:
                return None
            try:
                self.conn.reattach(sock)
                self.conn.replay_from(a["cursor"])
            except (RpcError, OSError) as e:
                _logger.warning("shard %s resume replay failed (%s); "
                                "window stays open", self.shard_id, e)
                return None
            self.reconnects += 1
            self._m_reconnects.labels(shard=self.shard_id).inc()
            self.window_deadline = None
            # ggrs-model: transitions(reconnecting->up)
            self.link_state = LINK_UP
            _logger.info("shard %s link resumed (epoch %d)",
                         self.shard_id, self.epoch)
            return ("resumed", None)
        # fresh connect
        if self.link_state != LINK_CONNECTING or self._fresh_granted:
            hs.refuse(HS_REFUSED_BUSY, self.epoch)
            self._count_refusal("busy")
            return None
        sock = hs.grant(HS_OK_FRESH, self.epoch, 0)
        if sock is None:
            return None
        self._fresh_granted = True
        return ("fresh", sock)

    def wait_for_runner(self, timeout: float) -> socket.socket:
        """Blocking pump until a fresh handshake is granted (the spawn /
        adoption path).  Raises ``TimeoutError`` past ``timeout``."""
        deadline = time.monotonic() + timeout
        while True:
            now = time.monotonic()
            ev = self.pump(now)
            if ev is not None and ev[0] == "fresh":
                return ev[1]
            if now >= deadline:
                raise TimeoutError(
                    f"shard {self.shard_id}: no runner handshake within "
                    f"{timeout:.1f}s on {self.address[0]}:{self.address[1]}"
                )
            rl = [self._listener] + [h.sock for h in self._pending]
            try:
                select.select(rl, [], [], min(0.05, deadline - now))
            except (OSError, ValueError):
                pass

    def info(self) -> Dict[str, Any]:
        return dict(
            state=self.link_state,
            epoch=self.epoch,
            address=f"{self.address[0]}:{self.address[1]}",
            reconnects=self.reconnects,
            window_expiries=self.window_expiries,
            refusals=dict(self.refusals),
            pending=len(self._pending),
        )

    def close(self) -> None:
        for hs in self._pending:
            hs.close()
        self._pending = []
        self.conn = None
        try:
            self._listener.close()
        except OSError:
            pass


class RunnerLink:
    """Runner-side dialer: the fresh connect at startup and the
    jittered-backoff resume loop inside the runner's own reconnect
    window.  Holds the supervisor-granted epoch token."""

    def __init__(self, host: str, port: int, *, token: str,
                 shard_id: str = "") -> None:
        self.host = host
        self.port = port
        self.token = token
        self.shard_id = shard_id
        self.epoch = 0
        # pre-hello defaults; configure() re-reads them from the
        # supervisor's FleetTuning once hello delivers it
        self.window_s = 3.0
        self.backoff_s = 0.05
        self.handshake_timeout_s = 2.0
        self.keepalive_s = 5.0
        self._rng = random.Random(
            zlib.crc32((shard_id or host).encode()) ^ 0x71CB)

    def configure(self, tuning: Any) -> None:
        self.window_s = tuning.link_reconnect_window_s
        self.backoff_s = tuning.link_backoff_s
        self.handshake_timeout_s = tuning.link_handshake_timeout_s
        self.keepalive_s = tuning.link_keepalive_s

    def _dial(self, *, epoch: int, cursor: int,
              resume: bool) -> Tuple[int, int, int, socket.socket]:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.handshake_timeout_s)
        try:
            tune_tcp_socket(sock, self.keepalive_s)
            code, granted, srv_cursor = client_handshake(
                sock, token=self.token, shard_id=self.shard_id,
                epoch=epoch, cursor=cursor, resume=resume,
                timeout=self.handshake_timeout_s)
        except BaseException:
            sock.close()
            raise
        if code not in (HS_OK_FRESH, HS_OK_RESUME):
            sock.close()
        return code, granted, srv_cursor, sock

    def dial_fresh(self, timeout: float = 30.0) -> socket.socket:
        """Startup connect, retried with jittered backoff until the
        supervisor's listener answers (it may not be pumping yet)."""
        deadline = time.monotonic() + timeout
        attempt = 0
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            try:
                code, granted, _cur, sock = self._dial(
                    epoch=0, cursor=0, resume=False)
            except (OSError, HandshakeError) as e:
                last = e
            else:
                if code == HS_OK_FRESH:
                    self.epoch = granted
                    return sock
                if code in (HS_REFUSED_AUTH, HS_REFUSED_VERSION,
                            HS_REFUSED_FENCE):
                    raise HandshakeError(
                        f"supervisor refused fresh handshake "
                        f"(code {code})")
                last = HandshakeError(f"verdict code {code}")
            delay = (self.backoff_s * (2 ** min(attempt, 6))
                     * (0.5 + self._rng.random()))
            attempt += 1
            time.sleep(min(delay, max(
                0.0, deadline - time.monotonic())))
        raise HandshakeError(
            f"no supervisor on {self.host}:{self.port} within "
            f"{timeout:.1f}s: {last}")

    def reconnect(self, conn: RpcConn) -> str:
        """The runner half of the reconnect window: redial with
        jittered backoff, present the granted epoch + rx cursor, resume
        the conn in place on success.  Returns ``"resumed"``,
        ``"fenced"`` (a newer incarnation owns the shard — exit, do not
        fail over the supervisor's decision), ``"refused"``, or
        ``"gave-up"`` (window exhausted)."""
        if conn.poisoned is not None:
            return "refused"  # a poisoned stream must not be resumed
        deadline = time.monotonic() + self.window_s
        attempt = 0
        while True:
            try:
                code, _granted, srv_cursor, sock = self._dial(
                    epoch=self.epoch, cursor=conn.rx_seq, resume=True)
            except (OSError, HandshakeError):
                code, sock = None, None
            if code in (HS_OK_RESUME, HS_OK_FRESH) and sock is not None:
                try:
                    conn.reattach(sock)
                    conn.replay_from(srv_cursor)
                    return "resumed"
                except (RpcError, OSError):
                    try:
                        sock.close()
                    except OSError:
                        pass
            elif code == HS_REFUSED_FENCE:
                return "fenced"
            elif code in (HS_REFUSED_AUTH, HS_REFUSED_VERSION):
                return "refused"
            # HS_REFUSED_RESUME / HS_REFUSED_BUSY / no answer: the
            # supervisor may still be noticing the sever — keep trying
            now = time.monotonic()
            if now >= deadline:
                return "gave-up"
            delay = (self.backoff_s * (2 ** min(attempt, 6))
                     * (0.5 + self._rng.random()))
            attempt += 1
            time.sleep(min(delay, max(0.0, deadline - now)))
