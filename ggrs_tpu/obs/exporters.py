"""Exporters for the metrics registry: Prometheus text exposition, JSON
snapshots, and a stdlib HTTP endpoint (DESIGN.md §12).

The exporters only *read* — they never drive the pool.  Bank-side gauges
refresh when the driving thread calls ``HostSessionPool.scrape()`` (one
ctypes crossing for the whole bank); the HTTP server then serves whatever
the last scrape left in the registry.  Serving and scraping are split
deliberately: sessions are single-threaded (the Send-not-Sync contract),
so an HTTP thread must never reach into the bank itself.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional

from .registry import Registry

__all__ = ["prometheus_text", "json_snapshot", "start_http_server",
           "MetricsServer"]


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: Registry) -> str:
    """The registry in Prometheus text exposition format (version 0.0.4:
    ``# HELP`` / ``# TYPE`` headers, one sample per line)."""
    lines = []
    for fam in registry.families():
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                for upper, cum in child.cumulative():
                    le = "+Inf" if upper == float("inf") else _fmt_value(upper)
                    extra = 'le="%s"' % le
                    lines.append(
                        f"{fam.name}_bucket{_label_str(labels, extra)} {cum}"
                    )
                lines.append(
                    f"{fam.name}_sum{_label_str(labels)} "
                    f"{_fmt_value(child.sum)}"
                )
                lines.append(
                    f"{fam.name}_count{_label_str(labels)} {child.count}"
                )
            else:
                lines.append(
                    f"{fam.name}{_label_str(labels)} "
                    f"{_fmt_value(child.value)}"
                )
    return "\n".join(lines) + "\n"


def json_snapshot(registry: Registry) -> Dict[str, Any]:
    """The registry as a JSON-serializable dict — the shape bench.py
    embeds in its ``bench_out`` records and chaos summaries print."""
    out: Dict[str, Any] = {}
    for fam in registry.families():
        samples = []
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                samples.append({
                    "labels": labels,
                    "sum": child.sum,
                    "count": child.count,
                    "buckets": [
                        {"le": upper if upper != float("inf") else "+Inf",
                         "count": cum}
                        for upper, cum in child.cumulative()
                    ],
                })
            else:
                samples.append({"labels": labels, "value": child.value})
        out[fam.name] = {
            "type": fam.kind,
            "help": fam.help,
            "samples": samples,
        }
    return out


class MetricsServer:
    """Minimal scrape endpoint over ``http.server``: ``/metrics`` serves
    the Prometheus text format, ``/metrics.json`` the JSON snapshot.
    Daemon-threaded; ``close()`` shuts it down.  Reads are GIL-safe
    against concurrent increments (plain attribute reads), so no
    coordination with the driving thread is needed."""

    def __init__(self, registry: Registry, port: int = 0,
                 addr: str = "127.0.0.1") -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_GET(h) -> None:  # noqa: N805 - handler convention
                if h.path.startswith("/metrics.json"):
                    body = json.dumps(json_snapshot(registry)).encode()
                    ctype = "application/json"
                elif h.path.startswith("/metrics"):
                    body = prometheus_text(registry).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
                h.send_response(200)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(h, *args) -> None:  # quiet by default
                pass

        self._httpd = ThreadingHTTPServer((addr, port), Handler)
        self.addr = addr
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ggrs-obs-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_http_server(registry: Registry, port: int = 0,
                      addr: str = "127.0.0.1") -> MetricsServer:
    """Serve ``registry`` on ``http://addr:port/metrics`` (port 0 picks a
    free one; read it back from the returned server's ``.port``)."""
    return MetricsServer(registry, port=port, addr=addr)
