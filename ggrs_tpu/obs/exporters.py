"""Exporters for the metrics registry: Prometheus text exposition, JSON
snapshots, and a stdlib HTTP endpoint (DESIGN.md §12, §14).

The exporters only *read* — they never drive the pool.  Bank-side gauges
refresh when the driving thread calls ``HostSessionPool.scrape()`` (one
ctypes crossing for the whole bank); the HTTP server then serves whatever
the last scrape left in the registry.  Serving and scraping are split
deliberately: sessions are single-threaded (the Send-not-Sync contract),
so an HTTP thread must never reach into the bank itself.

Endpoints:

- ``/metrics`` — Prometheus text, ``/metrics.json`` — the JSON snapshot;
- ``/healthz`` — liveness plus last-tick age (a ``health`` callable
  returning the driving loop's last-tick ``time.monotonic()`` stamp, e.g.
  ``lambda: pool.last_tick_at``; 503 when the loop has gone stale);
- ``/trace`` — the attached :class:`~ggrs_tpu.obs.trace.Tracer`'s current
  window as Chrome trace-event JSON (save it, open in chrome://tracing).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional

from .registry import Registry

__all__ = ["prometheus_text", "json_snapshot", "start_http_server",
           "MetricsServer", "MetricsHTTPServer"]


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: Registry) -> str:
    """The registry in Prometheus text exposition format (version 0.0.4:
    ``# HELP`` / ``# TYPE`` headers, one sample per line)."""
    lines = []
    for fam in registry.families():
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                for upper, cum in child.cumulative():
                    le = "+Inf" if upper == float("inf") else _fmt_value(upper)
                    extra = 'le="%s"' % le
                    lines.append(
                        f"{fam.name}_bucket{_label_str(labels, extra)} {cum}"
                    )
                lines.append(
                    f"{fam.name}_sum{_label_str(labels)} "
                    f"{_fmt_value(child.sum)}"
                )
                lines.append(
                    f"{fam.name}_count{_label_str(labels)} {child.count}"
                )
            else:
                lines.append(
                    f"{fam.name}{_label_str(labels)} "
                    f"{_fmt_value(child.value)}"
                )
    return "\n".join(lines) + "\n"


def json_snapshot(registry: Registry) -> Dict[str, Any]:
    """The registry as a JSON-serializable dict — the shape bench.py
    embeds in its ``bench_out`` records and chaos summaries print."""
    out: Dict[str, Any] = {}
    for fam in registry.families():
        samples = []
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                samples.append({
                    "labels": labels,
                    "sum": child.sum,
                    "count": child.count,
                    "buckets": [
                        {"le": upper if upper != float("inf") else "+Inf",
                         "count": cum}
                        for upper, cum in child.cumulative()
                    ],
                })
            else:
                samples.append({"labels": labels, "value": child.value})
        out[fam.name] = {
            "type": fam.kind,
            "help": fam.help,
            "samples": samples,
        }
    return out


class MetricsServer:
    """Minimal scrape endpoint over ``http.server``: ``/metrics`` serves
    the Prometheus text format, ``/metrics.json`` the JSON snapshot,
    ``/healthz`` liveness + last-tick age, ``/trace`` the tracer window.
    Daemon-threaded; ``close()`` shuts it down.  Reads are GIL-safe
    against concurrent increments (plain attribute reads), so no
    coordination with the driving thread is needed.

    ``health``: optional callable returning either the driving loop's
    last-tick timestamp on the ``time.monotonic()`` clock (or None before
    the first tick), or an aggregate health DICT with an ``"ok"`` key
    (e.g. ``ShardSupervisor.healthz`` — the fleet-wide ``/healthz``
    aggregation, served verbatim).  ``/healthz`` reports 200 while
    healthy (timestamp age under ``stale_after`` seconds / ``ok`` true),
    503 otherwise — the pageable "pool wedged" signal.  ``tracer``:
    optional :class:`~ggrs_tpu.obs.trace.Tracer` served on ``/trace``.
    """

    def __init__(self, registry: Registry, port: int = 0,
                 addr: str = "127.0.0.1", tracer: Any = None,
                 health: Any = None, stale_after: float = 5.0) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        def healthz_body() -> tuple:
            last = health() if health is not None else None
            if isinstance(last, dict):
                # an aggregate health report (e.g.
                # ``ShardSupervisor.healthz``: fleet-wide verdict +
                # per-shard records): its "ok" decides the status code,
                # AND the server's stale_after still applies to the
                # report's last_tick_age_s — a wedged serving loop that
                # stops calling advance_all() must go 503 here exactly
                # like the timestamp path (the pageable signal), because
                # the aggregate's own ok is computed from state the dead
                # loop can no longer update
                age = last.get("last_tick_age_s")
                ok = bool(last.get("ok")) and (
                    age is None or age <= stale_after
                )
                return (200 if ok else 503), json.dumps(
                    dict(last, ok=ok), default=str
                ).encode()
            age = None
            if last is not None:
                age = max(0.0, time.monotonic() - last)
            ok = age is None or age <= stale_after
            body = json.dumps({
                "ok": ok,
                "last_tick_age_s": age,
                "stale_after_s": stale_after if health is not None else None,
            }).encode()
            return (200 if ok else 503), body

        class Handler(BaseHTTPRequestHandler):
            def do_GET(h) -> None:  # noqa: N805 - handler convention
                status = 200
                if h.path.startswith("/metrics.json"):
                    body = json.dumps(json_snapshot(registry)).encode()
                    ctype = "application/json"
                elif h.path.startswith("/metrics"):
                    body = prometheus_text(registry).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif h.path.startswith("/healthz"):
                    status, body = healthz_body()
                    ctype = "application/json"
                elif h.path.startswith("/trace") and tracer is not None:
                    body = json.dumps(tracer.chrome_trace()).encode()
                    ctype = "application/json"
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
                h.send_response(status)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(h, *args) -> None:  # quiet by default
                pass

        self._httpd = ThreadingHTTPServer((addr, port), Handler)
        self.addr = addr
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ggrs-obs-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# the name the quickstarts use; MetricsServer predates the /healthz and
# /trace endpoints and stays as an alias
MetricsHTTPServer = MetricsServer


def start_http_server(registry: Registry, port: int = 0,
                      addr: str = "127.0.0.1", tracer: Any = None,
                      health: Any = None,
                      stale_after: float = 5.0) -> MetricsServer:
    """Serve ``registry`` on ``http://addr:port/metrics`` (port 0 picks a
    free one; read it back from the returned server's ``.port``).  Pass
    ``tracer=`` / ``health=`` to light up ``/trace`` and ``/healthz``."""
    return MetricsServer(registry, port=port, addr=addr, tracer=tracer,
                         health=health, stale_after=stale_after)
