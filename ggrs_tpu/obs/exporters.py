"""Exporters for the metrics registry: Prometheus text exposition, JSON
snapshots, and a stdlib HTTP endpoint (DESIGN.md §12, §14).

The exporters only *read* — they never drive the pool.  Bank-side gauges
refresh when the driving thread calls ``HostSessionPool.scrape()`` (one
ctypes crossing for the whole bank); the HTTP server then serves whatever
the last scrape left in the registry.  Serving and scraping are split
deliberately: sessions are single-threaded (the Send-not-Sync contract),
so an HTTP thread must never reach into the bank itself.

Endpoints:

- ``/metrics`` — Prometheus text, ``/metrics.json`` — the JSON snapshot;
- ``/healthz`` — liveness plus last-tick age (a ``health`` callable
  returning the driving loop's last-tick ``time.monotonic()`` stamp, e.g.
  ``lambda: pool.last_tick_at``; 503 when the loop has gone stale);
- ``/trace`` — the attached :class:`~ggrs_tpu.obs.trace.Tracer`'s current
  window as Chrome trace-event JSON (save it, open in chrome://tracing).
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .registry import Registry

__all__ = ["prometheus_text", "json_snapshot", "start_http_server",
           "validate_exposition", "MetricsServer", "MetricsHTTPServer"]


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(value: str) -> str:
    # HELP lines escape backslash and newline (not quotes) per the text
    # exposition format — an unescaped newline would tear the line apart
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _grouped_families(registry) -> "List[List[Any]]":
    """Families grouped by name, preserving first-seen order.  A
    :class:`~ggrs_tpu.obs.registry.MultiRegistry` view can legitimately
    yield the same family name from two member registries (local vs
    fleet-harvested, DESIGN.md §18); the exposition must then emit ONE
    ``# TYPE`` header with every group's samples under it — duplicate
    headers are a promtool error."""
    order: List[str] = []
    groups: Dict[str, List[Any]] = {}
    for fam in registry.families():
        if fam.name not in groups:
            order.append(fam.name)
            groups[fam.name] = []
        groups[fam.name].append(fam)
    return [groups[name] for name in order]


def prometheus_text(registry) -> str:
    """The registry (or a ``MultiRegistry`` union view) in Prometheus
    text exposition format (version 0.0.4: ``# HELP`` / ``# TYPE``
    headers, one sample per line, label/help values escaped)."""
    lines = []
    for group in _grouped_families(registry):
        first = group[0]
        if first.help:
            lines.append(f"# HELP {first.name} {_escape_help(first.help)}")
        lines.append(f"# TYPE {first.name} {first.kind}")
        for fam in group:
            if fam.kind != first.kind:
                # shape conflict across registries: emitting mixed-kind
                # samples under one header would be invalid exposition
                continue
            for labels, child in fam.samples():
                if fam.kind == "histogram":
                    for upper, cum in child.cumulative():
                        le = ("+Inf" if upper == float("inf")
                              else _fmt_value(upper))
                        extra = 'le="%s"' % le
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_label_str(labels, extra)} {cum}"
                        )
                    lines.append(
                        f"{fam.name}_sum{_label_str(labels)} "
                        f"{_fmt_value(child.sum)}"
                    )
                    lines.append(
                        f"{fam.name}_count{_label_str(labels)} {child.count}"
                    )
                else:
                    lines.append(
                        f"{fam.name}{_label_str(labels)} "
                        f"{_fmt_value(child.value)}"
                    )
    return "\n".join(lines) + "\n"


def json_snapshot(registry) -> Dict[str, Any]:
    """The registry (or a ``MultiRegistry`` view) as a JSON-serializable
    dict — the shape bench.py embeds in its ``bench_out`` records and
    chaos summaries print.  Same-name families across member registries
    merge their sample lists."""
    out: Dict[str, Any] = {}
    for group in _grouped_families(registry):
        first = group[0]
        samples = []
        for fam in group:
            if fam.kind != first.kind:
                continue
            for labels, child in fam.samples():
                if fam.kind == "histogram":
                    samples.append({
                        "labels": labels,
                        "sum": child.sum,
                        "count": child.count,
                        "buckets": [
                            {"le": upper if upper != float("inf")
                             else "+Inf",
                             "count": cum}
                            for upper, cum in child.cumulative()
                        ],
                    })
                else:
                    samples.append({"labels": labels, "value": child.value})
        out[first.name] = {
            "type": first.kind,
            "help": first.help,
            "samples": samples,
        }
    return out


# ----------------------------------------------------------------------
# promtool-style exposition validation (DESIGN.md §18, run in CI by
# build_sanitized.sh through tests/test_fleet_obs.py)
# ----------------------------------------------------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")
_VALUE_RE = re.compile(
    r"(?:[+-]?Inf|NaN|[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?)"
)
_SAMPLE_KINDS = {"counter", "gauge", "histogram", "summary", "untyped"}


def _parse_labels(s: str, errors: List[str], where: str
                  ) -> Optional[List[Tuple[str, str]]]:
    """Parse one ``{k="v",...}`` label block (without the braces);
    validates names and escape sequences.  Returns None on error."""
    out: List[Tuple[str, str]] = []
    i, n = 0, len(s)
    while i < n:
        m = _LABEL_NAME_RE.match(s, i)
        if m is None:
            errors.append(f"{where}: bad label name at ...{s[i:i+20]!r}")
            return None
        name = m.group(0)
        i = m.end()
        if i >= n or s[i] != "=":
            errors.append(f"{where}: expected '=' after label {name!r}")
            return None
        i += 1
        if i >= n or s[i] != '"':
            errors.append(f"{where}: label {name!r} value not quoted")
            return None
        i += 1
        value = []
        while i < n and s[i] != '"':
            if s[i] == "\\":
                if i + 1 >= n or s[i + 1] not in ('\\', '"', 'n'):
                    errors.append(
                        f"{where}: invalid escape in label {name!r}"
                    )
                    return None
                value.append({"\\": "\\", '"': '"', "n": "\n"}[s[i + 1]])
                i += 2
            elif s[i] == "\n":
                errors.append(f"{where}: raw newline in label {name!r}")
                return None
            else:
                value.append(s[i])
                i += 1
        if i >= n:
            errors.append(f"{where}: unterminated label value ({name!r})")
            return None
        i += 1  # closing quote
        out.append((name, "".join(value)))
        if i < n:
            if s[i] != ",":
                errors.append(f"{where}: expected ',' between labels")
                return None
            i += 1
    return out


def validate_exposition(text: str) -> List[str]:
    """Validate Prometheus text exposition the way ``promtool check
    metrics`` would: line syntax, label escaping, at most one ``# TYPE``
    per family (before its samples), no duplicate samples, and histogram
    structure — ``le`` strictly ascending with a terminal ``+Inf``
    bucket, cumulative counts non-decreasing, ``_count`` equal to the
    ``+Inf`` bucket, ``_sum``/``_count`` present.  Returns the list of
    problems (empty = conformant)."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    sampled: set = set()      # family names that already emitted samples
    seen: set = set()         # (name, frozen labelset) duplicate check
    # histogram bookkeeping: (base name, base labelset) -> parts
    hist: Dict[Tuple[str, Tuple], Dict[str, Any]] = {}
    for lineno, line in enumerate(text.split("\n"), 1):
        if not line:
            continue
        where = f"line {lineno}"
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                if _NAME_RE.fullmatch(name) is None:
                    errors.append(f"{where}: bad metric name {name!r}")
                    continue
                if parts[1] == "TYPE":
                    kind = parts[3].strip() if len(parts) > 3 else ""
                    if kind not in _SAMPLE_KINDS:
                        errors.append(
                            f"{where}: unknown TYPE {kind!r} for {name}"
                        )
                    if name in types:
                        errors.append(f"{where}: duplicate TYPE for {name}")
                    if name in sampled:
                        errors.append(
                            f"{where}: TYPE for {name} after its samples"
                        )
                    types[name] = kind
            continue
        m = _NAME_RE.match(line)
        if m is None:
            errors.append(f"{where}: unparseable sample {line[:40]!r}")
            continue
        name = m.group(0)
        rest = line[m.end():]
        labels: List[Tuple[str, str]] = []
        if rest.startswith("{"):
            # a '}' inside a quoted value is legal; scan for the real one
            depth_in_quote = False
            close = -1
            j = 1
            while j < len(rest):
                c = rest[j]
                if depth_in_quote:
                    if c == "\\":
                        j += 1
                    elif c == '"':
                        depth_in_quote = False
                elif c == '"':
                    depth_in_quote = True
                elif c == "}":
                    close = j
                    break
                j += 1
            if close < 0:
                errors.append(f"{where}: unterminated label block")
                continue
            parsed = _parse_labels(rest[1:close], errors, where)
            if parsed is None:
                continue
            labels = parsed
            rest = rest[close + 1:]
        if not rest.startswith(" "):
            errors.append(f"{where}: missing space before value")
            continue
        fields = rest[1:].split(" ")
        if not fields or _VALUE_RE.fullmatch(fields[0]) is None:
            errors.append(f"{where}: bad sample value {rest[1:]!r}")
            continue
        value = float(fields[0])
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and types.get(name[: -len(suffix)]) \
                    == "histogram":
                base = name[: -len(suffix)]
                break
        sampled.add(base)
        key = (name, tuple(sorted(labels)))
        if key in seen:
            errors.append(f"{where}: duplicate sample {name}{labels}")
        seen.add(key)
        if base != name or types.get(base) == "histogram":
            no_le = tuple(sorted(
                (k, v) for k, v in labels if k != "le"
            ))
            h = hist.setdefault((base, no_le),
                                {"le": [], "sum": None, "count": None})
            if name == base + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    errors.append(f"{where}: bucket without le label")
                    continue
                try:
                    le_v = float("inf") if le == "+Inf" else float(le)
                except ValueError:
                    errors.append(f"{where}: unparseable le {le!r}")
                    continue
                h["le"].append((le_v, value, lineno))
            elif name == base + "_sum":
                h["sum"] = value
            elif name == base + "_count":
                h["count"] = value
    for (base, no_le), h in hist.items():
        where = f"histogram {base}{dict(no_le)}"
        les = h["le"]
        if not les:
            errors.append(f"{where}: no buckets")
            continue
        uppers = [u for u, _c, _l in les]
        if uppers != sorted(uppers) or len(set(uppers)) != len(uppers):
            errors.append(f"{where}: le not strictly ascending")
        if uppers[-1] != float("inf"):
            errors.append(f"{where}: missing terminal +Inf bucket")
        cums = [c for _u, c, _l in les]
        if any(b < a for a, b in zip(cums, cums[1:])):
            errors.append(f"{where}: cumulative counts decrease")
        if h["count"] is None:
            errors.append(f"{where}: missing _count")
        elif uppers[-1] == float("inf") and h["count"] != cums[-1]:
            errors.append(
                f"{where}: _count {h['count']} != +Inf bucket {cums[-1]}"
            )
        if h["sum"] is None:
            errors.append(f"{where}: missing _sum")
    return errors


class MetricsServer:
    """Minimal scrape endpoint over ``http.server``: ``/metrics`` serves
    the Prometheus text format, ``/metrics.json`` the JSON snapshot,
    ``/healthz`` liveness + last-tick age, ``/trace`` the tracer window.
    Daemon-threaded; ``close()`` shuts it down.  Reads are GIL-safe
    against concurrent increments (plain attribute reads), so no
    coordination with the driving thread is needed.

    ``health``: optional callable returning either the driving loop's
    last-tick timestamp on the ``time.monotonic()`` clock (or None before
    the first tick), or an aggregate health DICT with an ``"ok"`` key
    (e.g. ``ShardSupervisor.healthz`` — the fleet-wide ``/healthz``
    aggregation, served verbatim).  ``/healthz`` reports 200 while
    healthy (timestamp age under ``stale_after`` seconds / ``ok`` true),
    503 otherwise — the pageable "pool wedged" signal.  ``tracer``:
    optional :class:`~ggrs_tpu.obs.trace.Tracer` served on ``/trace``.
    ``timelines``: optional callable returning the merged §28 match
    timelines (``{mid: [events]}``), served on ``/timeline`` for
    ``scripts/match_timeline.py`` and the fleet_top footer.
    """

    def __init__(self, registry: Registry, port: int = 0,
                 addr: str = "127.0.0.1", tracer: Any = None,
                 health: Any = None, stale_after: float = 5.0,
                 timelines: Any = None) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        def healthz_body() -> tuple:
            last = health() if health is not None else None
            if isinstance(last, dict):
                # an aggregate health report (e.g.
                # ``ShardSupervisor.healthz``: fleet-wide verdict +
                # per-shard records): its "ok" decides the status code,
                # AND the server's stale_after still applies to the
                # report's last_tick_age_s — a wedged serving loop that
                # stops calling advance_all() must go 503 here exactly
                # like the timestamp path (the pageable signal), because
                # the aggregate's own ok is computed from state the dead
                # loop can no longer update
                age = last.get("last_tick_age_s")
                ok = bool(last.get("ok")) and (
                    age is None or age <= stale_after
                )
                return (200 if ok else 503), json.dumps(
                    dict(last, ok=ok), default=str
                ).encode()
            age = None
            if last is not None:
                age = max(0.0, time.monotonic() - last)
            ok = age is None or age <= stale_after
            body = json.dumps({
                "ok": ok,
                "last_tick_age_s": age,
                "stale_after_s": stale_after if health is not None else None,
            }).encode()
            return (200 if ok else 503), body

        class Handler(BaseHTTPRequestHandler):
            def do_GET(h) -> None:  # noqa: N805 - handler convention
                status = 200
                if h.path.startswith("/metrics.json"):
                    body = json.dumps(json_snapshot(registry)).encode()
                    ctype = "application/json"
                elif h.path.startswith("/metrics"):
                    body = prometheus_text(registry).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif h.path.startswith("/healthz"):
                    status, body = healthz_body()
                    ctype = "application/json"
                elif h.path.startswith("/trace") and tracer is not None:
                    body = json.dumps(tracer.chrome_trace()).encode()
                    ctype = "application/json"
                elif h.path.startswith("/timeline") and timelines is not None:
                    body = json.dumps(timelines(), default=str).encode()
                    ctype = "application/json"
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
                h.send_response(status)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(h, *args) -> None:  # quiet by default
                pass

        self._httpd = ThreadingHTTPServer((addr, port), Handler)
        self.addr = addr
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ggrs-obs-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# the name the quickstarts use; MetricsServer predates the /healthz and
# /trace endpoints and stays as an alias
MetricsHTTPServer = MetricsServer


def start_http_server(registry: Registry, port: int = 0,
                      addr: str = "127.0.0.1", tracer: Any = None,
                      health: Any = None,
                      stale_after: float = 5.0,
                      timelines: Any = None) -> MetricsServer:
    """Serve ``registry`` on ``http://addr:port/metrics`` (port 0 picks a
    free one; read it back from the returned server's ``.port``).  Pass
    ``tracer=`` / ``health=`` to light up ``/trace`` and ``/healthz``,
    ``timelines=`` for ``/timeline``."""
    return MetricsServer(registry, port=port, addr=addr, tracer=tracer,
                         health=health, stale_after=stale_after,
                         timelines=timelines)
