"""``ggrs_tpu.obs`` — pool-scale observability (DESIGN.md §12).

Three dependency-free pieces:

- :mod:`registry` — counters, gauges, fixed-bucket histograms with label
  sets; near-zero cost on the hot path and a shared null mode for
  metrics-off runs.
- :mod:`recorder` — the per-slot flight recorder: a bounded ring of
  recent events (state changes, faults, rollback decisions, wire
  digests) dumped on quarantine/eviction for post-mortems.
- :mod:`exporters` — Prometheus text exposition, JSON snapshots, and a
  stdlib HTTP scrape endpoint (``/metrics``, ``/healthz``, ``/trace``).
- :mod:`trace` — the span tracer (DESIGN.md §14): tick → crossing → slot
  spans in a bounded ring with Chrome/Perfetto trace-event export;
  ``Tracer(enabled=False)`` compiles the layer out.
- :mod:`forensics` — desync post-mortems: first-divergent-frame bisection
  over shared checksum histories and the :class:`DesyncReport` artifact.
- :mod:`timeline` — match-lifecycle timelines (DESIGN.md §28): the
  stable cross-host event schema, the 16-byte trace context, and the
  bounded per-match stores the fleet ferries over the harvest plane.
- :mod:`slo` — frame-budget SLOs (DESIGN.md §28): per-tier compliance
  counters on the shard, multi-window burn rates + the 503-on-burn
  verdict on the supervisor.

The bank-side numbers behind these come from the native stat harvest:
``HostSessionPool.scrape()`` dumps every slot's protocol/sync counters
(ping, kbps, send-queue length, last-acked frame, rollback depth, frame
advantage both ways) in ONE ctypes crossing per scrape
(``ggrs_bank_stats`` in native/session_bank.cpp), preserving the
one-crossing-per-tick invariant of DESIGN.md §8.

Quickstart (see README "Observability")::

    from ggrs_tpu.obs import Registry, start_http_server
    from ggrs_tpu.parallel import HostSessionPool

    reg = Registry()
    pool = HostSessionPool(metrics=reg)
    ...
    server = start_http_server(reg, port=9464)
    while running:
        pool.advance_all()          # one crossing (the tick)
        pool.scrape()               # one crossing (every slot's stats)
"""

from .registry import (
    Counter,
    DEFAULT,
    Gauge,
    Histogram,
    MultiRegistry,
    Registry,
    default_registry,
)
from .recorder import ChecksumHistory, FlightRecorder
from .trace import NULL_TRACER, Tracer, validate_chrome_trace
from .forensics import (
    DesyncReport,
    build_desync_report,
    first_divergent_frame,
)
from .exporters import (
    MetricsHTTPServer,
    MetricsServer,
    json_snapshot,
    prometheus_text,
    start_http_server,
    validate_exposition,
)
from .fleet_obs import (
    FleetObs,
    RegistryCollector,
    fleet_metrics_digest,
    histogram_quantile,
)
from .timeline import (
    MatchTimeline,
    TIMELINE_EVENTS,
    TRACE_CTX,
    TRACE_CTX_BYTES,
    TimelineStore,
    first_occurrence_order,
    format_timeline,
    match_trace_id,
    merge_timelines,
    pack_trace_ctx,
    timeline_event,
    unpack_trace_ctx,
)
from .slo import (
    BurnRateEngine,
    ShardSloMeter,
    SloPolicy,
)

__all__ = [
    "BurnRateEngine",
    "ChecksumHistory",
    "Counter",
    "DEFAULT",
    "DesyncReport",
    "FleetObs",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MatchTimeline",
    "MetricsHTTPServer",
    "MetricsServer",
    "MultiRegistry",
    "NULL_TRACER",
    "Registry",
    "RegistryCollector",
    "ShardSloMeter",
    "SloPolicy",
    "TIMELINE_EVENTS",
    "TRACE_CTX",
    "TRACE_CTX_BYTES",
    "TimelineStore",
    "Tracer",
    "build_desync_report",
    "default_registry",
    "first_divergent_frame",
    "first_occurrence_order",
    "fleet_metrics_digest",
    "format_timeline",
    "histogram_quantile",
    "json_snapshot",
    "match_trace_id",
    "merge_timelines",
    "pack_trace_ctx",
    "prometheus_text",
    "start_http_server",
    "timeline_event",
    "unpack_trace_ctx",
    "validate_chrome_trace",
    "validate_exposition",
]
