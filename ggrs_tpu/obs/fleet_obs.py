"""Fleet-wide observability plane: runner telemetry harvest over RPC,
cross-process trace correlation, and the forensics ferry (DESIGN.md §18).

PRs 3/5 made a *single process* fully observable; PRs 7–8 grew the system
into a multi-process fleet where each subprocess runner builds a private
``Registry`` that used to die with the child.  This module is the seam
that makes the whole fleet observable from one endpoint:

- :class:`RegistryCollector` — the RUNNER side: walks a local registry
  (or several) and emits a **delta-encoded snapshot** — counters as
  monotonic deltas, gauges by value, histograms by per-bucket deltas —
  containing only the samples that changed since the last collect.  The
  snapshot piggybacks on the existing heartbeat/tick RPC replies, so the
  harvest adds ZERO extra round trips.
- :class:`FleetObs` — the SUPERVISOR side: merges snapshots into a
  dedicated ``harvest`` registry under a ``shard=<id>,backend=proc``
  label set (labels the runner already carries are kept; ``shard`` is
  overridden with the supervisor's id so one scrape is unambiguous),
  re-emits runner trace spans into the supervisor's tracer with an
  RTT-estimated clock offset, aggregates span durations into a
  ``ggrs_fleet_span_seconds{shard,name}`` histogram (the per-phase p99
  data ``scripts/fleet_top.py`` renders), and keeps a bounded ring of
  ferried forensics (flight-recorder dumps, DesyncReports) that would
  otherwise die with the child.

Merge semantics (pinned by tests/test_fleet_obs.py):

- **idempotent** — every snapshot carries ``(gen, seq)``; ``gen`` is the
  runner incarnation (its pid), ``seq`` a per-incarnation monotonic
  counter.  A re-delivered snapshot (same gen, seq <= last applied) is
  dropped, so double delivery can never double-count a counter delta.
- **restart-safe** — a new incarnation's ``gen`` differs; its deltas are
  relative to a fresh registry, so merged counters simply keep growing
  monotonically across restarts (federation semantics, no reset dip).
- **loss-tolerant** — a lost reply loses at most one interval's deltas;
  gauges self-heal on the next snapshot, counters under-count by the
  lost interval, which the ``ggrs_fleet_obs_snapshot_gaps_total``
  counter makes visible.

Like the rest of ``ggrs_tpu.obs``, everything here is observational
only: merging never drives a shard, collection never perturbs session
behavior, and a disabled harvest (``FleetTuning.obs_harvest=0``)
compiles the runner side out entirely.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from .registry import Registry
from .timeline import TimelineStore, timeline_ring_events
from .trace import NULL_TRACER

__all__ = [
    "RegistryCollector",
    "FleetObs",
    "histogram_quantile",
    "fleet_metrics_digest",
]

SNAPSHOT_VERSION = 1

# span-duration aggregation buckets (seconds): sub-ms resolution for the
# in-crossing phases, stretching to the 16.7 ms tick budget and beyond
SPAN_SECONDS_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
    0.0167, 0.05, 0.25, 1.0,
)

# cardinality clamp: at most this many distinct span names aggregate per
# shard; the long tail lands in name="other" (no unbounded label values)
MAX_SPAN_NAMES_PER_SHARD = 24

# the supervisor keeps at most this many ferried forensic records
MAX_FORENSICS = 64


class RegistryCollector:
    """Delta-encoded snapshots of one or more local registries.

    Single-threaded like its caller (the shard runner's serving loop):
    ``collect()`` walks every family, emits only samples whose value
    moved since the previous collect, and advances its baseline.  The
    first collect is therefore a full snapshot (every touched sample's
    delta from zero), which is exactly what a fresh incarnation should
    send.
    """

    def __init__(self, *registries: Registry, gen: int = 0) -> None:
        self._registries = [r for r in registries if r is not None]
        self.gen = gen
        self.seq = 0
        # (registry idx, family name, label values) -> baseline
        self._counters: Dict[Tuple, float] = {}
        self._gauges: Dict[Tuple, float] = {}
        self._hists: Dict[Tuple, Tuple[Tuple[int, ...], float, int]] = {}

    def collect(self) -> Optional[Dict[str, Any]]:
        """The changes since the last collect as a snapshot dict, or
        ``None`` when nothing moved (the caller then skips the payload
        entirely — an idle shard costs nothing on the wire)."""
        families: List[Dict[str, Any]] = []
        for ridx, reg in enumerate(self._registries):
            for fam in reg.families():
                samples: List[Tuple[Tuple[str, ...], Any]] = []
                for labels, child in list(fam.children.items()):
                    key = (ridx, fam.name, labels)
                    if fam.kind == "counter":
                        v = child.value
                        delta = v - self._counters.get(key, 0.0)
                        if delta:
                            self._counters[key] = v
                            samples.append((labels, delta))
                    elif fam.kind == "gauge":
                        v = child.value
                        if key not in self._gauges or self._gauges[key] != v:
                            self._gauges[key] = v
                            samples.append((labels, v))
                    elif fam.kind == "histogram":
                        counts = tuple(child.counts)
                        s, c = child.sum, child.count
                        last = self._hists.get(
                            key, ((0,) * len(counts), 0.0, 0)
                        )
                        if c != last[2] or counts != last[0]:
                            self._hists[key] = (counts, s, c)
                            samples.append((labels, [
                                [a - b for a, b in zip(counts, last[0])],
                                s - last[1], c - last[2],
                            ]))
                if samples:
                    entry: Dict[str, Any] = dict(
                        name=fam.name, kind=fam.kind, help=fam.help,
                        labels=list(fam.labelnames), samples=samples,
                    )
                    if fam.kind == "histogram":
                        entry["uppers"] = list(
                            next(iter(fam.children.values())).uppers
                        )
                    families.append(entry)
        if not families:
            return None
        self.seq += 1
        return dict(v=SNAPSHOT_VERSION, gen=self.gen, seq=self.seq,
                    families=families)


class FleetObs:
    """The supervisor-side sink: snapshot merge, span re-emission, and
    the forensics ring.  One instance per supervisor, shared by its
    :class:`~ggrs_tpu.fleet.proc.ProcShard` proxies; a standalone
    ``ProcShard`` builds its own.

    ``harvest`` is a dedicated registry — merged runner families keep
    their own names/labels plus ``shard``/``backend``, and live beside
    (never colliding with) the supervisor's local instruments; the
    exporters serve both through one
    :class:`~ggrs_tpu.obs.registry.MultiRegistry` view
    (``ShardSupervisor.merged_registry()``).
    """

    def __init__(self, metrics: Optional[Registry] = None, tracer=None,
                 harvest: Optional[Registry] = None,
                 max_forensics: int = MAX_FORENSICS) -> None:
        self.harvest = harvest if harvest is not None else Registry()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.forensics: Deque[Dict[str, Any]] = deque(maxlen=max_forensics)
        # per-match lifecycle timelines (§28): local events recorded by
        # the supervisor + remote events ferried over the same payloads
        # that carry metrics/spans/forensics, clock-offset corrected
        self.timelines = TimelineStore()
        self._applied: Dict[str, Tuple[int, int]] = {}  # shard -> (gen, seq)
        self._span_names: Dict[str, set] = {}           # shard -> names seen
        m = metrics if metrics is not None else Registry(enabled=False)
        self._m_snapshots = m.counter(
            "ggrs_fleet_obs_snapshots_total",
            "runner metric snapshots merged into the fleet harvest",
            labels=("shard",))
        self._m_dups = m.counter(
            "ggrs_fleet_obs_snapshot_dups_total",
            "re-delivered snapshots dropped by the (gen, seq) dedup",
            labels=("shard",))
        self._m_gaps = m.counter(
            "ggrs_fleet_obs_snapshot_gaps_total",
            "sequence gaps observed in a runner's snapshot stream "
            "(an interval of counter deltas was lost)", labels=("shard",))
        self._m_dropped = m.counter(
            "ggrs_fleet_obs_samples_dropped_total",
            "merged samples refused (family shape conflict)",
            labels=("shard", "reason"))
        self._m_spans = m.counter(
            "ggrs_fleet_obs_spans_total",
            "runner trace spans re-emitted into the supervisor tracer",
            labels=("shard",))
        self._m_forensics = m.counter(
            "ggrs_fleet_obs_forensics_total",
            "forensic records (flight dumps, desync reports) ferried "
            "from shards", labels=("shard", "kind"))
        self._m_timeline = m.counter(
            "ggrs_fleet_obs_timeline_events_total",
            "match-lifecycle timeline events merged into the fleet view",
            labels=("shard",))
        self._h_span = self.harvest.histogram(
            "ggrs_fleet_span_seconds",
            "fleet-wide span durations harvested from shard trace rings",
            buckets=SPAN_SECONDS_BUCKETS, labels=("shard", "name"))

    # ------------------------------------------------------------------
    # ingestion (one call per RPC reply / heartbeat payload)
    # ------------------------------------------------------------------

    def ingest(self, shard: str, payload: Optional[Dict[str, Any]], *,
               backend: str = "proc", offset_ns: int = 0) -> None:
        """Fold one piggybacked obs payload (``{"metrics":..,
        "spans":.., "forensics":..}`` — every key optional) into the
        fleet view.  Never raises: a malformed payload must not take the
        serving path down."""
        if not payload:
            return
        # each section fails independently: a malformed span tuple must
        # not discard the forensics ferried in the same payload
        for section, fold in (
            # metrics may be one snapshot or an ordered list (a runner
            # re-sending a previously unsent snapshot before the fresh
            # one — seq order preserved, the dedup handles the rest)
            ("metrics", lambda v: [
                self.merge_snapshot(shard, s, backend=backend)
                for s in (v if isinstance(v, list) else [v])
            ]),
            ("spans", lambda v: self.ingest_spans(
                shard, v, offset_ns=offset_ns)),
            ("forensics", lambda v: self.ingest_forensics(shard, v)),
            ("timeline", lambda v: self.ingest_timeline(
                shard, v, offset_ns=offset_ns)),
        ):
            value = payload.get(section) if isinstance(payload, dict) \
                else None
            if not value:
                continue
            try:
                fold(value)
            except Exception:
                self._m_dropped.labels(shard=str(shard),
                                       reason="ingest-error").inc()

    # ------------------------------------------------------------------
    # metric snapshot merge
    # ------------------------------------------------------------------

    def merge_snapshot(self, shard: str, snap: Dict[str, Any], *,
                       backend: str = "proc") -> bool:
        """Merge one delta snapshot under ``shard``/``backend`` labels.
        Returns False when the snapshot was a duplicate (idempotency)."""
        shard = str(shard)
        gen = int(snap.get("gen", 0))
        seq = int(snap.get("seq", 0))
        last = self._applied.get(shard)
        if last is not None and last[0] == gen:
            if seq <= last[1]:
                self._m_dups.labels(shard=shard).inc()
                return False
            if seq != last[1] + 1:
                self._m_gaps.labels(shard=shard).inc()
        elif seq != 1:
            # first snapshot seen from this (shard, gen) is not the
            # incarnation's first collect: the earlier ones were lost in
            # transit (e.g. a discarded first tick reply) — the startup
            # window is where losses are most likely, count it
            self._m_gaps.labels(shard=shard).inc()
        self._applied[shard] = (gen, seq)
        for fam in snap.get("families", ()):
            self._merge_family(shard, backend, fam)
        self._m_snapshots.labels(shard=shard).inc()
        return True

    def _merge_family(self, shard: str, backend: str,
                      fam: Dict[str, Any]) -> None:
        name = fam["name"]
        kind = fam["kind"]
        labelnames = list(fam.get("labels", ()))
        merged_names = list(labelnames)
        for extra in ("shard", "backend"):
            if extra not in merged_names:
                merged_names.append(extra)
        help_ = fam.get("help", "")
        try:
            if kind == "counter":
                family = self.harvest.counter(name, help_,
                                              labels=merged_names)
            elif kind == "gauge":
                family = self.harvest.gauge(name, help_,
                                            labels=merged_names)
            elif kind == "histogram":
                family = self.harvest.histogram(
                    name, help_, buckets=tuple(fam.get("uppers", ())),
                    labels=merged_names)
            else:
                self._m_dropped.labels(shard=shard, reason="kind").inc()
                return
        except ValueError:
            # two shards (or a shard and an earlier merge) disagree about
            # the family's shape: refuse loudly rather than corrupt
            self._m_dropped.labels(shard=shard, reason="conflict").inc()
            return
        for values, payload in fam.get("samples", ()):
            lv = dict(zip(labelnames, values))
            lv["shard"] = shard
            lv["backend"] = backend
            try:
                child = family.labels(**lv)
            except ValueError:
                self._m_dropped.labels(shard=shard, reason="labels").inc()
                continue
            if kind == "counter":
                child.inc(float(payload))
            elif kind == "gauge":
                child.set(float(payload))
            else:
                deltas, dsum, dcount = payload
                if len(deltas) != len(child.counts):
                    self._m_dropped.labels(shard=shard,
                                           reason="buckets").inc()
                    continue
                for i, d in enumerate(deltas):
                    child.counts[i] += d
                child.sum += dsum
                child.count += dcount

    # ------------------------------------------------------------------
    # cross-process traces
    # ------------------------------------------------------------------

    def ingest_spans(self, shard: str, events: List[Tuple], *,
                     offset_ns: int = 0) -> int:
        """Re-emit a runner's shipped span ring into the supervisor's
        tracer (start times shifted by the RTT-estimated clock offset so
        they nest inside the supervisor's fleet-tick span) and fold the
        durations into ``ggrs_fleet_span_seconds{shard,name}``."""
        shard = str(shard)
        n = self.tracer.import_spans(
            events, offset_ns=offset_ns, extra_args={"shard": shard},
        )
        if n:
            self._m_spans.labels(shard=shard).inc(n)
        names = self._span_names.setdefault(shard, set())
        for ev in events:
            try:
                ph, name, _cat, _t0, dur_ns = ev[:5]
                dur_ns = int(dur_ns)
                name = str(name)
            except Exception:
                continue  # malformed entry: skip, never raise
            if ph != "X":
                continue
            if name not in names:
                if len(names) >= MAX_SPAN_NAMES_PER_SHARD:
                    name = "other"
                else:
                    names.add(name)
            self._h_span.labels(shard=shard, name=name).observe(
                dur_ns / 1e9
            )
        return n

    # ------------------------------------------------------------------
    # forensics ferry
    # ------------------------------------------------------------------

    def ingest_forensics(self, shard: str,
                         items: List[Dict[str, Any]]) -> None:
        """Stash ferried forensic records (bounded ring) and mark each
        arrival on the tracer — the dump now outlives the child that
        produced it."""
        shard = str(shard)
        for item in items:
            if not isinstance(item, dict):
                continue
            record = dict(item)
            record["shard"] = shard
            record.setdefault("received_at", time.time())
            self.forensics.append(record)
            kind = str(record.get("kind", "unknown"))
            self._m_forensics.labels(shard=shard, kind=kind).inc()
            self.tracer.add_instant(
                "fleet.forensic", cat="fleet", shard=shard, kind=kind,
                match=record.get("match"),
            )

    def drain_forensics(self) -> List[Dict[str, Any]]:
        out = list(self.forensics)
        self.forensics.clear()
        return out

    # ------------------------------------------------------------------
    # match-lifecycle timelines (§28)
    # ------------------------------------------------------------------

    def ingest_timeline(self, shard: str, events: List[Dict[str, Any]],
                        *, offset_ns: int = 0) -> int:
        """Fold ferried timeline events into the per-match store (clock
        offset applied, like spans) and re-emit each as a Perfetto
        instant on the supervisor tracer — the cross-host causal view
        drops out of the existing ``chrome_trace`` export."""
        shard = str(shard)
        n = self.timelines.ingest(events, offset_ns=offset_ns)
        if n:
            self._m_timeline.labels(shard=shard).inc(n)
            self.tracer.import_spans(
                timeline_ring_events(events), offset_ns=offset_ns,
                extra_args={"shard": shard},
            )
        return n

    def record_timeline(self, etype: str, match_id: str,
                        **kw: Any) -> Dict[str, Any]:
        """A LOCAL (supervisor-side) timeline emission: stored, and
        re-emitted as a tracer instant in the local clock domain."""
        ev = self.timelines.record(etype, match_id, **kw)
        self.tracer.import_spans(timeline_ring_events([ev]))
        return ev


# ----------------------------------------------------------------------
# read-side helpers (fleet_top, chaos artifacts)
# ----------------------------------------------------------------------


def histogram_quantile(q: float, uppers, cumcounts) -> Optional[float]:
    """Prometheus-style quantile estimate from cumulative bucket counts
    (``uppers`` excludes +Inf; ``cumcounts`` includes it as last entry).
    Linear interpolation within the chosen bucket; the +Inf bucket
    answers with the largest finite upper bound."""
    if not cumcounts:
        return None
    total = cumcounts[-1]
    if total <= 0:
        return None
    rank = q * total
    prev_upper, prev_cum = 0.0, 0
    for upper, cum in zip(uppers, cumcounts):
        if cum >= rank:
            if cum == prev_cum:
                return upper
            return prev_upper + (upper - prev_upper) * (
                (rank - prev_cum) / (cum - prev_cum)
            )
        prev_upper, prev_cum = upper, cum
    return uppers[-1] if uppers else None


def fleet_metrics_digest(supervisor) -> Dict[str, Any]:
    """A compact JSON-safe digest of the merged fleet view — embedded in
    ``scripts/chaos.py`` artifacts so a CI run records what the harvest
    saw: series counts, harvest-plane health, and the headline per-shard
    counters."""
    merged = supervisor.merged_registry()
    obs = supervisor.fleet_obs
    series = 0
    by_family: Dict[str, int] = {}
    for fam in merged.families():
        n = len(fam.children)
        series += n
        by_family[fam.name] = by_family.get(fam.name, 0) + n
    reg = supervisor.metrics

    def _sum(name: str) -> float:
        total = 0.0
        for fam in reg.families():
            if fam.name != name:
                continue
            for _labels, child in fam.samples():
                total += child.value
        return total

    return dict(
        series=series,
        families=len(by_family),
        top_families=dict(sorted(by_family.items(),
                                 key=lambda kv: -kv[1])[:10]),
        snapshots_merged=_sum("ggrs_fleet_obs_snapshots_total"),
        snapshot_dups=_sum("ggrs_fleet_obs_snapshot_dups_total"),
        snapshot_gaps=_sum("ggrs_fleet_obs_snapshot_gaps_total"),
        samples_dropped=_sum("ggrs_fleet_obs_samples_dropped_total"),
        spans_reemitted=_sum("ggrs_fleet_obs_spans_total"),
        forensics_ferried=_sum("ggrs_fleet_obs_forensics_total"),
        forensics_pending=len(obs.forensics),
        timeline_events_merged=_sum(
            "ggrs_fleet_obs_timeline_events_total"),
        timeline_matches=len(obs.timelines),
    )
