"""Dependency-free metrics registry: counters, gauges, fixed-bucket
histograms, label sets.

Design constraints (DESIGN.md §12):

- **Near-zero cost when nothing is watching.**  Instruments are plain
  Python attribute adds under the GIL — no locks on the hot path, no
  timestamps, no allocation per increment.  A registry built with
  ``enabled=False`` hands out shared null instruments whose methods are
  no-ops, so a driver can compile the instrumentation out entirely.
- **Observational only.**  Nothing in this package may perturb session
  behavior: no RNG draws, no clock reads, no socket traffic.  The pool
  chaos suite pins survivors' wire bytes bit-identical with metrics
  enabled vs disabled (tests/test_obs.py).
- **No dependencies.**  Pure stdlib; exporters (Prometheus text, JSON)
  live in ``obs.exporters`` and only read what is registered here.

Instruments follow the Prometheus data model: a *family* has a name, a
type, help text, and a tuple of label names; ``family.labels(k=v, ...)``
returns (creating on first use) the child instrument for one label-value
combination.  A label-free family is itself the instrument.

Process-wide layers (protocol drops, socket send errors, session
rollbacks, executor dispatches) register on the module's ``DEFAULT``
registry at import; pool-scoped metrics take an explicit ``Registry`` so
tests and multi-pool processes can isolate their numbers.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MultiRegistry",
    "Registry",
    "DEFAULT",
    "default_registry",
]

# histogram default: powers of two — rollback depths, queue lengths, and
# latency-in-ticks all live on this scale
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


class Counter:
    """Monotonic counter.  ``inc`` only; decrements are a bug."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self) -> None:
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """A value that can go up and down (slot counts, window occupancy)."""

    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self) -> None:
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = value

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram: per-bucket counts plus sum and count.

    Buckets are upper bounds (a le="+Inf" bucket is implicit); the small
    linear scan beats bisect for the single-digit bucket counts used
    here.
    """

    __slots__ = ("uppers", "counts", "sum", "count")
    kind = "histogram"

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        self.uppers: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts = [0] * (len(self.uppers) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for upper in self.uppers:
            if value <= upper:
                break
            i += 1
        self.counts[i] += 1
        self.sum += value
        self.count += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs, +Inf last — the
        Prometheus exposition shape."""
        out: List[Tuple[float, int]] = []
        running = 0
        for upper, c in zip(self.uppers, self.counts):
            running += c
            out.append((upper, running))
        out.append((float("inf"), running + self.counts[-1]))
        return out


class _Null:
    """Shared no-op instrument for disabled registries: every method of
    every instrument kind, doing nothing."""

    __slots__ = ()
    kind = "null"
    value = 0.0
    sum = 0.0
    count = 0
    uppers: Tuple[float, ...] = ()

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def labels(self, **label_values) -> "_Null":
        return self

    def cumulative(self) -> List[Tuple[float, int]]:
        return []


_NULL = _Null()


class Family:
    """One named metric: its type, help text, label names, and the child
    instrument per label-value combination.  A label-free family proxies
    the single default child so ``registry.counter("x").inc()`` works."""

    __slots__ = ("name", "kind", "help", "labelnames", "children", "_ctor",
                 "_default", "_lock")

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Tuple[str, ...], ctor, lock) -> None:
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        self.children: Dict[Tuple[str, ...], object] = {}
        self._ctor = ctor
        self._lock = lock  # the owning registry's creation lock
        self._default = None
        if not labelnames:
            self._default = ctor()
            self.children[()] = self._default

    def labels(self, **label_values):
        if set(label_values) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {sorted(label_values)}"
            )
        values = tuple(str(label_values[n]) for n in self.labelnames)
        child = self.children.get(values)
        if child is None:
            # lock only the first touch of a label set: two threads racing
            # here must not each build a child (increments on the loser
            # would vanish); steady-state lookups stay lock-free
            with self._lock:
                child = self.children.get(values)
                if child is None:
                    child = self._ctor()
                    self.children[values] = child
        return child

    # label-free convenience: the family is the instrument
    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default.dec(amount)

    def set(self, value: float) -> None:
        self._default.set(value)

    def observe(self, value: float) -> None:
        self._default.observe(value)

    @property
    def value(self) -> float:
        return self._default.value

    @property
    def count(self) -> int:
        return self._default.count

    @property
    def sum(self) -> float:
        return self._default.sum

    def cumulative(self) -> List[Tuple[float, int]]:
        return self._default.cumulative()

    def samples(self) -> Iterable[Tuple[Dict[str, str], object]]:
        for values, child in self.children.items():
            yield dict(zip(self.labelnames, values)), child


class Registry:
    """A metric namespace.  Instrument factories are idempotent: asking
    for an existing name returns the existing family (the kind and label
    names must match, else ``ValueError`` — two call sites disagreeing
    about a metric is a bug worth failing loudly on).

    Threading: creation (families and first-touch label children) is
    lock-guarded; increments deliberately take no lock — ``+=`` spans
    bytecodes, so concurrent writers to the SAME instrument from several
    threads can rarely lose an increment (never corrupt state).  Sessions
    and pools are single-threaded by contract, so each instrument has one
    writer in practice; reads from other threads (exporters) are always
    safe.  ``Registry(enabled=False)`` returns shared null instruments
    from every factory — the off switch for the bit-identical-wire-bytes
    comparisons and for cost-averse drivers.
    """

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._families: Dict[str, Family] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # instrument factories
    # ------------------------------------------------------------------

    def _family(self, name: str, kind: str, help: str,
                labels: Sequence[str], ctor) -> Family:
        labelnames = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind} "
                        f"with labels {fam.labelnames}; asked for {kind} "
                        f"with {labelnames}"
                    )
                return fam
            fam = Family(name, kind, help, labelnames, ctor, self._lock)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()):
        if not self.enabled:
            return _NULL
        return self._family(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()):
        if not self.enabled:
            return _NULL
        return self._family(name, "gauge", help, labels, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  labels: Sequence[str] = ()):
        if not self.enabled:
            return _NULL
        return self._family(
            name, "histogram", help, labels,
            lambda b=tuple(buckets): Histogram(b),
        )

    # ------------------------------------------------------------------
    # reads (exporters, tests, scripts)
    # ------------------------------------------------------------------

    def families(self) -> List[Family]:
        with self._lock:
            return list(self._families.values())

    def value(self, name: str, **label_values) -> Optional[float]:
        """One sample's value, or None when the metric or label set was
        never touched (convenience for tests and summaries — histograms
        report their count)."""
        fam = self._families.get(name)
        if fam is None:
            return None
        values = tuple(str(label_values[n]) for n in fam.labelnames
                       if n in label_values)
        if len(values) != len(fam.labelnames):
            return None
        child = fam.children.get(values)
        if child is None:
            return None
        if fam.kind == "histogram":
            return float(child.count)
        return child.value


class MultiRegistry:
    """A read-only union view over several registries, for the exporters.

    The fleet observability plane (DESIGN.md §18) keeps harvested runner
    metrics in a registry of their own — the same family NAME can then
    carry different label sets locally vs merged (e.g. an unlabeled local
    ``ggrs_pool_ticks_total`` beside the harvested
    ``ggrs_pool_ticks_total{shard,backend}``) without tripping the
    single-registry shape check.  The exporters group families by name,
    so one ``/metrics`` scrape serves the union; writes still go to the
    underlying registries (this view has no factories on purpose).
    """

    __slots__ = ("registries",)

    def __init__(self, *registries) -> None:
        self.registries = tuple(r for r in registries if r is not None)

    def families(self) -> List[Family]:
        out: List[Family] = []
        for reg in self.registries:
            out.extend(reg.families())
        return out

    def value(self, name: str, **label_values) -> Optional[float]:
        for reg in self.registries:
            v = reg.value(name, **label_values)
            if v is not None:
                return v
        return None


# The process-wide registry: cross-cutting layers (protocol, sockets,
# sessions, executors) bind their instruments here at import.  Pools take
# an explicit Registry when isolation matters.
DEFAULT = Registry()


def default_registry() -> Registry:
    return DEFAULT
