"""Desync forensics: turn a checksum mismatch into an actionable report
(DESIGN.md §14).

The reference's desync story ends at an opaque ``DesyncDetected`` event
(p2p_session.rs:904-975): frame, two checksums, an address.  By the time a
human sees it, the interesting state — *which frame first diverged*, what
the session was doing around it, what the journal recorded — is gone.
This module assembles that state, from pieces the obs subsystem already
captures, into a :class:`DesyncReport`:

- **first divergent frame** — a bisection over the two peers' shared
  per-frame checksum history (:func:`first_divergent_frame`).  Determinism
  makes agreement prefix-closed: every frame before the true divergence
  matches, every frame after differs, so the boundary is found in
  O(log n) compares over the retained window.
- **flight-recorder dumps** — the local ring (and the remote's, when the
  driver has both ends, e.g. the chaos harness).
- **journal tail** — the frames around the divergence from an attached
  ``MatchJournal``'s in-memory tail window.
- **trace window** — the active :class:`~ggrs_tpu.obs.trace.Tracer` ring
  as Chrome trace events, so the report carries the tick structure
  leading up to the detection.

Reports are plain data: ``to_dict()`` is JSON-serializable, ``write()``
drops the artifact next to the chaos/CI outputs.  Building one is
observational only — it reads histories and rings, never session state.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, List, Mapping, Optional, Tuple

from ..core.types import NULL_FRAME
from .recorder import ChecksumHistory, FlightRecorder

__all__ = [
    "DesyncReport",
    "build_desync_report",
    "first_divergent_frame",
]

# how many frames of checksum/journal context a report carries each side
# of the divergence
_CONTEXT_FRAMES = 8
# a session keeps at most this many reports (one desync usually re-fires
# every interval until the match is torn down)
MAX_REPORTS = 8


def first_divergent_frame(
    local: Mapping[int, int], remote: Mapping[int, int]
) -> int:
    """The smallest shared frame whose checksums differ, or ``NULL_FRAME``.

    Bisection over the sorted shared frames: a deterministic simulation
    that diverged at frame F agrees on every reported frame < F and
    differs on every reported frame >= F, so "does frame i match?" is
    monotone and the boundary is found in O(log n) compares.  The result
    is validated to actually mismatch, so a non-monotone history (memory
    corruption rather than divergence) can at worst return a later
    divergent frame, never a false one.
    """
    common = sorted(set(local) & set(remote))
    if not common:
        return NULL_FRAME
    lo, hi = 0, len(common) - 1
    first = NULL_FRAME
    while lo <= hi:
        mid = (lo + hi) // 2
        frame = common[mid]
        if local[frame] == remote[frame]:
            lo = mid + 1
        else:
            first = frame
            hi = mid - 1
    return first


class DesyncReport:
    """One desync post-mortem.  ``kind`` is ``"checksum-compare"`` (the
    reference detection path: both histories available, bisection ran) or
    ``"native-fault"`` (a desync-class slot fault in the bank: evidence
    without a local checksum history).  Plain data throughout — safe to
    stash, serialize, and ship long after the session is gone."""

    __slots__ = (
        "kind", "detected_frame", "first_divergent_frame", "addr",
        "local_checksum", "remote_checksum", "checksum_window",
        "recorder_dump", "remote_recorder_dump", "journal_tail",
        "trace_events", "timeline", "detail",
    )

    def __init__(
        self,
        kind: str,
        detected_frame: int,
        first_divergent: int,
        addr: Any = None,
        local_checksum: Optional[int] = None,
        remote_checksum: Optional[int] = None,
        checksum_window: Optional[Dict[str, Dict[int, int]]] = None,
        recorder_dump: str = "",
        journal_tail: Optional[List[Tuple[int, int]]] = None,
        trace_events: Optional[List[Dict[str, Any]]] = None,
        timeline: Optional[List[Dict[str, Any]]] = None,
        detail: str = "",
    ) -> None:
        self.kind = kind
        self.detected_frame = detected_frame
        self.first_divergent_frame = first_divergent
        self.addr = addr
        self.local_checksum = local_checksum
        self.remote_checksum = remote_checksum
        self.checksum_window = checksum_window or {}
        self.recorder_dump = recorder_dump
        # filled by drivers that hold both ends (scripts/chaos.py)
        self.remote_recorder_dump = ""
        self.journal_tail = journal_tail or []
        self.trace_events = trace_events or []
        # the match's §28 lifecycle timeline up to the desync — filled
        # by the shard (its per-match history) or a chaos driver
        self.timeline = timeline or []
        self.detail = detail

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "detected_frame": self.detected_frame,
            "first_divergent_frame": self.first_divergent_frame,
            "addr": None if self.addr is None else repr(self.addr),
            "local_checksum": self.local_checksum,
            "remote_checksum": self.remote_checksum,
            # JSON objects key on strings; keep frames sortable
            "checksum_window": {
                side: {str(f): c for f, c in sorted(window.items())}
                for side, window in self.checksum_window.items()
            },
            "recorder_dump": self.recorder_dump,
            "remote_recorder_dump": self.remote_recorder_dump,
            "journal_tail": [
                {"frame": f, "crc32": c} for f, c in self.journal_tail
            ],
            "trace_events": self.trace_events,
            "timeline": self.timeline,
            "detail": self.detail,
        }

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def write(self, path) -> str:
        path = os.fspath(path)
        with open(path, "w") as f:
            f.write(self.to_json(indent=2))
        return path

    def summary(self) -> str:
        """One-paragraph human digest (chaos output, quarantine logs)."""
        lines = [
            f"DesyncReport[{self.kind}] detected at frame "
            f"{self.detected_frame}, first divergent frame "
            f"{self.first_divergent_frame}"
            + (f", peer {self.addr!r}" if self.addr is not None else ""),
        ]
        if self.local_checksum is not None:
            lines.append(
                f"  checksums at detection: local={self.local_checksum:#x} "
                f"remote={self.remote_checksum:#x}"
            )
        if self.journal_tail:
            lines.append(
                f"  journal tail: {len(self.journal_tail)} frames around "
                f"the divergence"
            )
        if self.trace_events:
            lines.append(f"  trace window: {len(self.trace_events)} spans")
        if self.detail:
            lines.append(f"  {self.detail}")
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"DesyncReport(kind={self.kind!r}, "
            f"detected={self.detected_frame}, "
            f"first_divergent={self.first_divergent_frame})"
        )


def _window_around(
    history: Mapping[int, int], center: int, context: int = _CONTEXT_FRAMES
) -> Dict[int, int]:
    if center == NULL_FRAME:
        return dict(history)
    return {
        f: c for f, c in history.items()
        if center - context <= f <= center + context
    }


def _journal_tail_around(journal, center: int,
                         context: int = _CONTEXT_FRAMES
                         ) -> List[Tuple[int, int]]:
    """(frame, crc32(inputs)) pairs from a MatchJournal's in-memory tail
    window around ``center`` — enough to replay-diff the divergence
    without embedding raw input bytes in the report."""
    if journal is None:
        return []
    tail = getattr(journal, "tail", None)
    if not tail:
        return []
    out = []
    for frame, flags, blob in tail:
        if center == NULL_FRAME or center - context <= frame <= center + context:
            out.append((frame, zlib.crc32(bytes(flags) + bytes(blob))))
    return out


def build_desync_report(
    *,
    kind: str = "checksum-compare",
    detected_frame: int,
    addr: Any = None,
    local_checksum: Optional[int] = None,
    remote_checksum: Optional[int] = None,
    local_history: Optional[Mapping[int, int]] = None,
    remote_history: Optional[Mapping[int, int]] = None,
    recorder: Optional[FlightRecorder] = None,
    journal: Any = None,
    tracer: Any = None,
    timeline: Optional[List[Dict[str, Any]]] = None,
    detail: str = "",
) -> DesyncReport:
    """Assemble a :class:`DesyncReport` from whatever forensic sources the
    caller holds; every source is optional and a missing one simply leaves
    its section empty.  ``local_history``/``remote_history`` accept plain
    frame→checksum mappings or :class:`ChecksumHistory` instances."""
    if isinstance(local_history, ChecksumHistory):
        local_history = local_history.items()
    if isinstance(remote_history, ChecksumHistory):
        remote_history = remote_history.items()
    local_history = dict(local_history or {})
    remote_history = dict(remote_history or {})
    first = first_divergent_frame(local_history, remote_history)
    if first == NULL_FRAME and local_checksum is not None:
        # histories too thin to bisect (e.g. the very first report
        # mismatched): the detection frame is the best known bound
        first = detected_frame
    center = first if first != NULL_FRAME else detected_frame
    trace_events: List[Dict[str, Any]] = []
    if tracer is not None and getattr(tracer, "enabled", False):
        trace_events = tracer.chrome_trace()["traceEvents"]
    return DesyncReport(
        kind=kind,
        detected_frame=detected_frame,
        first_divergent=first,
        addr=addr,
        local_checksum=local_checksum,
        remote_checksum=remote_checksum,
        checksum_window={
            "local": _window_around(local_history, center),
            "remote": _window_around(remote_history, center),
        },
        recorder_dump=recorder.dump(32) if recorder is not None else "",
        journal_tail=_journal_tail_around(journal, center),
        trace_events=trace_events,
        timeline=timeline,
        detail=detail,
    )
