"""Match-lifecycle timelines (DESIGN.md §28).

Through §26 a match's life became genuinely distributed — admitted
through ingress, placed by the ``PlacementService``, live-migrated
cross-host, demoted to lockstep, journal-failed-over on host death —
but every one of those transitions landed in an isolated counter with
no causal ordering.  This module is the shared vocabulary that stitches
them back together:

- a **stable event schema**: each event is one flat JSON-safe dict
  (``TIMELINE_VERSION`` pins the shape) stamped with the origin
  process's monotonic clock, so events ferry over the existing
  harvest plane exactly like forensics do and get clock-offset
  corrected at ingest like spans do (§18);
- a **16-byte trace context** (``TRACE_CTX``: match-id hash u64,
  placement epoch u32, span id u32) that rides real wire bytes — the
  ingress ROUTE_UPDATE tail and the fleet-link RPC payloads — so one
  Perfetto export correlates a match's events across hosts;
- bounded per-match logs (:class:`MatchTimeline`) and a bounded
  per-process store (:class:`TimelineStore`) with LRU match eviction —
  a timeline is forensic context, never an unbounded ledger.

Transport is strictly piggyback: emitters buffer events locally and the
EXISTING heartbeat/tick obs payloads ship them (zero extra RPC round
trips); nothing here touches the native bank (zero extra ctypes
crossings) — both pinned by tests/test_timeline_slo.py.

Event schema (``v`` = TIMELINE_VERSION = 1)::

    {"v": 1, "ev": "ADMIT", "mid": "m3", "ts_ns": 123456789,
     "origin": "h0", "tick": 7, "trace": 0x9a..., "epoch": 2,
     "span": 5, "detail": {...}}

``ts_ns`` is ``time.perf_counter_ns()`` in the ORIGIN process; merging
across processes applies the §18 RTT-estimated offset, merging across
hosts relies on the per-runner offsets both supervisors maintain.
"""

from __future__ import annotations

import struct
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "TIMELINE_VERSION", "TIMELINE_EVENTS",
    "EV_ADMIT", "EV_PLACE", "EV_MIGRATE_BEGIN", "EV_MIGRATE_COMMIT",
    "EV_MIGRATE_ABORT", "EV_ROUTE_FLIP", "EV_DEMOTE_LOCKSTEP",
    "EV_QUARANTINE", "EV_EVICT", "EV_FAILOVER", "EV_DESYNC", "EV_RETIRE",
    "TRACE_CTX_FMT", "TRACE_CTX", "TRACE_CTX_BYTES", "ZERO_TRACE_CTX",
    "match_trace_id", "pack_trace_ctx", "unpack_trace_ctx",
    "timeline_event", "MatchTimeline", "TimelineStore",
    "merge_timelines", "fold_trace_aliases", "timeline_ring_events",
    "format_timeline", "first_occurrence_order",
]

# ----------------------------------------------------------------------
# the stable event vocabulary
# ----------------------------------------------------------------------

TIMELINE_VERSION = 1

EV_ADMIT = "ADMIT"                      # supervisor accepted the match
EV_PLACE = "PLACE"                      # placement chose a host + vport
EV_MIGRATE_BEGIN = "MIGRATE_BEGIN"      # source bundle exported
EV_MIGRATE_COMMIT = "MIGRATE_COMMIT"    # route flipped after adoption
EV_MIGRATE_ABORT = "MIGRATE_ABORT"      # adopt failed; restored on source
EV_ROUTE_FLIP = "ROUTE_FLIP"            # ingress dst actually changed
EV_DEMOTE_LOCKSTEP = "DEMOTE_LOCKSTEP"  # load-shed to the lockstep tier
EV_QUARANTINE = "QUARANTINE"            # slot fault isolated the match
EV_EVICT = "EVICT"                      # bundled off its shard
EV_FAILOVER = "FAILOVER"                # host/shard death; journal resume
EV_DESYNC = "DESYNC"                    # desync forensics captured
EV_RETIRE = "RETIRE"                    # shard retired under the match

TIMELINE_EVENTS: Tuple[str, ...] = (
    EV_ADMIT, EV_PLACE, EV_MIGRATE_BEGIN, EV_MIGRATE_COMMIT,
    EV_MIGRATE_ABORT, EV_ROUTE_FLIP, EV_DEMOTE_LOCKSTEP, EV_QUARANTINE,
    EV_EVICT, EV_FAILOVER, EV_DESYNC, EV_RETIRE,
)

# ----------------------------------------------------------------------
# the 16-byte trace context (§20 layout row: TRACE_CTX_FMT)
# ----------------------------------------------------------------------

# match-id hash u64, placement epoch u32, span id u32 — 16 bytes that
# ride inside the fleet-link RPC payloads and as the ROUTE_UPDATE tail
# (fleet/transport.py mirrors the struct; analysis/layout.py pins both).
TRACE_CTX_FMT = "<QII"
TRACE_CTX = struct.Struct("<QII")  # literal: the §20 layout parser
TRACE_CTX_BYTES = 16
ZERO_TRACE_CTX = b"\x00" * TRACE_CTX_BYTES

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3


def match_trace_id(match_id: str) -> int:
    """A stable u64 for ``match_id`` — FNV-1a over the utf-8 bytes, so
    every host/process derives the SAME id with no coordination (the
    property that lets a Perfetto query join a match's events across
    hosts)."""
    h = _FNV64_OFFSET
    for b in str(match_id).encode("utf-8"):
        h = ((h ^ b) * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def pack_trace_ctx(match_id: str, epoch: int, span: int) -> bytes:
    return TRACE_CTX.pack(match_trace_id(match_id),
                          epoch & 0xFFFFFFFF, span & 0xFFFFFFFF)


def unpack_trace_ctx(data: bytes) -> Tuple[int, int, int]:
    """``(trace, epoch, span)`` from 16 packed bytes; all-zero context
    decodes to ``(0, 0, 0)`` (the "no context" value)."""
    return TRACE_CTX.unpack(data)


# ----------------------------------------------------------------------
# events
# ----------------------------------------------------------------------

def timeline_event(
    etype: str,
    match_id: str,
    *,
    origin: str = "",
    tick: Optional[int] = None,
    epoch: Optional[int] = None,
    span: Optional[int] = None,
    detail: Optional[Dict[str, Any]] = None,
    ts_ns: Optional[int] = None,
) -> Dict[str, Any]:
    """One schema-stable event dict (flat, JSON-safe, picklable)."""
    return {
        "v": TIMELINE_VERSION,
        "ev": etype,
        "mid": str(match_id),
        "ts_ns": time.perf_counter_ns() if ts_ns is None else int(ts_ns),
        "origin": origin,
        "tick": tick,
        "trace": match_trace_id(match_id),
        "epoch": 0 if epoch is None else int(epoch),
        "span": 0 if span is None else int(span),
        "detail": dict(detail) if detail else {},
    }


class MatchTimeline:
    """One match's bounded event log.  Events keep arrival order in
    storage; :meth:`events` returns them time-sorted (with arrival seq
    as the tiebreak so same-nanosecond events stay stable).  Past
    ``capacity`` the OLDEST events are dropped and counted — the tail
    of a match's life (the interesting part during an incident) always
    survives."""

    __slots__ = ("match_id", "capacity", "dropped", "_events", "_seq")

    def __init__(self, match_id: str, capacity: int = 64) -> None:
        self.match_id = str(match_id)
        self.capacity = int(capacity)
        self.dropped = 0
        self._events: List[Tuple[int, int, Dict[str, Any]]] = []
        self._seq = 0

    def __len__(self) -> int:
        return len(self._events)

    def add(self, event: Dict[str, Any]) -> None:
        self._events.append((int(event.get("ts_ns", 0)), self._seq, event))
        self._seq += 1
        if len(self._events) > self.capacity:
            # evict the oldest-by-time entry, not merely oldest-arrived:
            # a late-ferried early event must not push out the live tail
            self._events.remove(min(self._events))
            self.dropped += 1

    def events(self) -> List[Dict[str, Any]]:
        return [e for _, _, e in sorted(self._events,
                                        key=lambda t: (t[0], t[1]))]

    def last_ts_ns(self) -> int:
        return max((ts for ts, _, _ in self._events), default=0)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "match_id": self.match_id,
            "dropped": self.dropped,
            "events": self.events(),
        }


class TimelineStore:
    """A bounded per-process timeline sink: one :class:`MatchTimeline`
    per match, LRU-evicted past ``capacity_matches`` (a retired match's
    timeline ages out naturally once nothing touches it).

    Two write paths mirror the harvest plane's split:

    - :meth:`record` — a LOCAL emission: stamps this process's clock,
      stores the event, and returns it (callers buffer the same dict
      for the piggyback ferry);
    - :meth:`ingest` — REMOTE events off a harvest payload: each
      ``ts_ns`` is shifted by ``offset_ns`` (the §18 RTT-estimated
      clock offset) into the local clock domain before storage.

    Malformed remote events are dropped and counted, never raised — a
    corrupt ferry item must not poison the whole ingest fold.
    """

    def __init__(self, capacity_matches: int = 256,
                 capacity_events: int = 64,
                 clock: Callable[[], int] = time.perf_counter_ns) -> None:
        self.capacity_matches = int(capacity_matches)
        self.capacity_events = int(capacity_events)
        self.clock = clock
        self.malformed = 0
        self._matches: Dict[str, MatchTimeline] = {}
        self._touch = 0
        self._touched: Dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._matches)

    def _get(self, match_id: str) -> MatchTimeline:
        tl = self._matches.get(match_id)
        if tl is None:
            tl = MatchTimeline(match_id, capacity=self.capacity_events)
            self._matches[match_id] = tl
            if len(self._matches) > self.capacity_matches:
                victim = min(self._touched, key=self._touched.get,
                             default=None)
                if victim is not None and victim != match_id:
                    self._matches.pop(victim, None)
                    self._touched.pop(victim, None)
        self._touch += 1
        self._touched[match_id] = self._touch
        return tl

    def record(
        self,
        etype: str,
        match_id: str,
        *,
        origin: str = "",
        tick: Optional[int] = None,
        epoch: Optional[int] = None,
        span: Optional[int] = None,
        detail: Optional[Dict[str, Any]] = None,
        ts_ns: Optional[int] = None,
    ) -> Dict[str, Any]:
        ev = timeline_event(
            etype, match_id, origin=origin, tick=tick, epoch=epoch,
            span=span, detail=detail,
            ts_ns=self.clock() if ts_ns is None else ts_ns,
        )
        self._get(ev["mid"]).add(ev)
        return ev

    def ingest(self, events: Iterable[Dict[str, Any]],
               offset_ns: int = 0) -> int:
        n = 0
        for ev in events:
            try:
                mid = str(ev["mid"])
                shifted = dict(ev)
                shifted["ts_ns"] = int(ev["ts_ns"]) - int(offset_ns)
            except Exception:
                self.malformed += 1
                continue
            self._get(mid).add(shifted)
            n += 1
        return n

    def match_ids(self) -> List[str]:
        return list(self._matches)

    def timeline(self, match_id: str) -> List[Dict[str, Any]]:
        tl = self._matches.get(str(match_id))
        return [] if tl is None else tl.events()

    def to_dict(self) -> Dict[str, List[Dict[str, Any]]]:
        """``{match_id: [events...]}`` — the chaos-artifact embedding."""
        return {mid: tl.events() for mid, tl in self._matches.items()}

    def counts(self) -> Dict[str, int]:
        return {mid: len(tl) for mid, tl in self._matches.items()}


# ----------------------------------------------------------------------
# merging, rendering, re-emission
# ----------------------------------------------------------------------

def merge_timelines(*sources: Any) -> Dict[str, List[Dict[str, Any]]]:
    """Merge stores and/or already-exported ``{mid: [events]}`` dicts
    into one time-sorted per-match view — the cross-host merged
    timeline (two supervisors + the placement plane + ingress)."""
    merged: Dict[str, List[Dict[str, Any]]] = {}
    for src in sources:
        if src is None:
            continue
        exported = src.to_dict() if isinstance(src, TimelineStore) else src
        for mid, events in exported.items():
            merged.setdefault(str(mid), []).extend(events)
    for mid in merged:
        merged[mid].sort(key=lambda e: (e.get("ts_ns", 0),
                                        e.get("span", 0)))
    return merged


def fold_trace_aliases(
    merged: Dict[str, List[Dict[str, Any]]],
) -> Dict[str, List[Dict[str, Any]]]:
    """Fold ``trace:<hex>`` pseudo-matches into the real match whose
    :func:`match_trace_id` equals the hex.  Ingress nodes never learn
    match ids — their ROUTE_FLIP events key on the 16-byte wire trace
    context — so this join is what lands an edge-observed flip inside
    the match's causal chain.  Unresolvable aliases stay keyed as-is."""
    by_trace = {match_trace_id(mid): mid
                for mid in merged if not mid.startswith("trace:")}
    out: Dict[str, List[Dict[str, Any]]] = {}
    for mid, events in merged.items():
        if mid.startswith("trace:"):
            try:
                trace = int(mid.split(":", 1)[1], 16)
            except ValueError:
                trace = -1
            real = by_trace.get(trace)
            if real is not None:
                out.setdefault(real, []).extend(events)
                continue
        out.setdefault(mid, []).extend(events)
    for mid in out:
        out[mid].sort(key=lambda e: (e.get("ts_ns", 0), e.get("span", 0)))
    return out


def timeline_ring_events(
    events: Iterable[Dict[str, Any]],
) -> List[Tuple[str, str, str, int, int, int, Dict[str, Any]]]:
    """Timeline events as raw Tracer ring tuples (instant phase) for
    ``Tracer.import_spans`` — the clock-offset-corrected Perfetto
    re-emission path timelines share with harvested spans (§18)."""
    out = []
    for ev in events:
        args = {
            "mid": ev.get("mid"),
            "origin": ev.get("origin"),
            "tick": ev.get("tick"),
            "trace": f"{ev.get('trace', 0):#018x}",
            "epoch": ev.get("epoch"),
            "span": ev.get("span"),
        }
        detail = ev.get("detail")
        if detail:
            args.update(detail)
        out.append((
            "i", f"timeline.{ev.get('ev', '?')}", "timeline",
            int(ev.get("ts_ns", 0)), 0, 0, args,
        ))
    return out


def format_timeline(events: List[Dict[str, Any]],
                    base_ns: Optional[int] = None) -> List[str]:
    """Human-readable lines, one per event, offsets relative to the
    first event (fleet_top footer, match_timeline.py)."""
    if not events:
        return []
    base = events[0].get("ts_ns", 0) if base_ns is None else base_ns
    lines = []
    for ev in events:
        dt_ms = (ev.get("ts_ns", 0) - base) / 1e6
        bits = [f"+{dt_ms:10.3f}ms", f"{ev.get('ev', '?'):<16}"]
        if ev.get("origin"):
            bits.append(f"origin={ev['origin']}")
        if ev.get("tick") is not None:
            bits.append(f"tick={ev['tick']}")
        if ev.get("epoch"):
            bits.append(f"epoch={ev['epoch']}")
        if ev.get("span"):
            bits.append(f"span={ev['span']}")
        detail = ev.get("detail") or {}
        for k in sorted(detail):
            bits.append(f"{k}={detail[k]}")
        lines.append("  ".join(bits))
    return lines


def first_occurrence_order(events: List[Dict[str, Any]],
                           *etypes: str) -> bool:
    """True when the FIRST occurrence of each named event type appears
    in the given order (and all are present) — the causal-ordering
    acceptance check (ADMIT → MIGRATE_BEGIN → ROUTE_FLIP →
    MIGRATE_COMMIT) chaos legs and tests assert."""
    firsts = []
    for etype in etypes:
        idx = next((i for i, ev in enumerate(events)
                    if ev.get("ev") == etype), None)
        if idx is None:
            return False
        firsts.append(idx)
    return firsts == sorted(firsts) and len(set(firsts)) == len(firsts)
