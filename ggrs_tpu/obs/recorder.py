"""Per-slot flight recorder: a bounded ring of recent events, dumped on
quarantine/eviction for post-mortems (DESIGN.md §12).

Rollback netcode faults are archaeology: by the time a slot quarantines,
the packet or decision that doomed it is several ticks in the past.  The
recorder keeps the last ``capacity`` events per slot — supervision state
changes, faults, rollback decisions, and short digests of recent wire
traffic — so the dump that accompanies a quarantine pinpoints what the
slot was doing, without logging anything for healthy slots.

Events are ``(tick, kind, detail)`` triples.  ``detail`` is usually a
short pre-formatted string; hot-path events (the per-datagram wire
digests) may instead pass a small tuple of scalars, which ``dump``
formats lazily — recording must stay cheap enough to leave on for every
healthy slot.  The recorder never holds references into live session
state, so a dump is safe to stash long after the slot is gone.  Like the
metrics registry, recording is observational only and must never perturb
session behavior.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Tuple

__all__ = ["ChecksumHistory", "FlightRecorder", "EV_STATE", "EV_FAULT",
           "EV_ROLLBACK", "EV_WIRE", "EV_EVICT", "EV_DESYNC"]

# event kinds (free-form strings are allowed too; these are the ones the
# pool emits and the chaos summaries group by)
EV_STATE = "state"        # supervision transition (native -> quarantined...)
EV_FAULT = "fault"        # a SlotFault landed
EV_ROLLBACK = "rollback"  # the slot executed a rollback (load op)
EV_WIRE = "wire"          # outbound datagram digest (crc32, length)
EV_EVICT = "evict"        # eviction attempt / outcome
EV_DESYNC = "desync"      # a checksum mismatch / desync-class fault landed


class ChecksumHistory:
    """Bounded per-frame checksum window (desync forensics, DESIGN.md §14).

    The reference's desync detection compares one frame at a time and
    forgets; the first-divergent-frame bisection needs a *window* of
    recent (frame, checksum) pairs from both ends.  This is that window:
    a dict bounded to the newest ``capacity`` distinct frames.
    """

    __slots__ = ("_map", "_order", "capacity")

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = capacity
        self._map: Dict[int, int] = {}
        self._order: Deque[int] = deque()

    def record(self, frame: int, checksum: int) -> None:
        if frame not in self._map:
            self._order.append(frame)
            while len(self._order) > self.capacity:
                self._map.pop(self._order.popleft(), None)
        self._map[frame] = checksum

    def get(self, frame: int):
        return self._map.get(frame)

    def items(self) -> Dict[int, int]:
        """A snapshot copy, safe to keep after the session is gone."""
        return dict(self._map)

    def frames(self) -> List[int]:
        return sorted(self._map)

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, frame: int) -> bool:
        return frame in self._map


class FlightRecorder:
    """Bounded event ring for one pool slot."""

    __slots__ = ("_ring", "recorded", "checksums", "remote_checksums")

    def __init__(self, capacity: int = 256,
                 checksum_window: int = 256) -> None:
        self._ring: Deque[Tuple[int, str, Any]] = deque(maxlen=capacity)
        self.recorded = 0  # total ever recorded (ring drops the oldest)
        # desync forensics (DESIGN.md §14): the local per-frame checksum
        # window plus one window per remote peer, fed from the desync
        # detection interval traffic — the bisection inputs
        self.checksums = ChecksumHistory(checksum_window)
        self.remote_checksums: Dict[Any, ChecksumHistory] = {}

    def record_checksum(self, frame: int, checksum: int,
                        addr: Any = None) -> None:
        """Record one per-frame checksum: local when ``addr`` is None,
        else into the peer's window."""
        if addr is None:
            self.checksums.record(frame, checksum)
        else:
            hist = self.remote_checksums.get(addr)
            if hist is None:
                hist = ChecksumHistory(self.checksums.capacity)
                self.remote_checksums[addr] = hist
            hist.record(frame, checksum)

    def record(self, tick: int, kind: str, detail: Any = "") -> None:
        self._ring.append((tick, kind, detail))
        self.recorded += 1

    def events(self, last: int = 0) -> List[Tuple[int, str, Any]]:
        """The retained events, oldest first; ``last`` > 0 keeps only the
        newest ``last``."""
        out = list(self._ring)
        if last > 0:
            out = out[-last:]
        return out

    def dump(self, last: int = 32) -> str:
        """Human-readable dump of the newest ``last`` events — the
        post-mortem attached to quarantine/eviction logs and chaos
        summaries."""
        events = self.events(last)
        if not events:
            return "  (no recorded events)"
        dropped = self.recorded - len(self._ring)
        lines = []
        if len(events) < self.recorded:
            lines.append(
                f"  ... {self.recorded - len(events)} earlier events "
                f"({dropped} beyond ring capacity)"
            )
        for tick, kind, detail in events:
            if kind == EV_WIRE and isinstance(detail, tuple):
                ep, length, crc = detail
                detail = f"ep={ep} len={length}B crc={crc:08x}"
            lines.append(f"  t{tick:06d} {kind:<9s} {detail}")
        return "\n".join(lines)

    def __len__(self) -> int:
        return len(self._ring)
