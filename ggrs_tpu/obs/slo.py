"""Frame-budget SLOs and multi-window burn rates (DESIGN.md §28).

The 16.7 ms frame budget is the acceptance metric of every bench round,
but until §28 nothing tracked budget COMPLIANCE at serve time.  This
module closes that gap in two halves, split the same way the harvest
plane is (§18):

- **shard side** (:class:`ShardSloMeter`): per-tick budget-compliance
  counters — ``ggrs_slo_ticks_total{tier}`` /
  ``ggrs_slo_breaches_total{tier}`` — fed from measurements the tick
  already makes (the shard's wall-clock tick timer over the native
  phase timers; the lockstep tier's confirmed-lag from its
  Python-resident sessions).  The counters ride the EXISTING registry
  harvest: zero extra RPC round trips, zero extra ctypes crossings.
- **supervisor side** (:class:`BurnRateEngine`): windowed burn rates
  over the merged counters.  Burn rate = (windowed error rate) /
  (error budget); a burn of 1.0 exactly spends the budget at the
  target, 14.4 spends a month's 99.9% budget in ~5 m.  Two windows on
  the FLEET clock (ticks, not wall time — deterministic under test and
  under chaos clock control) must BOTH burn hot before escalation, the
  classic multi-window guard against paging on a blip.

Escalation is wired into the existing health plane: a ``critical``
verdict flips ``supervisor.healthz()["ok"]`` to False, which the
``MetricsServer`` dict-health path already answers with a 503 — the
SLO plane pages through the door the fleet already watches.  ROADMAP
item 5 note: these burn rates are the designated autoscaling trigger
input.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "TIER_ROLLBACK", "TIER_LOCKSTEP", "SLO_TIERS",
    "LEVEL_OK", "LEVEL_WARN", "LEVEL_CRITICAL", "SLO_LEVELS",
    "SloPolicy", "ShardSloMeter", "BurnRateEngine",
]

TIER_ROLLBACK = "rollback"
TIER_LOCKSTEP = "lockstep"
SLO_TIERS = (TIER_ROLLBACK, TIER_LOCKSTEP)

LEVEL_OK = "ok"
LEVEL_WARN = "warn"
LEVEL_CRITICAL = "critical"
SLO_LEVELS = (LEVEL_OK, LEVEL_WARN, LEVEL_CRITICAL)
_LEVEL_RANK = {LEVEL_OK: 0, LEVEL_WARN: 1, LEVEL_CRITICAL: 2}


class SloPolicy:
    """Per-tier targets and burn thresholds.

    - rollback tier: a tick is compliant when it lands inside the frame
      budget (default 16.7 ms — one 60 Hz frame);
    - lockstep tier: a tick is compliant when the worst confirmed-lag
      across lockstep slots stays within ``lockstep_lag_frames``
      (a lockstep session's only latency observable — it never
      predicts, it waits);
    - ``windows`` are (name, fleet-ticks) pairs, defaults sized for
      5 m / 1 h at 60 Hz.  Both must burn past a threshold to change
      the verdict.
    """

    __slots__ = ("rollback_budget_ms", "lockstep_lag_frames", "target",
                 "windows", "warn_burn", "critical_burn")

    def __init__(
        self,
        rollback_budget_ms: float = 16.7,
        lockstep_lag_frames: int = 4,
        target: float = 0.999,
        windows: Tuple[Tuple[str, int], ...] = (("5m", 18000),
                                                ("1h", 216000)),
        warn_burn: float = 6.0,
        critical_burn: float = 14.4,
    ) -> None:
        self.rollback_budget_ms = float(rollback_budget_ms)
        self.lockstep_lag_frames = int(lockstep_lag_frames)
        self.target = float(target)
        self.windows = tuple((str(n), int(w)) for n, w in windows)
        self.warn_burn = float(warn_burn)
        self.critical_burn = float(critical_burn)

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.target)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rollback_budget_ms": self.rollback_budget_ms,
            "lockstep_lag_frames": self.lockstep_lag_frames,
            "target": self.target,
            "windows": {n: w for n, w in self.windows},
            "warn_burn": self.warn_burn,
            "critical_burn": self.critical_burn,
        }


class ShardSloMeter:
    """The shard-resident half: two counters per tier, prebound label
    children so the per-tick hot path is two attribute loads and an
    ``+=`` (the §23 zero-allocation discipline)."""

    __slots__ = ("policy", "_rb_ticks", "_rb_breaches",
                 "_ls_ticks", "_ls_breaches")

    def __init__(self, metrics, policy: Optional[SloPolicy] = None) -> None:
        self.policy = policy if policy is not None else SloPolicy()
        c_ticks = metrics.counter(
            "ggrs_slo_ticks_total",
            "ticks observed for slo budget compliance, by tier",
            labels=("tier",))
        c_breaches = metrics.counter(
            "ggrs_slo_breaches_total",
            "ticks that breached the tier's slo budget",
            labels=("tier",))
        self._rb_ticks = c_ticks.labels(tier=TIER_ROLLBACK)
        self._rb_breaches = c_breaches.labels(tier=TIER_ROLLBACK)
        self._ls_ticks = c_ticks.labels(tier=TIER_LOCKSTEP)
        self._ls_breaches = c_breaches.labels(tier=TIER_LOCKSTEP)

    def observe_rollback(self, tick_ms: float) -> bool:
        """One rollback-tier tick; returns True when compliant."""
        ok = tick_ms <= self.policy.rollback_budget_ms
        self._rb_ticks.inc()
        if not ok:
            self._rb_breaches.inc()
        return ok

    def observe_lockstep(self, worst_lag_frames: int) -> bool:
        """One lockstep-tier tick (worst confirmed-lag across the
        shard's lockstep slots); returns True when compliant."""
        ok = worst_lag_frames <= self.policy.lockstep_lag_frames
        self._ls_ticks.inc()
        if not ok:
            self._ls_breaches.inc()
        return ok


def _slo_totals(registry) -> Dict[str, Tuple[float, float]]:
    """Sum the two ``ggrs_slo_*`` counter families across every sample
    (harvested shard counters carry extra shard/backend labels; the
    tier label is the grouping key), from a ``Registry`` or a merged
    ``MultiRegistry`` view."""
    ticks: Dict[str, float] = {}
    breaches: Dict[str, float] = {}
    for fam in registry.families():
        if fam.name == "ggrs_slo_ticks_total":
            dest = ticks
        elif fam.name == "ggrs_slo_breaches_total":
            dest = breaches
        else:
            continue
        for labels, child in fam.samples():
            tier = labels.get("tier", TIER_ROLLBACK)
            dest[tier] = dest.get(tier, 0.0) + child.value
    return {
        tier: (ticks.get(tier, 0.0), breaches.get(tier, 0.0))
        for tier in set(ticks) | set(breaches)
    }


class BurnRateEngine:
    """The supervisor-resident half: per fleet tick, snapshot the merged
    cumulative counters and derive windowed burn rates + the verdict.

    Snapshots are kept on a pruned ring sized by the longest window —
    memory is O(windowed ticks), not O(uptime).  The exported family:

    - ``ggrs_slo_burn_rate{tier,window}`` (gauge)
    - ``ggrs_slo_level`` (gauge: 0 ok / 1 warn / 2 critical)
    - ``ggrs_slo_escalations_total`` (counter: transitions INTO
      critical — the page count, not the page duration)
    """

    def __init__(self, metrics=None,
                 policy: Optional[SloPolicy] = None) -> None:
        self.policy = policy if policy is not None else SloPolicy()
        self._snaps: List[Tuple[int, Dict[str, Tuple[float, float]]]] = []
        self._verdict: Dict[str, Any] = {
            "ok": True, "level": LEVEL_OK, "tiers": {},
            "policy": self.policy.as_dict(),
        }
        self._g_burn = self._g_level = self._c_escalations = None
        if metrics is not None:
            self._g_burn = metrics.gauge(
                "ggrs_slo_burn_rate",
                "windowed error-budget burn rate, by tier and window",
                labels=("tier", "window"))
            self._g_level = metrics.gauge(
                "ggrs_slo_level",
                "slo verdict level: 0 ok, 1 warn, 2 critical")
            self._c_escalations = metrics.counter(
                "ggrs_slo_escalations_total",
                "slo verdict transitions into critical")

    # ------------------------------------------------------------------

    def _reference(self, fleet_tick: int, window_ticks: int,
                   ) -> Tuple[int, Dict[str, Tuple[float, float]]]:
        """The snapshot to delta against for a window ending now: the
        newest snapshot at or before the window start, else the oldest
        held (a partial window while history warms up)."""
        start = fleet_tick - window_ticks
        ref = self._snaps[0]
        for snap in self._snaps:
            if snap[0] <= start:
                ref = snap
            else:
                break
        return ref

    def update(self, fleet_tick: int, registry) -> Dict[str, Any]:
        totals = _slo_totals(registry)
        self._snaps.append((int(fleet_tick), totals))
        # prune: keep one snapshot at/before the longest window start
        horizon = int(fleet_tick) - max(w for _, w in self.policy.windows)
        while len(self._snaps) > 2 and self._snaps[1][0] <= horizon:
            self._snaps.pop(0)

        tiers: Dict[str, Any] = {}
        level = LEVEL_OK
        for tier, (n_ticks, n_breaches) in sorted(totals.items()):
            burns: Dict[str, float] = {}
            for wname, wticks in self.policy.windows:
                _, ref = self._reference(fleet_tick, wticks)
                ref_ticks, ref_breaches = ref.get(tier, (0.0, 0.0))
                d_ticks = n_ticks - ref_ticks
                d_breaches = n_breaches - ref_breaches
                rate = (d_breaches / d_ticks) if d_ticks > 0 else 0.0
                burn = rate / self.policy.error_budget
                burns[wname] = burn
                if self._g_burn is not None:
                    self._g_burn.labels(tier=tier, window=wname).set(burn)
            # multi-window rule: EVERY window must burn past a threshold
            floor = min(burns.values()) if burns else 0.0
            if floor >= self.policy.critical_burn:
                tier_level = LEVEL_CRITICAL
            elif floor >= self.policy.warn_burn:
                tier_level = LEVEL_WARN
            else:
                tier_level = LEVEL_OK
            if _LEVEL_RANK[tier_level] > _LEVEL_RANK[level]:
                level = tier_level
            tiers[tier] = {
                "ticks": n_ticks, "breaches": n_breaches,
                "burn": burns, "level": tier_level,
            }
        prev = self._verdict.get("level", LEVEL_OK)
        if level == LEVEL_CRITICAL and prev != LEVEL_CRITICAL:
            if self._c_escalations is not None:
                self._c_escalations.inc()
        if self._g_level is not None:
            self._g_level.set(_LEVEL_RANK[level])
        self._verdict = {
            "ok": level != LEVEL_CRITICAL,
            "level": level,
            "tiers": tiers,
            "policy": self.policy.as_dict(),
        }
        return self._verdict

    def verdict(self) -> Dict[str, Any]:
        """The last computed verdict (healthz embeds this; ``ok`` False
        means the multi-window critical burn tripped and ``/healthz``
        should answer 503)."""
        return self._verdict
