"""Low-overhead span tracer with Chrome/Perfetto trace-event export
(DESIGN.md §14).

The metrics registry (§12) answers *how much* the pool does per tick; this
module answers *where the time goes* inside one tick.  A :class:`Tracer`
keeps a bounded ring of completed spans — tick → crossing → slot nesting on
the Python side, plus the native bank's per-phase timings re-emitted as
child spans of the crossing — and exports the window in the Chrome
trace-event JSON format, so one ``tracer.write(path)`` produces a file that
loads directly in ``chrome://tracing`` or https://ui.perfetto.dev.

Design constraints, shared with the rest of ``ggrs_tpu.obs``:

- **Compiles out.**  ``Tracer(enabled=False)`` hands back a shared no-op
  context manager from ``span()`` and drops every ``add_*`` immediately —
  no clock reads, no allocation, nothing on the ring.  The chaos suite
  pins wire bytes bit-identical with tracing on vs off
  (tests/test_trace.py), and the bank's crossing count is pinned
  unchanged: the native timing tail rides the EXISTING tick output, so
  tracing adds zero extra ctypes crossings.
- **Monotonic clocks only.**  Spans are stamped with
  ``time.perf_counter_ns`` (never the session clock, never wall time), so
  tracing cannot perturb timer-driven protocol behavior.
- **Bounded.**  The ring drops the oldest span; ``dropped`` counts what
  fell off.  A flight-recorder-sized window (default 4096 spans) is the
  point: the *recent* tick structure, attached to desync reports and the
  ``/trace`` endpoint, not an unbounded profile.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

__all__ = ["Tracer", "NULL_TRACER", "chrome_trace_events",
           "validate_chrome_trace"]

# event phases on the ring (Chrome trace-event "ph" values)
_PH_COMPLETE = "X"
_PH_INSTANT = "i"


class _NullSpan:
    """Shared no-op context manager: the whole cost of a disabled span is
    one attribute load and one method call returning this singleton."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]) -> None:
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        t0 = self._t0
        self._tracer._append(
            _PH_COMPLETE, self._name, self._cat, t0,
            time.perf_counter_ns() - t0, self._args,
        )
        return False


class Tracer:
    """Bounded ring of trace spans with Chrome trace-event export.

    Usage::

        tracer = Tracer()                      # or Tracer(enabled=False)
        with tracer.span("pool.tick", cat="py", tick=7):
            with tracer.span("bank.crossing", cat="native"):
                ...
        tracer.write("pool.trace.json")        # chrome://tracing loads this

    Spans nest naturally through ``with`` nesting (Chrome infers the tree
    from containment on one thread's timeline).  ``add_complete`` records a
    span from explicit timestamps — how the native bank's per-phase
    timings, measured inside the tick crossing, are re-emitted as child
    spans of the crossing without any Python-side context manager.
    """

    def __init__(self, capacity: int = 4096, enabled: bool = True) -> None:
        self.enabled = enabled
        self.capacity = capacity
        # (ph, name, cat, start_ns, dur_ns, tid, args)
        self._ring: Deque[Tuple] = deque(maxlen=capacity)
        self.recorded = 0  # total ever recorded (ring drops the oldest)

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------

    def span(self, name: str, cat: str = "py", **args):
        """Context manager timing one span; no-op when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def add_complete(self, name: str, start_ns: int, dur_ns: int,
                     cat: str = "native",
                     args: Optional[Dict[str, Any]] = None) -> None:
        """Record a complete span from explicit monotonic-ns timestamps
        (the native timing tail's re-emission path)."""
        if self.enabled:
            self._append(_PH_COMPLETE, name, cat, start_ns, dur_ns, args)

    def add_instant(self, name: str, cat: str = "py", **args) -> None:
        """Record an instant event (faults, desyncs, evictions)."""
        if self.enabled:
            self._append(_PH_INSTANT, name, cat, time.perf_counter_ns(), 0,
                         args or None)

    def now_ns(self) -> int:
        """The tracer's clock (monotonic ns) — for callers timing a region
        by hand around a ctypes call."""
        return time.perf_counter_ns()

    def import_spans(self, events: List[Tuple], *, offset_ns: int = 0,
                     extra_args: Optional[Dict[str, Any]] = None) -> int:
        """Re-emit raw ring events shipped from ANOTHER process
        (DESIGN.md §18): each event's start time is shifted by
        ``offset_ns`` (the RTT-estimated clock offset between the two
        processes' ``perf_counter`` clocks) and recorded on THIS thread's
        track, so a runner's spans nest inside the supervisor span that
        covers the RPC which carried them.  ``extra_args`` (e.g.
        ``{"shard": "s1"}``) is folded into every event's args; the
        source thread id is preserved as ``src_tid``.  Returns the number
        of events imported; malformed entries are skipped, never raised.
        """
        if not self.enabled or not events:
            return 0
        n = 0
        for ev in events:
            try:
                ph, name, cat, start_ns, dur_ns, src_tid, args = ev
                start_ns = int(start_ns) - offset_ns
                dur_ns = int(dur_ns)
            except Exception:
                continue
            a: Dict[str, Any] = dict(args) if args else {}
            if extra_args:
                a.update(extra_args)
            a.setdefault("src_tid", src_tid)
            self._append(ph, str(name), str(cat), start_ns, dur_ns, a)
            n += 1
        return n

    def _append(self, ph: str, name: str, cat: str, start_ns: int,
                dur_ns: int, args: Optional[Dict[str, Any]]) -> None:
        self._ring.append(
            (ph, name, cat, start_ns, dur_ns, threading.get_ident(), args)
        )
        self.recorded += 1

    # ------------------------------------------------------------------
    # reads / export
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        return self.recorded - len(self._ring)

    def events(self, last: int = 0) -> List[Tuple]:
        """The retained raw events, oldest first; ``last`` > 0 keeps only
        the newest ``last``."""
        out = list(self._ring)
        if last > 0:
            out = out[-last:]
        return out

    def clear(self) -> None:
        self._ring.clear()

    def chrome_trace(self, last: int = 0) -> Dict[str, Any]:
        """The current window as a Chrome trace-event JSON object
        (``{"traceEvents": [...]}``) — loads in ``chrome://tracing`` and
        Perfetto.  Timestamps are microseconds relative to the oldest
        retained event."""
        events = self.events(last)
        return {
            "traceEvents": chrome_trace_events(events),
            "displayTimeUnit": "ms",
        }

    def write(self, path) -> str:
        """Serialize :meth:`chrome_trace` to ``path``; returns the path."""
        path = os.fspath(path)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name totals over the window: count and total/max
        duration in microseconds — the quick textual digest chaos runs
        print alongside the full export."""
        out: Dict[str, Dict[str, float]] = {}
        for ph, name, _cat, _t0, dur, _tid, _args in self._ring:
            if ph != _PH_COMPLETE:
                continue
            s = out.setdefault(name, {"count": 0, "total_us": 0.0,
                                      "max_us": 0.0})
            s["count"] += 1
            us = dur / 1000.0
            s["total_us"] += us
            if us > s["max_us"]:
                s["max_us"] = us
        return out


def chrome_trace_events(events: List[Tuple]) -> List[Dict[str, Any]]:
    """Convert raw ring events to Chrome trace-event dicts.  The time base
    is shifted so the oldest event sits at ts=0 (chrome://tracing dislikes
    raw multi-hour perf_counter offsets)."""
    if not events:
        return []
    base = min(e[3] for e in events)
    out: List[Dict[str, Any]] = []
    for ph, name, cat, start_ns, dur_ns, tid, args in events:
        ev: Dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": ph,
            "ts": (start_ns - base) / 1000.0,
            "pid": 1,
            "tid": tid & 0xFFFF,
        }
        if ph == _PH_COMPLETE:
            ev["dur"] = dur_ns / 1000.0
        else:
            ev["s"] = "t"  # instant scope: thread
        if args:
            ev["args"] = dict(args)
        out.append(ev)
    return out


def validate_chrome_trace(trace: Any, eps_us: float = 0.001) -> List[str]:
    """Schema validation for a Chrome/Perfetto trace-event export: the
    checks a load into ui.perfetto.dev would fail on, run in CI instead
    (DESIGN.md §18).  Returns a list of problems (empty = valid):

    - the object is ``{"traceEvents": [...]}`` and JSON-serializable;
    - every event has a string ``name``, a known ``ph``, numeric
      finite ``ts >= 0``, and ``pid``/``tid``;
    - complete ("X") events carry ``dur >= 0``;
    - per (pid, tid) track, complete events properly nest: sorted by
      start time, any two spans are either disjoint or one contains the
      other — partial overlap on one track is how a bad clock offset or
      a torn import shows up.

    ``eps_us`` is the nesting slack in microseconds: keep the tight
    default for single-process traces (one clock, exact containment);
    fleet traces carrying imported cross-process spans should allow the
    residual clock-offset error (tens of µs).
    """
    problems: List[str] = []
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        return ["not a {'traceEvents': [...]} object"]
    events = trace["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serializable: {e}")
    known_ph = {"X", "i", "I", "B", "E", "M", "b", "e", "n", "s", "t", "f"}
    tracks: Dict[Tuple, List[Tuple[float, float, str]]] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        name = ev.get("name")
        if not isinstance(name, str) or not name:
            problems.append(f"event {i}: missing/empty name")
            name = "?"
        ph = ev.get("ph")
        if ph not in known_ph:
            problems.append(f"event {i} ({name}): unknown ph {ph!r}")
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts != ts or ts < 0 \
                or ts in (float("inf"), float("-inf")):
            problems.append(f"event {i} ({name}): bad ts {ts!r}")
            continue
        if "pid" not in ev or "tid" not in ev:
            problems.append(f"event {i} ({name}): missing pid/tid")
            continue
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur != dur or dur < 0:
                problems.append(f"event {i} ({name}): bad dur {dur!r}")
                continue
            tracks.setdefault((ev["pid"], ev["tid"]), []).append(
                (float(ts), float(dur), name)
            )
    # nesting per track: the epsilon absorbs ns→µs rounding (default)
    # or residual cross-process offset error (caller-raised)
    eps = eps_us
    for track, spans in tracks.items():
        spans.sort(key=lambda s: (s[0], -s[1]))
        stack: List[Tuple[float, str]] = []  # (end_ts, name)
        for ts, dur, name in spans:
            end = ts + dur
            while stack and ts >= stack[-1][0] - eps:
                stack.pop()
            if stack and end > stack[-1][0] + eps:
                problems.append(
                    f"track {track}: span {name!r} [{ts:.3f}, {end:.3f}] "
                    f"partially overlaps enclosing {stack[-1][1]!r} "
                    f"(ends {stack[-1][0]:.3f})"
                )
                continue
            stack.append((end, name))
    return problems


# The shared disabled tracer: sessions and pools default to this so the
# hot path pays one attribute load + one no-op call when nobody is tracing.
NULL_TRACER = Tracer(capacity=1, enabled=False)
