"""Massed P2P hosting: fulfill MANY live sessions' request lists in ONE
device dispatch per tick.

The reference binds one rollback session to one process — a server hosting
hundreds of matches runs hundreds of processes, each paying its own
per-request state churn (/root/reference/src/sessions/p2p_session.rs:254-265).
``ops.DeviceRequestExecutor`` already moves a single session's save/load/
advance onto HBM, but a pool of N executors still costs N device dispatches
per tick — on a tunneled TPU the dispatch overhead, not the game, is the
bill.  This module batches the *fulfillment*: B independent host sessions
(P2P, SyncTest, Spectator — anything that emits the reference's request
grammar) hand their per-tick request lists to one ``BatchedRequestExecutor``,
which compiles a single uniform tick program over ``[B, ...]`` state and
dispatches it once for the whole pool.

Uniformity is the TPU trade: every session's tick is normalized to the same
fixed-shape descriptor —

    [pre-save*] [load [post-load-save]*] (advance, save?) * <= max_burst

— padded with masked no-ops, so heterogeneous ticks (one session rolling
back 8 frames, another advancing once, a third skipping on prediction
threshold) are ONE program with per-session predication, not B programs.
Grammar parity: the same ``Save | Load (Adv Save?)* | Adv`` request shapes
``ops.DeviceRequestExecutor`` executes (/root/reference/src/lib.rs:170-195).

Saved states live in per-session device rings ``[B, R, ...]`` tagged with
frame numbers and (optionally) 4-lane digests; ``GameStateCell``s are
fulfilled with lazy slot references and lazy checksums, so desync detection
and user ``cell.load()`` work unchanged while the live path performs ZERO
device→host reads.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.types import (
    AdvanceFrame,
    Frame,
    GgrsRequest,
    LoadGameState,
    SaveGameState,
)
from ..obs.registry import default_registry
from ..obs.trace import NULL_TRACER
from ..ops.checksum import CHECKSUM_LANES, checksum_device, checksum_to_u128

# obs (DESIGN.md §12): device-dispatch accounting for the pooled executor —
# process-wide counters, observational only
_OBS_DISPATCHES = default_registry().counter(
    "ggrs_executor_dispatches_total",
    "pooled tick programs dispatched to the device",
)
_OBS_EMPTY_TICKS = default_registry().counter(
    "ggrs_executor_empty_ticks_total",
    "run() calls where every session's request list was empty (no dispatch)",
)
_OBS_ROLLBACK_LOADS = default_registry().counter(
    "ggrs_executor_rollback_loads_total",
    "sessions that carried a LoadGameState (rollback) into a pooled tick",
)
_OBS_BURST_DEPTH = default_registry().histogram(
    "ggrs_executor_burst_depth_frames",
    "deepest per-session advance burst (replay depth) per dispatched tick",
    buckets=(1, 2, 4, 8, 16, 32),
)


def _tree_where(pred: jax.Array, a: Any, b: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(pred, x, y), a, b
    )


class _BatchSlotRef:
    """What a fulfilled SaveGameState cell holds: a reference into the pool's
    device ring.  ``load()``/``data()`` on the cell returns this; materialize
    via the owning executor (a device gather + transfer — diagnostics only,
    the live path never calls it)."""

    __slots__ = ("owner", "index", "frame")

    def __init__(self, owner: "BatchedRequestExecutor", index: int, frame: Frame):
        self.owner = owner
        self.index = index
        self.frame = frame

    def materialize(self) -> Any:
        return self.owner.ring_state(self.index, self.frame)

    def __repr__(self) -> str:  # pragma: no cover
        return f"_BatchSlotRef(session={self.index}, frame={self.frame})"


class _LazyBatchChecksum:
    """Lazy u128 checksum handle backed by the pool's digest ring; satisfies
    ``GameStateCell.save``'s ``materialize()`` protocol so the desync
    exchange only pays a device read for frames it actually reports."""

    __slots__ = ("_owner", "_index", "_frame", "_value")

    def __init__(self, owner: "BatchedRequestExecutor", index: int, frame: Frame):
        self._owner = owner
        self._index = index
        self._frame = frame
        self._value: Optional[int] = None

    def materialize(self) -> int:
        if self._value is None:
            self._value = self._owner.ring_checksum(self._index, self._frame)
        return self._value


class BatchedRequestExecutor:
    """Fulfills B sessions' GgrsRequest lists with one dispatch per tick.

    ``advance``         pure JAX ``(state, inputs_array) -> state`` (unbatched;
                        the pool vmaps it).
    ``init_state``      one session's initial state pytree.
    ``inputs_to_array`` maps a request's ``[(input, status), ...]`` to the
                        array ``advance`` consumes — same contract as
                        ``ops.DeviceRequestExecutor``.
    ``batch_size``      B, the number of pooled sessions (index 0..B-1).
    ``ring_length``     saved-state slots per session; must exceed the
                        sessions' ``max_prediction`` (the reference keeps
                        ``max_prediction + 1`` cells, sync_layer.rs:144-166).
    ``max_burst``       most advances one tick can carry (rollback resims +
                        the live advance): ``max_prediction + 1`` for the
                        stock P2P session.
    ``mesh``            optional ``jax.sharding.Mesh``: shard the session
                        axis over every mesh axis (the device count must
                        divide ``batch_size``) so one pool spans chips — sessions
                        are independent, so the tick program needs no
                        collectives and scales linearly over ICI-attached
                        devices.  Descriptor arrays are built host-side and
                        split per-shard by ``shard_map``.
    ``raw_inputs_to_array``  optional bulk twin of ``inputs_to_array`` for
                        the descriptor plane (DESIGN.md §21): called as
                        ``raw(blobs, statuses)`` with the ENCODED input
                        bytes ``[k, players, input_size]`` (u8) and status
                        codes ``[k, players]`` (u8) of k advances, it must
                        return the ``[k, ...]`` array ``advance`` consumes —
                        the vectorized equivalent of decoding each blob and
                        calling ``inputs_to_array`` per slot.  With it set,
                        a ``HostSessionPool`` RequestPlan's quiet slots are
                        consumed as flat NumPy columns: zero ``GgrsRequest``
                        objects, zero per-slot ``input_decode`` calls.
                        Without it, plans still work (per-slot
                        materialization — the reference semantics).
    """

    def __init__(
        self,
        advance: Callable[[Any, Any], Any],
        init_state: Any,
        inputs_to_array: Callable[[Sequence[Tuple[Any, Any]]], np.ndarray],
        batch_size: int,
        ring_length: int,
        max_burst: int,
        with_checksums: bool = True,
        mesh: Optional["jax.sharding.Mesh"] = None,
        raw_inputs_to_array: Optional[
            Callable[[np.ndarray, np.ndarray], np.ndarray]
        ] = None,
    ) -> None:
        assert batch_size >= 1 and ring_length >= 2 and max_burst >= 1
        self.batch_size = batch_size
        self.ring_length = ring_length
        self.max_burst = max_burst
        self._inputs_to_array = inputs_to_array
        self._raw_inputs = raw_inputs_to_array
        self._with_checksums = with_checksums
        # descriptor plane (§21): per-(session, ring slot) pooled
        # _BatchSlotRef/_LazyBatchChecksum pairs so the fast path's cell
        # fulfillment allocates nothing at steady state.  (Descriptor
        # buffers are deliberately NOT pooled — see _reset_desc.)
        self._ref_rings: List[Optional[List[Any]]] = [None] * batch_size
        self.mesh = mesh
        if mesh is not None:
            assert batch_size % mesh.devices.size == 0, (
                f"batch_size {batch_size} must divide evenly over "
                f"{mesh.devices.size} mesh devices"
            )

        from ..ops.ring import DeviceStateRing

        state0 = jax.tree_util.tree_map(jnp.asarray, init_state)
        B, R = batch_size, ring_length
        self._ring = DeviceStateRing(R)
        ring0 = self._ring.init(state0)
        self._carry: Dict[str, Any] = {
            "live": jax.tree_util.tree_map(
                lambda l: jnp.broadcast_to(l[None, ...], (B,) + l.shape), state0
            ),
            # one DeviceStateRing (states / checksums / frames) per session,
            # stacked on a leading B axis; its frame tags back the host-side
            # accessors and the _parse-time ring-capacity guard
            "ring": jax.tree_util.tree_map(
                lambda l: jnp.broadcast_to(l[None, ...], (B,) + l.shape).copy(),
                ring0,
            ),
        }
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._sharding = NamedSharding(
                mesh, PartitionSpec(tuple(mesh.axis_names))
            )
            self._carry = jax.tree_util.tree_map(
                lambda l: jax.device_put(l, self._sharding), self._carry
            )
        self._input_dtype: Optional[np.dtype] = None
        self._input_shape: Optional[Tuple[int, ...]] = None
        # tracing (DESIGN.md §14): device dispatch + fence spans; assign a
        # live Tracer (or let HostedPool share the host pool's) to light up
        self.tracer = NULL_TRACER
        # set on a failed run(): once a tick aborts mid-parse, fulfilled
        # cells reference slots that were never written — every later use
        # must fail loudly instead of serving stale state
        self._invalid: Optional[str] = None
        # host shadow of the ring frame tags: loud failure at _parse time if
        # a session rolls back past ring_length (device aliasing is silent)
        self._host_frames = np.full((B, R), -1, np.int64)

        dring = self._ring
        zero_cs = jnp.zeros((CHECKSUM_LANES,), jnp.uint32)

        def session_tick(
            live: Any,
            ring: Any,
            pre_save: jax.Array,
            pre_frame: jax.Array,
            do_load: jax.Array,
            load_frame: jax.Array,
            postload_save: jax.Array,
            postload_frame: jax.Array,
            n_adv: jax.Array,
            inputs: Any,  # [max_burst, ...]
            save_mask: jax.Array,  # [max_burst]
            save_frame: jax.Array,  # [max_burst]
        ):
            def write(ring, frame, st, pred):
                cs = checksum_device(st) if with_checksums else zero_cs
                return dring.save_where(ring, frame, st, cs, pred)

            ring = write(ring, pre_frame, live, pre_save)
            st = _tree_where(do_load, dring.load(ring, load_frame), live)
            # sparse saving can save the just-loaded state before any advance
            # (reference: p2p_session.rs:666-672 — the min_confirmed save)
            ring = write(ring, postload_frame, st, postload_save)

            def step(carry, xs):
                st, ring = carry
                j, inp, smask, sframe = xs
                act = j < n_adv
                st = _tree_where(act, advance(st, inp), st)
                ring = write(ring, sframe, st, act & smask)
                return (st, ring), None

            (st, ring), _ = jax.lax.scan(
                step,
                (st, ring),
                (
                    jnp.arange(max_burst, dtype=jnp.int32),
                    inputs,
                    save_mask,
                    save_frame,
                ),
            )
            return st, ring

        def tick(carry: Dict[str, Any], desc: Dict[str, Any]) -> Dict[str, Any]:
            live, ring = jax.vmap(session_tick)(
                carry["live"],
                carry["ring"],
                desc["pre_save"],
                desc["pre_frame"],
                desc["do_load"],
                desc["load_frame"],
                desc["postload_save"],
                desc["postload_frame"],
                desc["n_adv"],
                desc["inputs"],
                desc["save_mask"],
                desc["save_frame"],
            )
            return {"live": live, "ring": ring}

        if mesh is not None:
            # sessions are independent: shard the B axis, no collectives
            try:  # jax >= 0.8
                from jax import shard_map
            except ImportError:  # pragma: no cover - older jax
                from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec

            from .batch import shard_map_check_kwargs

            spec_b = PartitionSpec(tuple(mesh.axis_names))
            tick = shard_map(
                tick,
                mesh=mesh,
                in_specs=(spec_b, spec_b),
                out_specs=spec_b,
                **shard_map_check_kwargs(fn=shard_map),
            )

        donate = (0,) if jax.default_backend() == "tpu" else ()
        self._tick = jax.jit(tick, donate_argnums=donate)

        # slot probe with TRACED indices: one compile covers every
        # (session, slot) the desync exchange ever reads.  Eager integer
        # indexing would bake the indices into the program and recompile per
        # distinct pair — measured ~1s of compile per exchange interval,
        # enough to trip real-clock disconnect timers mid-session.
        def _fetch(frames: jax.Array, checksums: jax.Array, b, s):
            row_f = jax.lax.dynamic_index_in_dim(frames, b, 0, keepdims=False)
            row_c = jax.lax.dynamic_index_in_dim(checksums, b, 0, keepdims=False)
            return (
                jax.lax.dynamic_index_in_dim(row_f, s, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(row_c, s, 0, keepdims=False),
            )

        self._fetch_slot = jax.jit(_fetch)

    # ------------------------------------------------------------------
    # request-list parsing (host, NumPy only — zero dispatches)
    # ------------------------------------------------------------------

    def _parse(
        self, index: int, requests: List[GgrsRequest], desc: Dict[str, np.ndarray]
    ) -> None:
        """Normalize one session's tick into the descriptor row ``index``,
        fulfilling its Save cells with lazy slot references.

        Fulfillment is eager (cell + ``_host_frames`` updated during parse)
        because the ring-capacity guard below must see this tick's pre-saves
        in DEVICE order — the tick program writes pre-saves before the load,
        so a pre-save that aliases the load's slot means the gather returns
        the pre-saved frame, and only the updated shadow catches that.  The
        flip side — a parse failure partway through ``run()`` leaves earlier
        sessions' cells pointing at slots the aborted dispatch never wrote —
        is handled by invalidating the whole pool (see ``run``)."""
        i = 0
        n = len(requests)
        b = index

        def fulfill_save(req: SaveGameState) -> None:
            self._host_frames[b, req.frame % self.ring_length] = req.frame
            req.cell.save(
                req.frame,
                _BatchSlotRef(self, b, req.frame),
                _LazyBatchChecksum(self, b, req.frame)
                if self._with_checksums
                else None,
            )

        # optional pre-save(s) of the live state (the frame-0 tick emits the
        # initial save AND the per-frame save, both of frame 0 — reference:
        # p2p_session.rs:307-310); all must label the same frame, since no
        # advance runs between them
        while i < n and isinstance(requests[i], SaveGameState):
            if desc["pre_save"][b] and desc["pre_frame"][b] != requests[i].frame:
                raise ValueError(
                    f"session {b}: consecutive pre-saves of different frames "
                    f"({desc['pre_frame'][b]} then {requests[i].frame})"
                )
            desc["pre_save"][b] = True
            desc["pre_frame"][b] = requests[i].frame
            fulfill_save(requests[i])
            i += 1

        if i < n and isinstance(requests[i], LoadGameState):
            req = requests[i]
            data = req.cell.data()
            # real exceptions, not asserts: these guards are the only thing
            # standing between an undersized ring and a silent desync, and
            # ``python -O`` strips asserts
            if not (
                isinstance(data, _BatchSlotRef)
                and data.owner is self
                and data.index == b
                and data.frame == req.frame
            ):
                raise ValueError(
                    f"session {b} loads frame {req.frame} from a cell this "
                    f"pool did not save ({data!r})"
                )
            # ring-capacity guard: the device gather cannot tell an aliased
            # slot from the right one, so check the host shadow of the frame
            # tags loudly here (a session whose max_prediction reaches
            # ring_length would otherwise silently load a NEWER frame)
            held = self._host_frames[b, req.frame % self.ring_length]
            if held != req.frame:
                raise RuntimeError(
                    f"session {b}: rollback to frame {req.frame} but its ring "
                    f"slot holds frame {held} — ring_length={self.ring_length} "
                    f"is too small for this session's prediction window"
                )
            desc["do_load"][b] = True
            desc["load_frame"][b] = req.frame
            i += 1
            # sparse saving: save of the just-loaded state before any advance
            while i < n and isinstance(requests[i], SaveGameState):
                if (
                    desc["postload_save"][b]
                    and desc["postload_frame"][b] != requests[i].frame
                ):
                    raise ValueError(
                        f"session {b}: consecutive post-load saves of "
                        f"different frames ({desc['postload_frame'][b]} then "
                        f"{requests[i].frame})"
                    )
                desc["postload_save"][b] = True
                desc["postload_frame"][b] = requests[i].frame
                fulfill_save(requests[i])
                i += 1

        j = 0
        while i < n and isinstance(requests[i], AdvanceFrame):
            if j >= self.max_burst:
                raise ValueError(
                    f"session {b}: tick carries more than max_burst="
                    f"{self.max_burst} advances"
                )
            # shapes were recorded by warmup(); _blank_desc asserts that
            desc["inputs"][b, j] = np.asarray(
                self._inputs_to_array(requests[i].inputs)
            )
            i += 1
            if i < n and isinstance(requests[i], SaveGameState):
                desc["save_mask"][b, j] = True
                desc["save_frame"][b, j] = requests[i].frame
                fulfill_save(requests[i])
                i += 1
            j += 1
        desc["n_adv"][b] = j
        if i != n:
            raise ValueError(
                f"session {b}: unsupported request shape at position {i}: "
                f"{requests[i]!r}"
            )

    def _blank_desc(self) -> Dict[str, np.ndarray]:
        B, D = self.batch_size, self.max_burst
        assert self._input_shape is not None, (
            "call warmup(example_inputs) before the first run()"
        )
        return {
            "pre_save": np.zeros((B,), bool),
            "pre_frame": np.zeros((B,), np.int32),
            "do_load": np.zeros((B,), bool),
            "load_frame": np.zeros((B,), np.int32),
            "postload_save": np.zeros((B,), bool),
            "postload_frame": np.zeros((B,), np.int32),
            "n_adv": np.zeros((B,), np.int32),
            "inputs": np.zeros((B, D) + self._input_shape, self._input_dtype),
            "save_mask": np.zeros((B, D), bool),
            "save_frame": np.zeros((B, D), np.int32),
        }

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def warmup(self, example_inputs: Any) -> None:
        """Record the per-frame input array shape and compile the tick
        program before any live session exists (a compile stall inside a live
        loop trips real-clock disconnect timers — see ops executor warmup)."""
        arr = np.asarray(example_inputs)
        self._input_dtype = arr.dtype
        self._input_shape = arr.shape
        desc = self._blank_desc()
        out = self._tick(self._carry, desc)
        jax.block_until_ready(out)
        # a no-op tick leaves the carry semantically unchanged; keep the
        # result so donation (TPU) doesn't invalidate the live buffers
        self._carry = out
        # the desync exchange's slot probe must be compiled up front too
        jax.block_until_ready(
            self._fetch_slot(
                self._carry["ring"]["frames"],
                self._carry["ring"]["checksums"],
                np.int32(0),
                np.int32(0),
            )
        )

    def _reset_desc(self) -> Dict[str, np.ndarray]:
        """Fresh descriptor buffers for one tick.  NOT reused in place:
        jax may alias host numpy buffers zero-copy (CPU backend) and the
        dispatch is asynchronous, so mutating last tick's arrays while the
        program may still read them corrupts the dispatch silently."""
        return self._blank_desc()

    def _fulfill_fast(self, cells, b: int, frame: Frame) -> None:
        """Fulfill one save cell WITHOUT a SaveGameState object (the
        descriptor path): pooled per-(session, ring slot) refs refilled in
        place.  Refs are pooled per ring slot, not per session — an older
        cell in a different slot must keep seeing its own frozen frame."""
        ring = self._ref_rings[b]
        if ring is None:
            ring = self._ref_rings[b] = [
                (_BatchSlotRef(self, b, -1), _LazyBatchChecksum(self, b, -1))
                for _ in range(self.ring_length)
            ]
        slot = frame % self.ring_length
        ref, cs = ring[slot]
        ref.frame = frame
        if self._with_checksums:
            cs._frame = frame
            cs._value = None
        self._host_frames[b, slot] = frame
        cells.get_cell(frame).save(
            frame, ref, cs if self._with_checksums else None
        )

    def _fill_resim(self, plan, desc: Dict[str, np.ndarray], b: int,
                    lf: int, n_adv: int, trailing: bool, adv_off: int,
                    adv_stride: int) -> None:
        """One rollback-resim slot straight from its descriptor row:
        load→advance^N with interleaved saves, inputs gathered from the
        tick output buffer — the ``load→advance^N→save`` program selection
        of DESIGN.md §21, no request objects."""
        cells = plan.saved_states(b)
        cell = cells.get_cell(lf)
        data = cell.data()
        # the same two guards the request path applies through the
        # LoadGameState cell: ownership and ring capacity
        if not (
            isinstance(data, _BatchSlotRef)
            and data.owner is self
            and data.index == b
            and data.frame == lf
        ):
            raise ValueError(
                f"session {b} loads frame {lf} from a cell this pool did "
                f"not save ({data!r})"
            )
        held = self._host_frames[b, lf % self.ring_length]
        if held != lf:
            raise RuntimeError(
                f"session {b}: rollback to frame {lf} but its ring slot "
                f"holds frame {held} — ring_length={self.ring_length} is "
                f"too small for this session's prediction window"
            )
        if n_adv > self.max_burst:
            raise ValueError(
                f"session {b}: tick carries more than max_burst="
                f"{self.max_burst} advances"
            )
        desc["do_load"][b] = True
        desc["load_frame"][b] = lf
        desc["n_adv"][b] = n_adv
        players, isize = plan.players, plan.input_size
        span = players * (1 + isize)
        buf = plan.buffer
        raw = self._raw_inputs
        for j in range(n_adv):
            so = adv_off + j * adv_stride
            st = buf[so : so + players]
            blobs = buf[so + players : so + span].reshape(1, players, isize)
            desc["inputs"][b, j] = raw(blobs, st[None])[0]
            # every advance except (with a trailing live advance) the last
            # is followed by a save of the frame it produced: lf + 1 + j
            if (j < n_adv - 1) if trailing else True:
                f = lf + 1 + j
                desc["save_mask"][b, j] = True
                desc["save_frame"][b, j] = f
                self._fulfill_fast(cells, b, f)

    def _run_plan(self, plan) -> None:
        """The descriptor-plane run() (DESIGN.md §21): consume a
        ``HostSessionPool`` RequestPlan's flat columns directly — quiet
        slots fill the device descriptor vectorized, resim/save-only slots
        fill it per row, and only the plan's eager (slow/other) slots go
        through request materialization and the classic ``_parse``."""
        pool = plan.pool
        if plan.tick_no != pool._tick_no or plan is not pool._plan:
            # the same staleness contract the materialization surface
            # enforces: the columns view the pool's REUSED output buffer,
            # so consuming an old plan would dispatch garbage silently
            raise RuntimeError(
                "stale RequestPlan: request plans are only valid until "
                "the next advance_all"
            )
        rows = plan.quiet_rows
        eager = list(plan.eager_rows)
        vector = self._raw_inputs is not None and plan.uniform
        desc = self._reset_desc()
        any_work = bool(
            rows.size or plan.resim_rows or plan.save_only_rows
        ) or any(plan.lists[b] for b in eager)
        if not any_work:
            _OBS_EMPTY_TICKS.inc()
            return
        try:
            with self.tracer.span("device.dispatch"):
                if vector and rows.size:
                    frames = plan.quiet_frames
                    desc["pre_save"][rows] = True
                    desc["pre_frame"][rows] = frames
                    desc["n_adv"][rows] = 1
                    statuses, blobs = plan.gather_quiet()
                    desc["inputs"][rows, 0] = self._raw_inputs(
                        blobs, statuses
                    )
                    # _fulfill_fast writes the _host_frames shadow too —
                    # one writer for the ring tags
                    for b, f in zip(rows.tolist(), frames.tolist()):
                        self._fulfill_fast(plan.saved_states(b), b, f)
                elif rows.size:
                    # no bulk converter / non-uniform pool: quiet slots
                    # materialize like any other (reference semantics)
                    eager.extend(rows.tolist())
                for b, f in plan.save_only_rows:
                    desc["pre_save"][b] = True
                    desc["pre_frame"][b] = f
                    self._fulfill_fast(plan.saved_states(b), b, f)
                for (b, lf, n_adv, trailing, adv_off,
                     adv_stride) in plan.resim_rows:
                    if vector:
                        self._fill_resim(plan, desc, b, lf, n_adv,
                                         trailing, adv_off, adv_stride)
                    else:
                        eager.append(b)
                for b in eager:
                    reqs = plan[b]
                    if reqs:
                        self._parse(b, reqs, desc)
                _OBS_DISPATCHES.inc()
                _OBS_ROLLBACK_LOADS.inc(int(desc["do_load"].sum()))
                _OBS_BURST_DEPTH.observe(int(desc["n_adv"].max()))
                self._carry = self._tick(self._carry, desc)
        except BaseException as e:  # incl. KeyboardInterrupt mid-fill
            self._invalid = f"{type(e).__name__}: {e}"
            raise

    def run(self, request_lists: Sequence[List[GgrsRequest]]) -> None:
        """Fulfill all B sessions' request lists — ONE device dispatch (zero
        if every list is empty).  ``request_lists[b]`` belongs to session
        ``b``; sessions with nothing to do this tick pass ``[]``.

        A ``HostSessionPool`` RequestPlan (the descriptor plane, §21) is
        consumed through its flat columns — no ``GgrsRequest`` objects are
        constructed for fast-path slots."""
        self._check_valid()
        if len(request_lists) != self.batch_size:
            raise ValueError(
                f"run() got {len(request_lists)} request lists for a pool of "
                f"{self.batch_size} sessions"
            )
        if getattr(request_lists, "quiet_rows", None) is not None:
            self._run_plan(request_lists)
            return
        if all(not reqs for reqs in request_lists):
            _OBS_EMPTY_TICKS.inc()
            return
        desc = self._reset_desc()
        # parse fulfills cells eagerly (the ring-capacity guard needs this
        # tick's pre-saves visible in device order — see _parse); if any
        # session's list fails to parse, or the dispatch itself fails,
        # earlier sessions already hold cells referencing slots this aborted
        # tick never wrote, so the pool is unusable: poison it loudly rather
        # than let a caller that caught the error keep running on stale loads
        try:
            with self.tracer.span("device.dispatch"):
                for b, reqs in enumerate(request_lists):
                    if reqs:
                        self._parse(b, reqs, desc)
                _OBS_DISPATCHES.inc()
                _OBS_ROLLBACK_LOADS.inc(int(desc["do_load"].sum()))
                _OBS_BURST_DEPTH.observe(int(desc["n_adv"].max()))
                self._carry = self._tick(self._carry, desc)
        except BaseException as e:  # incl. KeyboardInterrupt mid-parse
            self._invalid = f"{type(e).__name__}: {e}"
            raise

    # ------------------------------------------------------------------
    # accessors (device reads — diagnostics / desync exchange, not hot path)
    # ------------------------------------------------------------------

    @property
    def live_states(self) -> Any:
        """The [B, ...] live state pytree (device handles; no transfer)."""
        self._check_valid()
        return self._carry["live"]

    def live_state(self, index: int) -> Any:
        """One session's live state, fetched to host."""
        self._check_valid()
        return jax.device_get(
            jax.tree_util.tree_map(lambda l: l[index], self._carry["live"])
        )

    def _check_valid(self) -> None:
        if self._invalid is not None:
            raise RuntimeError(
                f"pool was invalidated by an earlier failed tick "
                f"({self._invalid}); rebuild it — its rings and fulfilled "
                f"cells are out of sync"
            )

    def _slot_probe(self, index: int, frame: Frame):
        """(slot, held_frame, checksum_lanes) via the precompiled traced-index
        fetch — one program for every (session, slot), one transfer for both
        scalars."""
        self._check_valid()
        slot = frame % self.ring_length
        held, lanes = jax.device_get(
            self._fetch_slot(
                self._carry["ring"]["frames"],
                self._carry["ring"]["checksums"],
                np.int32(index),
                np.int32(slot),
            )
        )
        if int(held) != frame:
            raise RuntimeError(
                f"session {index}: ring slot {slot} holds frame {int(held)}, "
                f"wanted {frame} (rolled past ring_length={self.ring_length}?)"
            )
        return slot, lanes

    def ring_state(self, index: int, frame: Frame) -> Any:
        """A saved state, fetched to host (validates the slot still holds
        ``frame``).  Diagnostics path — eager slicing is fine here."""
        slot, _ = self._slot_probe(index, frame)
        return jax.device_get(
            jax.tree_util.tree_map(
                lambda buf: buf[index, slot], self._carry["ring"]["states"]
            )
        )

    def ring_checksum(self, index: int, frame: Frame) -> int:
        """A saved frame's u128 checksum (validates the slot)."""
        assert self._with_checksums, "pool was built with with_checksums=False"
        _, lanes = self._slot_probe(index, frame)
        return checksum_to_u128(lanes)

    def block_until_ready(self) -> None:
        with self.tracer.span("device.fence"):
            jax.block_until_ready(self._carry)


class HostedPool:
    """The full massed-hosting tick, both halves pooled: a
    ``host_bank.HostSessionPool`` steps all B sessions' protocol + sync
    mechanism in ONE ctypes crossing, and a ``BatchedRequestExecutor``
    fulfills the B request lists in ONE device dispatch — two crossings of
    any boundary per pool tick, total, regardless of B.

    ``host_pool`` must hold the same sessions, in the same order, as the
    executor's batch indices.  When the native bank is unavailable the host
    half transparently degrades to per-session Python sessions (identical
    request lists), so this wrapper needs no fallback of its own.
    """

    def __init__(self, host_pool, executor: BatchedRequestExecutor) -> None:
        if len(host_pool) != executor.batch_size:
            raise ValueError(
                f"host pool has {len(host_pool)} sessions but the executor "
                f"was built for batch_size={executor.batch_size}"
            )
        self.host = host_pool
        self.executor = executor
        # one trace per hosted pool: the device dispatch/fence spans join
        # the host pool's tick -> crossing -> slot timeline
        host_tracer = getattr(host_pool, "tracer", None)
        if (
            host_tracer is not None and host_tracer.enabled
            and not executor.tracer.enabled
        ):
            executor.tracer = host_tracer

    def tick(self, local_inputs: Sequence[Tuple[int, int, Any]]) -> None:
        """One pool tick: stage ``(session_index, handle, value)`` local
        inputs (ONE batched native call on the descriptor plane, §21),
        advance every session, fulfill every request list."""
        stage = getattr(self.host, "stage_inputs", None)
        if stage is not None:
            stage(local_inputs)
        else:
            add = self.host.add_local_input
            for index, handle, value in local_inputs:
                add(index, handle, value)
        self.executor.run(self.host.advance_all())

    def block_until_ready(self) -> None:
        self.executor.block_until_ready()
