"""TPU parallelism strategies.

The reference is a single-threaded Rust library with no parallelism at all
(SURVEY §2, parallelism note).  The TPU build's first-class axes are new
design, not ports:

- **temporal** — the rollback replay as ``lax.scan`` (ggrs_tpu.ops.replay);
- **speculative** — ``vmap`` over K predicted-input branches with post-hoc
  selection on confirmed inputs (``speculation``);
- **session** — ``shard_map`` batching of many independent sessions across a
  device mesh with ICI collectives for global health counters (``batch``),
  plus massed request fulfillment for LIVE heterogeneous sessions — B
  networked sessions' per-tick request lists executed as one predicated
  device program (``session_pool``), with the HOST half of the same tick —
  protocol + sync mechanism for all B sessions — stepped in one native
  crossing (``host_bank``; ``HostedPool`` pairs the two);
- **player/entity** — vectorization inside one state pytree (the games do
  this by construction, e.g. BoxGame's (P, ...) arrays).
"""

from .speculation import SpeculativeBranches, build_speculation_programs
from .spec_rollback import SpeculativeRollback
from .batch import (
    BatchedSessions,
    HOST_AXIS,
    SESSION_AXIS,
    make_distributed_mesh,
    make_mesh,
    make_mesh2d,
)
from .session_pool import BatchedRequestExecutor, HostedPool
from .host_bank import HostSessionPool

__all__ = [
    "BatchedRequestExecutor",
    "HostSessionPool",
    "HostedPool",
    "BatchedSessions",
    "HOST_AXIS",
    "SESSION_AXIS",
    "SpeculativeBranches",
    "SpeculativeRollback",
    "build_speculation_programs",
    "make_distributed_mesh",
    "make_mesh",
    "make_mesh2d",
]
