"""Parallel slow-slot decode plane (DESIGN.md §24).

The bank's tick output is a packed byte stream: one body record per slot,
addressed by the §19 header table's ``rec_len`` jump chain.  Slow slots —
the ones the RequestPlan routes through the reference ``_parse_slot``
decoder — are *embarrassingly parallel to decode*: each record is an
independent byte range, and everything order-sensitive about a slot
(request construction, sends, journal taps, event dispatch, frame
mirrors) happens AFTER decoding, against plain data.

This module is that split.  :func:`decode_slot_record` is the pure half
of ``_parse_slot``: it walks one slot's record and returns a plain-data
tuple — no session state read, no side effects, nothing but ``bytes``
out — so it can run on any worker against a read-only view of the shared
tick buffer.  :class:`DecodePool` fans a tick's slow-slot ranges across
workers and returns the decoded tuples in slot order; the pool's
``_apply_slot`` then replays the side effects on the owning thread in
exactly the serial decoder's order.

Backends (resolved once, probed at construction):

- ``interp`` — sub-interpreter workers (``InterpreterPoolExecutor``,
  3.14+; each worker imports this module in its own interpreter, so
  decoding escapes the GIL).  Slot ranges cross as ``bytes`` (the one
  copy this backend pays — buffers cannot be shared across interpreters).
- ``thread`` — a plain thread pool.  A real speedup only on free-threaded
  (``Py_GIL_DISABLED``) builds; on GIL builds it exists to EXERCISE the
  merge/ordering machinery (the TSan leg forces it) rather than to win
  wall time.  Workers receive zero-copy memoryview slices.
- ``serial`` — the bit-identical fallback everywhere else, and the
  runtime default on GIL builds: the host pool then keeps calling its
  reference ``_parse_slot`` directly, so the default path is not just
  bit-identical but literally the same code.

Env switches (the §23 per-feature degradation discipline):

- ``GGRS_TPU_NO_PARALLEL_DECODE=1`` — kill switch, forces ``serial``.
- ``GGRS_TPU_DECODE_BACKEND=serial|thread|interp`` — force a backend
  (unavailable forced backends fall back to ``serial``, never raise).
- ``GGRS_TPU_DECODE_WORKERS=N`` — worker count override.
"""

from __future__ import annotations

import os
import struct
import sys
import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

from ..utils.ownership import ThreadOwned

# Mirrors of the bank's wire constants, re-declared locally so an interp
# worker importing this module pulls in nothing beyond the stdlib:
# session_bank.cpp EvKind (== host_bank._EV_*) and core.types.NULL_FRAME.
_EV_INTERRUPTED = 1
_EV_CHECKSUM = 4
_NULL_FRAME = -1

# A decoded slot is a plain tuple (index comments below); ops entries are
# (kind, a, b): kind 2 advance -> (2, statuses_bytes, inputs_blob), kind
# 0 save / 1 load -> (kind, frame, None).
DecodedSlot = Tuple[Any, ...]
# indices into a DecodedSlot, for readers of the apply path
DEC_ERR = 0          # bank error code (0 = stepped clean)
DEC_LANDED = 1       # landed frame
DEC_FRAMES_AHEAD = 2
DEC_CURRENT = 3
DEC_CONFIRMED = 4
DEC_CONSENSUS = 5
DEC_OPS = 6          # [(kind, a, b)] in bank order
DEC_POLL_OUT = 7     # [(ep_idx, bytes)] poll-phase endpoint sends
DEC_ADV_OUT = 8      # [(ep_idx, bytes)] adv-phase sends (broadcast mode)
DEC_EVENTS = 9       # [(kind, ep_idx, payload)] staged endpoint events
DEC_EPS = 10         # [(running_byte, [(disc, last_frame)] * players)]
DEC_LOCAL = 11       # [(disc, last_frame)] * players
DEC_SPEC = 12        # None | the broadcast tail (see decode_slot_record)
DEC_END = 13         # end position (pos after this record)


def decode_slot_record(buf, pos: int, players: int, isize: int,
                       has_spec: bool) -> DecodedSlot:
    """Decode ONE slot's body record starting at ``pos`` into plain data.

    The pure half of the host pool's ``_parse_slot``: the byte walk is
    identical, but where the reference decoder *does* things (builds
    requests, sends, records, mutates mirrors) this function only
    *collects* — every side-effect input lands in the returned tuple for
    the owning thread to replay in slot order.  Reads nothing but its
    arguments; safe on any worker against a read-only buffer view.
    """
    unpack_from = struct.unpack_from
    err, landed, frames_ahead, current, confirmed, consensus, n_ops = (
        unpack_from("<iqiqqBH", buf, pos)
    )
    pos += 35
    ops: List[Tuple[int, Any, Any]] = []
    for _ in range(n_ops):
        kind = buf[pos]
        pos += 1
        if kind == 2:
            statuses = bytes(buf[pos : pos + players])
            pos += players
            blob = bytes(buf[pos : pos + players * isize])
            pos += players * isize
            ops.append((2, statuses, blob))
        else:
            (frame,) = unpack_from("<q", buf, pos)
            pos += 8
            ops.append((kind, frame, None))
    poll_out: List[Tuple[int, bytes]] = []
    (n_out_poll,) = unpack_from("<H", buf, pos)
    pos += 2
    for _ in range(n_out_poll):
        ep_idx, dlen = unpack_from("<HI", buf, pos)
        pos += 6
        poll_out.append((ep_idx, bytes(buf[pos : pos + dlen])))
        pos += dlen
    adv_out: List[Tuple[int, bytes]] = []
    if has_spec:
        (n_out_adv,) = unpack_from("<H", buf, pos)
        pos += 2
        for _ in range(n_out_adv):
            ep_idx, dlen = unpack_from("<HI", buf, pos)
            pos += 6
            adv_out.append((ep_idx, bytes(buf[pos : pos + dlen])))
            pos += dlen
    (n_events,) = unpack_from("<H", buf, pos)
    pos += 2
    events: List[Tuple[int, int, Any]] = []
    for _ in range(n_events):
        kind, ep_idx = unpack_from("<BH", buf, pos)
        pos += 3
        if kind == _EV_INTERRUPTED:
            (remaining,) = unpack_from("<q", buf, pos)
            pos += 8
            events.append((kind, ep_idx, remaining))
        elif kind == _EV_CHECKSUM:
            frame, lo, hi = unpack_from("<qQQ", buf, pos)
            pos += 24
            events.append((kind, ep_idx, (frame, lo, hi)))
        else:
            events.append((kind, ep_idx, None))
    (n_eps,) = unpack_from("<B", buf, pos)
    pos += 1
    eps: List[Tuple[int, List[Tuple[int, int]]]] = []
    for _e in range(n_eps):
        running = buf[pos]
        pos += 1
        prs: List[Tuple[int, int]] = []
        for _h in range(players):
            disc, lf = unpack_from("<Bq", buf, pos)
            pos += 9
            prs.append((disc, lf))
        eps.append((running, prs))
    local: List[Tuple[int, int]] = []
    for _h in range(players):
        disc, lf = unpack_from("<Bq", buf, pos)
        pos += 9
        local.append((disc, lf))
    spec = None
    if has_spec:
        # broadcast tail (§13): spectator mirror, phase-tagged fan-out
        # streams, hub events, journal confirmed-frame records
        next_spec, n_specs = unpack_from("<qB", buf, pos)
        pos += 9
        sstat: List[Tuple[int, int]] = []
        for _e in range(n_specs):
            st, la = unpack_from("<Bq", buf, pos)
            pos += 9
            sstat.append((st, la))
        (n_spec_out,) = unpack_from("<H", buf, pos)
        pos += 2
        spec_poll: List[List[bytes]] = [[] for _ in range(n_specs)]
        spec_adv: List[List[bytes]] = [[] for _ in range(n_specs)]
        for _ in range(n_spec_out):
            sp_idx, phase, dlen = unpack_from("<HBI", buf, pos)
            pos += 7
            (spec_adv if phase else spec_poll)[sp_idx].append(
                bytes(buf[pos : pos + dlen])
            )
            pos += dlen
        (n_spec_events,) = unpack_from("<H", buf, pos)
        pos += 2
        spec_events: List[Tuple[int, int, Any]] = []
        for _ in range(n_spec_events):
            kind, sp_idx = unpack_from("<BH", buf, pos)
            pos += 3
            payload = None
            if kind == _EV_INTERRUPTED:
                (payload,) = unpack_from("<q", buf, pos)
                pos += 8
            spec_events.append((kind, sp_idx, payload))
        (n_conf,) = unpack_from("<H", buf, pos)
        pos += 2
        conf_start = _NULL_FRAME
        conf_records: List[Tuple[bytes, bytes]] = []
        if n_conf:
            (conf_start,) = unpack_from("<q", buf, pos)
            pos += 8
            blob_len = players * isize
            for _ in range(n_conf):
                flags = bytes(buf[pos : pos + players])
                pos += players
                conf_records.append(
                    (flags, bytes(buf[pos : pos + blob_len]))
                )
                pos += blob_len
        spec = (next_spec, n_specs, sstat, spec_poll, spec_adv,
                spec_events, conf_start, conf_records)
    return (err, landed, frames_ahead, current, confirmed, consensus,
            ops, poll_out, adv_out, events, eps, local, spec, pos)


def _decode_chunk(buf, jobs: Sequence[Tuple[int, int, int, bool]]):
    """Worker entry: decode a contiguous chunk of slot jobs against one
    shared read-only buffer view.  Returns ``(worker_tag, results)`` so
    the pool can attribute utilization without any worker-side shared
    mutation (the tag is the worker thread's ident — unique per pool
    worker for threads, and per interpreter's single thread for interps).
    """
    out = [
        decode_slot_record(buf, pos, players, isize, has_spec)
        for pos, players, isize, has_spec in jobs
    ]
    return threading.get_ident(), out


class DecodePool(ThreadOwned):
    """Worker engine fanning slow-slot decode across workers (§24).

    Owned like a session: :meth:`decode_slots` is a driving method (the
    §20 lint keeps the declaration closed), and only plain data crosses
    the worker boundary — workers run the module-level pure
    :func:`decode_slot_record`/:func:`_decode_chunk`, never a bound
    method of this class.  The tick buffer is shared read-only (thread
    backend: memoryview slices, zero copies; interp backend: one bytes
    copy per chunk, the interpreter boundary's price); workers never
    mutate shared state, and the caller applies results in slot order so
    side effects land exactly as the serial decoder produced them.
    """

    _DRIVING_METHODS = ("decode_slots",)

    def __init__(self, backend: Optional[str] = None,
                 workers: Optional[int] = None) -> None:
        self.jobs = 0          # slots decoded through the pool
        self.batches = 0       # decode_slots calls that fanned out
        self.decode_ns = 0     # wall ns inside decode_slots
        self.worker_jobs: dict = {}  # worker tag -> jobs decoded
        self._executor = None
        env_backend = os.environ.get("GGRS_TPU_DECODE_BACKEND")
        if os.environ.get("GGRS_TPU_NO_PARALLEL_DECODE"):
            backend = "serial"
        elif backend is None:
            backend = env_backend or self._auto_backend()
        if workers is None:
            try:
                workers = int(os.environ.get("GGRS_TPU_DECODE_WORKERS", 0))
            except ValueError:
                workers = 0
        if not workers or workers < 1:
            workers = min(8, max(2, (os.cpu_count() or 2) - 1))
        self.workers = workers
        if backend == "interp":
            ex = self._make_interp_executor(workers)
            if ex is None:
                backend = "serial"
            else:
                self._executor = ex
        elif backend == "thread":
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="ggrs-decode"
            )
        elif backend != "serial":
            backend = "serial"  # unknown forced backend: degrade, §23
        self.backend = backend

    @staticmethod
    def _auto_backend() -> str:
        """Default backend for THIS interpreter: sub-interpreters where
        the stdlib offers them, threads only where they actually run in
        parallel (free-threaded builds), serial everywhere else — a GIL
        build gains nothing from Python-level decode threads, so the
        default stays on the reference path."""
        if DecodePool._interp_available():
            return "interp"
        gil_check = getattr(sys, "_is_gil_enabled", None)
        if gil_check is not None and not gil_check():
            return "thread"
        return "serial"

    @staticmethod
    def _interp_available() -> bool:
        try:
            from concurrent.futures import (  # noqa: F401
                InterpreterPoolExecutor,
            )
        except ImportError:
            return False
        return True

    @staticmethod
    def _make_interp_executor(workers: int):
        try:
            from concurrent.futures import InterpreterPoolExecutor
        except ImportError:
            return None
        try:
            return InterpreterPoolExecutor(max_workers=workers)
        except Exception:
            return None  # interpreters exist but won't start: degrade

    def decode_slots(
        self, buf, jobs: Sequence[Tuple[int, int, int, bool]]
    ) -> List[DecodedSlot]:
        """Decode ``jobs`` — ``(pos, players, isize, has_spec)`` slot
        ranges into ``buf`` — and return the decoded tuples in job
        order.  One driving call per tick; the fan-out/merge is entirely
        inside."""
        self._check_owner()
        t0 = time.perf_counter_ns()
        n = len(jobs)
        ex = self._executor
        if ex is None or n <= 1:
            tag, out = _decode_chunk(buf, jobs)
            self.worker_jobs[tag] = self.worker_jobs.get(tag, 0) + n
        else:
            if self.backend == "interp":
                # buffers don't cross interpreters: ship the bytes once
                # per call (workers slice it read-only)
                buf = bytes(buf)
            # contiguous chunks, one per worker, submitted in slot order
            # and merged by list order — ordering never depends on
            # completion order
            n_chunks = min(self.workers, n)
            bounds = [n * i // n_chunks for i in range(n_chunks + 1)]
            futs = [
                ex.submit(_decode_chunk, buf, jobs[bounds[i]:bounds[i + 1]])
                for i in range(n_chunks)
            ]
            out = []
            for i, f in enumerate(futs):
                tag, part = f.result()
                self.worker_jobs[tag] = (
                    self.worker_jobs.get(tag, 0) + len(part)
                )
                out.extend(part)
        self.jobs += n
        self.batches += 1
        self.decode_ns += time.perf_counter_ns() - t0
        return out

    def stats(self) -> dict:
        """Plain-data counters for ``io_stats()``/profiling: backend,
        worker count, jobs/batches, wall ns, and per-worker utilization
        (jobs per worker tag — even spread == good utilization)."""
        return {
            "backend": self.backend,
            "workers": self.workers if self._executor is not None else 1,
            "jobs": self.jobs,
            "batches": self.batches,
            "decode_ns": self.decode_ns,
            "worker_jobs": dict(self.worker_jobs),
        }

    def close(self) -> None:
        ex = self._executor
        self._executor = None
        if ex is not None:
            ex.shutdown(wait=True)

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
