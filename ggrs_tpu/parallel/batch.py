"""Session parallelism: many independent sessions batched across a device mesh.

The reference runs exactly one session per process; scaling to hundreds of
matches means hundreds of processes.  On TPU the same hundreds of sessions are
one program: session state gets a leading batch axis (``vmap``), the batch is
sharded across chips over a 1-D ``Mesh`` with ``shard_map`` so each chip owns
``B / n_devices`` sessions, and the only cross-chip traffic is the scalar
health reduction (``psum`` over ICI — desync and frame counters), exactly the
collective-over-ICI design SURVEY §2's backend note calls for.  This is
BASELINE config 5's shape (256 concurrent SyncTest sessions on v5e-8).

Works identically on a virtual ``--xla_force_host_platform_device_count=N``
CPU mesh, which is how tests and the driver's multi-chip dry-run exercise it.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..ops.replay import ReplayPrograms, build_replay_programs


def shard_map_check_kwargs(fn=None) -> dict:
    """The kwarg disabling shard_map's replication check was renamed
    (``check_rep`` -> ``check_vma``) across jax versions; feature-detect
    which one this jax accepts so both signatures work."""
    import inspect

    target = shard_map if fn is None else fn
    try:
        params = inspect.signature(target).parameters
    except (TypeError, ValueError):  # pragma: no cover - C callables
        return {}
    if "check_vma" in params:
        return {"check_vma": False}
    if "check_rep" in params:
        return {"check_rep": False}
    return {}

SESSION_AXIS = "sessions"


def make_mesh(n_devices: Optional[int] = None, axis: str = SESSION_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` (default: all) devices."""
    devs = jax.devices()
    if n_devices is not None:
        assert n_devices <= len(devs), (
            f"asked for {n_devices} devices, have {len(devs)}"
        )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


HOST_AXIS = "hosts"


def make_mesh2d(
    n_hosts: int,
    chips_per_host: int,
    axes: Tuple[str, str] = (HOST_AXIS, SESSION_AXIS),
) -> Mesh:
    """2-D ``(hosts, chips)`` mesh — the multi-host shape.

    On a real multi-host job (``jax.distributed``), ``jax.devices()`` spans
    every host and the natural factorization puts the slow interconnect (DCN)
    on the outer axis and ICI on the inner one, so XLA routes the per-host
    partial reductions over ICI and only the scalar host-level combine over
    DCN — the hierarchy SURVEY §2's backend note calls for.  ``BatchedSessions``
    accepts either mesh rank and shards its session axis over ALL mesh axes,
    so moving from one host to N is a mesh swap, not a program change.  Tests
    exercise the same program on a virtual ``(2, 4)`` CPU mesh.
    """
    devs = jax.devices()
    need = n_hosts * chips_per_host
    assert need <= len(devs), f"asked for {need} devices, have {len(devs)}"
    grid = np.asarray(devs[:need]).reshape(n_hosts, chips_per_host)
    return Mesh(grid, axes)


def make_distributed_mesh(
    axes: Tuple[str, str] = (HOST_AXIS, SESSION_AXIS),
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Mesh:
    """The multi-host ``(hosts, chips)`` mesh for a real ``jax.distributed``
    job — ``make_mesh2d``'s launchable form (VERDICT r3 item 9).

    Call once per host process.  If the process is not yet part of a
    distributed job and a coordinator is known (arguments or the standard
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``
    environment), ``jax.distributed.initialize`` is called first; after
    that ``jax.devices()`` spans every host and the mesh is built host-major
    (outer axis = DCN between hosts, inner = ICI within a host), so
    ``BatchedSessions``' health ``psum`` reduces over ICI first and crosses
    DCN only for the per-host scalar combine.

    Two-host launch recipe (same binary on both, e.g. examples or a
    hosting server)::

        # host 0 (also the coordinator)
        JAX_COORDINATOR_ADDRESS=host0:8476 JAX_NUM_PROCESSES=2 \\
            JAX_PROCESS_ID=0 python my_server.py
        # host 1
        JAX_COORDINATOR_ADDRESS=host0:8476 JAX_NUM_PROCESSES=2 \\
            JAX_PROCESS_ID=1 python my_server.py

    where ``my_server.py`` does ``mesh = make_distributed_mesh()`` and
    passes it to ``BatchedSessions(..., mesh=mesh)`` — no other program
    change versus single-host.  On a single process (including the virtual
    CPU mesh) this degenerates to a ``(1, n_devices)`` mesh running the
    identical program, which is how tests and the driver's multi-chip
    dry-run keep it validated without multi-host hardware.
    """
    import os

    # jax.distributed.initialize must run before ANY jax call that could
    # initialize the XLA backend (even jax.process_count() does) — so decide
    # from args/env alone, touching no jax state first.  If the caller
    # already ran jax.distributed.initialize themselves, they must NOT also
    # provide coordinator args here (a second initialize raises).
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes or int(
        os.environ.get("JAX_NUM_PROCESSES", "0") or 0
    )
    if addr and nproc > 1:
        pid = (
            process_id
            if process_id is not None
            else os.environ.get("JAX_PROCESS_ID")
        )
        if pid is None:
            raise ValueError(
                "make_distributed_mesh: a coordinator and num_processes are "
                "set but no process id — pass process_id= or export "
                "JAX_PROCESS_ID (defaulting to 0 would register every host "
                "as process 0 and deadlock the coordinator barrier)"
            )
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=nproc,
            process_id=int(pid),
        )

    devs = jax.devices()  # global list: spans every host once initialized
    n_hosts = jax.process_count()
    per_host = len(devs) // n_hosts
    if per_host * n_hosts != len(devs):
        raise RuntimeError(
            f"make_distributed_mesh: {len(devs)} devices do not divide over "
            f"{n_hosts} hosts"
        )
    grid = np.empty((n_hosts, per_host), dtype=object)
    fill = [0] * n_hosts
    for d in devs:
        p = d.process_index
        grid[p, fill[p]] = d
        fill[p] += 1
    if fill != [per_host] * n_hosts:
        raise RuntimeError(
            f"make_distributed_mesh: devices are not evenly attached per "
            f"host: {fill}"
        )
    return Mesh(grid, axes)


class BatchedSessions:
    """B independent device-synctest sessions as one sharded program.

    All sessions share the same (advance, check_distance) program but have
    independent states, inputs, and desync counters.  ``run_ticks`` dispatches
    one program for the whole batch; mismatch totals come back via an on-mesh
    ``psum`` so the host reads two scalars per call, regardless of B.
    """

    def __init__(
        self,
        advance: Callable[[Any, Any], Any],
        init_state: Any,
        input_template: Any,
        batch_size: int,
        mesh: Optional[Mesh] = None,
        check_distance: int = 2,
        max_prediction: int = 8,
    ) -> None:
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        assert batch_size % n_dev == 0, (
            f"batch_size {batch_size} must divide evenly over {n_dev} devices"
        )
        self.batch_size = batch_size
        ring_length = max(max_prediction, check_distance) + 1
        self._programs: ReplayPrograms = build_replay_programs(
            advance, ring_length, check_distance, donate=False
        )
        self.check_distance = check_distance
        self._ticks_run = 0
        self._last_stats: Optional[Dict[str, Any]] = None

        # shard the leading (session) axis over EVERY mesh axis: on a 1-D
        # mesh that's plain chip-sharding; on a 2-D (hosts, chips) mesh the
        # batch splits host-major so reductions combine over ICI first, DCN
        # last (see make_mesh2d)
        axis_names = tuple(self.mesh.axis_names)
        spec_b = P(axis_names)
        sharding = NamedSharding(self.mesh, spec_b)
        self._sharding = sharding  # kept for checkpoint restore

        # one carry per session, stacked on a leading B axis and sharded
        carry0 = self._programs.init_carry(init_state, input_template)
        batched = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                leaf[None, ...], (batch_size,) + leaf.shape
            ),
            carry0,
        )
        self._carry = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding), batched
        )

        def _sharded(
            scan_fn, carry: Any, inputs: Any, start_frame: Any
        ) -> Tuple[Any, Dict[str, Any]]:
            def local(carry_l: Any, inputs_l: Any):
                # start_frame enters as an UNBATCHED scalar closure: ring
                # slots stay shared-index slice ops instead of per-session
                # scatters (see ReplayPrograms doc — ~30× on this bench)
                out = jax.vmap(lambda c, i: scan_fn(c, i, start_frame))(
                    carry_l, inputs_l
                )
                stats = {
                    "mismatches": jax.lax.psum(
                        jnp.sum(out["mismatches"]), axis_names
                    ),
                    "first_bad": jax.lax.pmin(
                        jnp.min(out["first_bad"]), axis_names
                    ),
                }
                return out, stats

            return shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_b, spec_b),
                out_specs=(spec_b, P()),
                **shard_map_check_kwargs(),
            )(carry, inputs)

        self._run_warmup = jax.jit(partial(_sharded, self._programs.scan_warmup))
        self._run_steady = jax.jit(partial(_sharded, self._programs.scan_steady))

    # ------------------------------------------------------------------

    @property
    def current_frame(self) -> int:
        return self._ticks_run

    def run_ticks(self, inputs: Any, check: bool = True) -> Optional[Dict[str, int]]:
        """Advance all sessions ``n`` frames.  ``inputs`` leading axes are
        ``(B, n, ...per-frame...)``.  Returns global stats from the on-mesh
        reduction: total mismatches and earliest bad frame across all
        sessions.

        ``check=False`` defers the stats fetch: the call stays fully async
        (no device→host read — a full round-trip on tunneled TPUs) and
        returns None; read the accumulated result later with ``verify()``."""
        inputs = jax.tree_util.tree_map(jnp.asarray, inputs)
        leaf0 = jax.tree_util.tree_leaves(inputs)[0]
        assert leaf0.shape[0] == self.batch_size
        n = leaf0.shape[1]
        if n == 0:
            return {"mismatches": 0, "first_bad": np.iinfo(np.int32).max} if check else None
        n_warm = self._programs.split_at_warmup(self._ticks_run, n)
        stats = None
        if n_warm:
            head = jax.tree_util.tree_map(lambda a: a[:, :n_warm], inputs)
            self._carry, stats = self._run_warmup(
                self._carry, head, np.int32(self._ticks_run)
            )
        if n > n_warm:
            tail = jax.tree_util.tree_map(lambda a: a[:, n_warm:], inputs)
            self._carry, stats = self._run_steady(
                self._carry, tail, np.int32(self._ticks_run + n_warm)
            )
        self._ticks_run += n
        self._last_stats = stats  # device scalars; fetched on demand
        if not check:
            return None
        return self.verify()

    def verify(self) -> Dict[str, int]:
        """Fetch the deferred global stats (one transfer for both scalars)."""
        if self._last_stats is None:
            return {"mismatches": 0, "first_bad": np.iinfo(np.int32).max}
        mismatches, first_bad = jax.device_get(
            (self._last_stats["mismatches"], self._last_stats["first_bad"])
        )
        return {"mismatches": int(mismatches), "first_bad": int(first_bad)}

    def live_states(self) -> Any:
        """All B live states, gathered to host (leading axis B)."""
        return jax.device_get(self._carry["live"])

    # ------------------------------------------------------------------
    # durable checkpoints (beyond the reference — SURVEY §5 checkpoint note):
    # the whole batch's sharded carry gathers to host and resumes bit-exactly
    # on any mesh of the same total device count divisor (batch_size checks)
    # ------------------------------------------------------------------

    def save_checkpoint(self, path: str) -> None:
        """Write every session's carry + the tick counter to ``path``."""
        from ..utils.checkpoint import save_pytree

        save_pytree(
            path,
            self._carry,
            {
                "ticks_run": self._ticks_run,
                "check_distance": self.check_distance,
                "batch_size": self.batch_size,
            },
        )

    def load_checkpoint(self, path: str) -> None:
        """Restore a checkpoint written by ``save_checkpoint`` into this
        batch (same game, batch_size, and check_distance; the mesh may
        differ — leaves are re-placed under this batch's sharding)."""
        from ..core.errors import InvalidRequest
        from ..utils.checkpoint import load_pytree

        carry, meta = load_pytree(path, self._carry)
        if meta["check_distance"] != self.check_distance:
            raise InvalidRequest(
                f"checkpoint was taken at check_distance="
                f"{meta['check_distance']}, batch uses {self.check_distance}"
            )
        if meta["batch_size"] != self.batch_size:
            raise InvalidRequest(
                f"checkpoint holds {meta['batch_size']} sessions, batch was "
                f"built for {self.batch_size}"
            )
        # device_put straight from the host arrays: shards across the mesh in
        # one step (jnp.asarray first would commit each leaf to one device
        # and then reshard device-to-device — wasted copies on restore)
        self._carry = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, self._sharding), carry
        )
        self._ticks_run = int(meta["ticks_run"])
        self._last_stats = None

    def block_until_ready(self) -> None:
        jax.block_until_ready(self._carry)
