"""Speculative rollback: skip the replay entirely when a branch guessed right.

The reference rolls back and resimulates every time a prediction was wrong
(/root/reference/src/sessions/p2p_session.rs:658-714) — and its single
repeat-last predictor is wrong whenever a remote player changes input.  On
TPU we can afford K predictions at once: this module keeps K branch
trajectories *incrementally extended each tick* under K different
remote-input hypotheses, so when confirmed inputs arrive and a rollback is
requested, a matching branch turns the whole load→(advance, save)^N replay
into a device-side select.  Misses fall back to the replay — correctness
never depends on a hit.

Zero device→host reads on the live path.  The round-1 design read the
hit/miss flag back to the host per rollback; a D2H read is a full round
trip (~80 ms of sync RTT on a tunneled TPU — bench.py "honest timing") and
a pipeline stall anywhere, so the redesign moves the decision on-device:

- branch states, trajectories, hypothesized inputs, and prefix-validity masks
  live in fixed-shape ``[W, K, ...]`` device ring buffers;
- ``extend`` is ONE fused dispatch (vmap advance + hypothesis match + buffer
  writes);
- ``fulfill`` is ONE fused dispatch per rollback: hypothesis matching, branch
  selection, and the fallback replay scan are a single ``lax.cond`` program,
  so the host never learns (or needs to learn) whether it hit — it always
  receives the correct per-step trajectory as device handles;
- ``refill`` re-anchors and re-extends the window after a rollback as one
  fused scan;
- hit counters accumulate on device and are only fetched when the
  ``spec_hits`` property is read (diagnostics, after timing).

``branch_inputs(k, frame, local_inputs)`` builds hypothesis k's full input
array for ``frame`` on the host; return **NumPy** arrays to keep hypothesis
construction off the dispatch path (JAX arrays are accepted but each costs an
eager device op).  ``DeviceRequestExecutor`` drives this through its
``speculation`` constructor argument — see ``ops.executor`` and
``tests/test_spec_integration.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

AdvanceFn = Callable[[Any, Any], Any]
# branch_inputs(branch_k, frame, tick_inputs_array) -> full inputs array for
# branch k at ``frame`` (local players' real inputs merged with hypothesis
# k's remote inputs; the session's own prediction arrives as ``tick_inputs``
# so the identity function is the "trust the predictor" branch)
BranchInputsFn = Callable[[int, int, Any], Any]


def _stack_pytrees(trees: Sequence[Any]) -> Any:
    """Stack pytrees on a new leading axis (branch or time, per the caller),
    staying on the host when every leaf is NumPy — the single H2D transfer
    then happens inside the consuming jit instead of as eager device ops."""

    def stack(*leaves: Any) -> Any:
        if all(isinstance(l, np.ndarray) for l in leaves):
            return np.stack(leaves)
        return jnp.stack([jnp.asarray(l) for l in leaves])

    return jax.tree_util.tree_map(stack, *trees)


def _swap01(tree: Any) -> Any:
    """Swap the two leading axes of every leaf, host-side when NumPy."""
    return jax.tree_util.tree_map(
        lambda l: np.swapaxes(l, 0, 1)
        if isinstance(l, np.ndarray)
        else jnp.swapaxes(jnp.asarray(l), 0, 1),
        tree,
    )


class SpeculativeRollback:
    """K incrementally-extended branch trajectories rooted at a saved frame.

    Usage per tick:
      - ``root(frame, state)`` whenever the rollback anchor moves (a Save of
        the confirmed frame);
      - ``extend(local_inputs)`` once per advanced frame: every branch steps
        under its own hypothesis (ONE fused dispatch for all K);
      - on rollback to ``frame``: if ``window_valid(frame, n)``, call
        ``fulfill`` (one fused resolve-or-replay dispatch) then ``refill`` to
        re-anchor; otherwise ``invalidate`` and replay normally.
    """

    def __init__(
        self,
        advance: AdvanceFn,
        num_branches: int,
        branch_inputs: BranchInputsFn,
        max_window: int = 16,
        branch_inputs_all: Optional[Callable[[int, Any], Any]] = None,
    ) -> None:
        assert num_branches >= 1
        self.K = num_branches
        self.max_window = max_window
        self._advance = advance
        self._branch_inputs = branch_inputs
        # optional vectorized hypothesis builder: one call producing the whole
        # [K, ...] stack for a frame instead of K per-branch calls — hypothesis
        # construction runs on the host every extend, so for large K the
        # per-branch Python loop becomes the tick's overhead
        self._branch_inputs_all = branch_inputs_all

        self._root_frame: Optional[int] = None
        self._count = 0  # host-tracked window length (never read from device)
        self._states: Any = None  # [K, ...] current branch states
        self._traj_buf: Any = None  # [W, K, ...] post-advance states
        self._inp_buf: Any = None  # [W, K, ...] hypothesized inputs
        self._prefix_buf: Optional[jax.Array] = None  # [W, K] cumulative ok
        self._hit_count = jnp.zeros((), jnp.uint32)

        self._root_fn = jax.jit(self._root_impl)
        # donate the [W, K, ...] ring buffers on TPU so the per-tick slot
        # write updates HBM in place instead of copying the whole window
        # (same treatment as ops.replay's carry; donation on CPU is a noisy
        # no-op, so gate it — and warmup() must hand scratch buffers to
        # these programs, never the live ones it restores afterwards)
        on_tpu = jax.default_backend() == "tpu"
        self._extend_fn = jax.jit(
            self._extend_impl, donate_argnums=(1, 2, 3) if on_tpu else ()
        )

        def _adv_ext(live_state, live_inputs, *extend_args):
            return (
                advance(live_state, live_inputs),
                *self._extend_impl(*extend_args),
            )

        self._adv_ext_fn = jax.jit(
            _adv_ext, donate_argnums=(3, 4, 5) if on_tpu else ()
        )
        self._fulfill_cache: Dict[Tuple[int, bool], Any] = {}
        self._fulfill_refill_cache: Dict[Tuple[int, bool], Any] = {}
        self._refill_cache: Dict[int, Any] = {}
        self._resolve_cache: Dict[int, Any] = {}

    # ------------------------------------------------------------------
    # fused programs
    # ------------------------------------------------------------------

    def _match(self, hyp: Any, target: Any) -> jax.Array:
        """[K] mask: which branches' hypothesis pytree equals ``target``."""

        def leaf_eq(h: jax.Array, c: Any) -> jax.Array:
            c = jnp.asarray(c)
            return jnp.all((h == c[None, ...]).reshape(self.K, -1), axis=1)

        eqs = jax.tree_util.tree_map(leaf_eq, hyp, target)
        return jax.tree_util.tree_reduce(
            jnp.logical_and, eqs, jnp.ones((self.K,), bool)
        )

    def _root_impl(self, state: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                jnp.asarray(leaf)[None, ...],
                (self.K,) + jnp.shape(jnp.asarray(leaf)),
            ),
            state,
        )

    def _extend_impl(
        self,
        states: Any,
        traj_buf: Any,
        inp_buf: Any,
        prefix_buf: jax.Array,
        t: jax.Array,
        inputs_k: Any,
        local_inputs: Any,
    ) -> Tuple[Any, Any, Any, jax.Array]:
        new_states = jax.vmap(self._advance)(states, inputs_k)
        step_ok = self._match(inputs_k, local_inputs)
        prev = jnp.where(
            t > 0, prefix_buf[jnp.maximum(t - 1, 0)], jnp.ones((self.K,), bool)
        )
        write = lambda buf, val: jax.tree_util.tree_map(
            lambda b, v: b.at[t].set(v), buf, val
        )
        return (
            new_states,
            write(traj_buf, new_states),
            write(inp_buf, inputs_k),
            prefix_buf.at[t].set(prev & step_ok),
        )

    def _resolve_window(
        self,
        traj_buf: Any,
        inp_buf: Any,
        prefix_buf: jax.Array,
        offset: jax.Array,
        load_state: Any,
        confirmed: Any,  # [n, ...] stacked
        n: int,
        with_checksums: bool,
    ):
        """Traced core shared by every fulfill program: hypothesis matching,
        branch selection, and the fallback replay as one ``lax.cond``.
        Returns ``(steps, sums, hit)`` — the n per-step post-advance states,
        their digests (or None), and the device hit flag."""
        from ..ops.checksum import checksum_device

        sl = lambda buf: jax.tree_util.tree_map(
            lambda b: jax.lax.dynamic_slice_in_dim(b, offset, n, axis=0),
            buf,
        )
        win_inp, win_traj = sl(inp_buf), sl(traj_buf)
        match = jnp.where(
            offset > 0,
            prefix_buf[jnp.maximum(offset - 1, 0)],
            jnp.ones((self.K,), bool),
        )
        frame_at = lambda tree, t: jax.tree_util.tree_map(
            lambda l: l[t], tree
        )
        for t in range(n):
            match = match & self._match(
                frame_at(win_inp, t), frame_at(confirmed, t)
            )
        hit = jnp.any(match)
        idx = jnp.argmax(match)

        def take_branch(_):
            return jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, idx, axis=1, keepdims=False
                ),
                win_traj,
            )

        def replay(_):
            def body(st: Any, inp: Any):
                nxt = self._advance(st, inp)
                return nxt, nxt

            _, ys = jax.lax.scan(body, load_state, confirmed)
            return ys

        out = jax.lax.cond(hit, take_branch, replay, None)
        steps = [frame_at(out, t) for t in range(n)]
        sums = [checksum_device(s) for s in steps] if with_checksums else None
        return steps, sums, hit

    def _extend_scan(self, states0: Any, hyps: Any, session_inputs: Any):
        """Traced core shared by refill programs: extend K branches over the
        [m, K, ...] hypotheses, matching each step against the session's own
        [m, ...] inputs.  Returns (states, traj, prefixes)."""

        def body(carry, xs):
            st, prefix = carry
            hyp_k, sess = xs
            nxt = jax.vmap(self._advance)(st, hyp_k)
            prefix = prefix & self._match(hyp_k, sess)
            return (nxt, prefix), (nxt, prefix)

        (states, _), (traj, prefixes) = jax.lax.scan(
            body,
            (states0, jnp.ones((self.K,), bool)),
            (hyps, session_inputs),
        )
        return states, traj, prefixes

    def _build_fulfill(self, n: int, with_checksums: bool):
        def fulfill(
            traj_buf: Any,
            inp_buf: Any,
            prefix_buf: jax.Array,
            offset: jax.Array,
            load_state: Any,
            confirmed: Any,  # [n, ...] stacked
            hit_count: jax.Array,
        ):
            steps, sums, hit = self._resolve_window(
                traj_buf, inp_buf, prefix_buf, offset, load_state,
                confirmed, n, with_checksums,
            )
            return steps, sums, hit_count + hit.astype(jnp.uint32)

        return jax.jit(fulfill)

    def _build_fulfill_refill(
        self, n: int, with_checksums: bool, with_live: bool = False
    ):
        """fulfill + re-anchor + re-extend as ONE program: the rollback's
        resolve-or-replay, rooting the branches at the window's first frame,
        and re-hypothesizing the confirmed tail — so a speculative rollback
        costs exactly one dispatch, the same as the plain fused replay.

        ``with_live`` additionally fuses the tick's trailing *live* advance
        (the saveless AdvanceFrame that follows every rollback burst) and the
        matching one-frame window extension into the same program: the whole
        rollback tick then costs ONE dispatch, exactly like the plain path's
        single load+replay+advance burst."""
        m = n - 1
        m_ext = m + (1 if with_live else 0)
        on_tpu = jax.default_backend() == "tpu"

        def fused(
            traj_buf: Any,
            inp_buf: Any,
            prefix_buf: jax.Array,
            offset: jax.Array,
            load_state: Any,
            confirmed: Any,  # [n, ...] stacked
            hyps: Any,  # [m_ext, K, ...] stacked (None when m_ext=0)
            hit_count: jax.Array,
            live_inputs: Any = None,  # only when with_live
        ):
            steps, sums, hit = self._resolve_window(
                traj_buf, inp_buf, prefix_buf, offset, load_state,
                confirmed, n, with_checksums,
            )
            # re-anchor at steps[0] and extend the confirmed tail (plus, when
            # fused, the live frame hypothesized against the live inputs)
            states = self._root_impl(steps[0])
            if m_ext:
                tail = jax.tree_util.tree_map(lambda l: l[1:], confirmed)
                if with_live:
                    tail = jax.tree_util.tree_map(
                        lambda c, lv: jnp.concatenate(
                            [c, jnp.asarray(lv)[None]], axis=0
                        ),
                        tail,
                        live_inputs,
                    )
                states, traj, prefixes = self._extend_scan(states, hyps, tail)
                put = lambda buf, val: jax.tree_util.tree_map(
                    lambda b, v: jax.lax.dynamic_update_slice_in_dim(
                        b, v, 0, axis=0
                    ),
                    buf,
                    val,
                )
                traj_buf = put(traj_buf, traj)
                inp_buf = put(inp_buf, hyps)
                prefix_buf = jax.lax.dynamic_update_slice_in_dim(
                    prefix_buf, prefixes, 0, axis=0
                )
            live = (
                self._advance(steps[-1], live_inputs) if with_live else None
            )
            return (
                steps,
                sums,
                hit_count + hit.astype(jnp.uint32),
                states,
                traj_buf,
                inp_buf,
                prefix_buf,
                live,
            )

        return jax.jit(fused, donate_argnums=(0, 1, 2) if on_tpu else ())

    def _build_refill(self, m: int):
        def refill(root_state: Any, hyps: Any, session_inputs: Any):
            """Re-anchor at ``root_state`` and extend ``m`` steps under
            ``hyps`` ([m, K, ...]), matching against ``session_inputs``
            ([m, ...]); returns (states, traj [m,K,...], prefix [m,K])."""
            return self._extend_scan(self._root_impl(root_state), hyps, session_inputs)

        return jax.jit(refill)

    # ------------------------------------------------------------------
    # buffers
    # ------------------------------------------------------------------

    def _ensure_buffers(self, inputs_k: Any) -> None:
        if self._traj_buf is not None:
            return
        W = self.max_window
        alloc = lambda tree: jax.tree_util.tree_map(
            lambda l: jnp.zeros((W,) + jnp.shape(l), jnp.asarray(l).dtype),
            tree,
        )
        self._traj_buf = alloc(self._states)
        self._inp_buf = alloc(
            jax.tree_util.tree_map(jnp.asarray, inputs_k)
        )
        self._prefix_buf = jnp.zeros((W, self.K), bool)

    def _hypotheses(self, frame: int, local_inputs: Any) -> Any:
        if self._branch_inputs_all is not None:
            return self._branch_inputs_all(frame, local_inputs)
        per_branch = [
            self._branch_inputs(k, frame, local_inputs) for k in range(self.K)
        ]
        return _stack_pytrees(per_branch)

    def _window_hypotheses(self, frame: int, inputs_seq: Sequence[Any]) -> Any:
        """Hypotheses for a whole window as ``[m, K, ...]``: branch k's
        inputs for frames ``frame + t`` built from ``inputs_seq[t]``.  Shared
        by ``refill`` and ``fulfill_and_refill`` — their windows must stay
        frame-offset-identical for the fused program's promise
        ("equals refill(frame + 1, steps[0], confirmed[1:])") to hold."""
        if self._branch_inputs_all is not None:
            return _stack_pytrees(
                [
                    self._branch_inputs_all(frame + t, inputs_seq[t])
                    for t in range(len(inputs_seq))
                ]
            )
        hyps = _stack_pytrees(
            [
                _stack_pytrees(
                    [
                        self._branch_inputs(k, frame + t, inputs_seq[t])
                        for t in range(len(inputs_seq))
                    ]
                )
                for k in range(self.K)
            ]
        )
        # built as [K, m, ...]; scan wants [m, K, ...]
        return _swap01(hyps)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @property
    def window(self) -> int:
        return self._count

    @property
    def root_frame(self) -> Optional[int]:
        return self._root_frame

    @property
    def hits(self) -> int:
        """Fetches the device hit counter — call only outside timed paths."""
        return int(jax.device_get(self._hit_count))

    def invalidate(self) -> None:
        """Drop the anchor and the whole window.  Callers MUST invalidate on
        any rollback that is not fulfilled by ``fulfill`` + ``refill``: such a
        rollback disproves the predicted inputs the prefix masks were
        validated against, so the window is unsound from then on.  ``extend``
        no-ops and ``window_valid`` is false until the next ``root``."""
        self._root_frame = None
        self._states = None
        self._count = 0

    def root(self, frame: int, state: Any) -> None:
        """Re-anchor all branches at ``state`` (the save of ``frame``)."""
        self._root_frame = frame
        self._states = self._root_fn(state)
        self._count = 0

    def extend(self, local_inputs: Any) -> None:
        """Advance every branch one frame under its hypothesis — one fused
        dispatch.  The frame being hypothesized is ``root_frame + window``
        (extensions are sequential from the anchor)."""
        if self._root_frame is None or self._count >= self.max_window:
            return
        inputs_k = self._hypotheses(self._root_frame + self._count, local_inputs)
        self._ensure_buffers(inputs_k)
        (
            self._states,
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
        ) = self._extend_fn(
            self._states,
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
            np.int32(self._count),
            inputs_k,
            local_inputs,
        )
        self._count += 1

    def advance_and_extend(self, state: Any, inputs: Any) -> Optional[Any]:
        """Advance the live ``state`` AND extend all K branches in ONE fused
        dispatch — speculation's steady-state tick costs the same dispatch
        count as running without it.  Returns the new live state, or None
        when the window cannot extend (unrooted / full): the caller must then
        advance the live state itself (``extend`` would no-op identically)."""
        if self._root_frame is None or self._count >= self.max_window:
            return None
        inputs_k = self._hypotheses(self._root_frame + self._count, inputs)
        self._ensure_buffers(inputs_k)
        (
            new_state,
            self._states,
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
        ) = self._adv_ext_fn(
            state,
            inputs,
            self._states,
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
            np.int32(self._count),
            inputs_k,
            inputs,
        )
        self._count += 1
        return new_state

    def window_valid(self, frame: int, n: int) -> bool:
        """Host-side check (no device read): can a rollback to ``frame``
        covering ``n`` resimulated frames be answered from this window?"""
        if self._root_frame is None or n < 1:
            return False
        offset = frame - self._root_frame
        return 0 <= offset and offset + n <= self._count

    def fulfill(
        self,
        frame: int,
        confirmed: Sequence[Any],
        load_state: Any,
        with_checksums: bool,
    ) -> Tuple[List[Any], Optional[List[Any]]]:
        """Resolve-or-replay as ONE dispatch: returns the ``n`` per-step
        post-advance states for the rollback window (device handles) and,
        when requested, their device checksum lanes.  The states come from the
        matching branch when one hypothesized exactly these inputs, else from
        the fallback replay of ``load_state`` — the host never reads which.

        Requires ``window_valid(frame, len(confirmed))``.  ``frame`` may lie
        past the root: rollback targets are the first mispredicted frame, so
        every frame between root and target was predicted correctly — a
        branch is valid iff its hypotheses equalled the session's own inputs
        over that prefix (the ``_prefix_buf`` masks) and the confirmed inputs
        from the target on."""
        n = len(confirmed)
        assert self.window_valid(frame, n)
        key = (n, with_checksums)
        fn = self._fulfill_cache.get(key)
        if fn is None:
            fn = self._fulfill_cache[key] = self._build_fulfill(
                n, with_checksums
            )
        steps, sums, self._hit_count = fn(
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
            np.int32(frame - self._root_frame),
            load_state,
            _stack_pytrees(confirmed),
            self._hit_count,
        )
        return steps, sums

    def fulfill_and_refill(
        self,
        frame: int,
        confirmed: Sequence[Any],
        load_state: Any,
        with_checksums: bool,
        live_inputs: Any = None,
    ) -> Union[
        Tuple[List[Any], Optional[List[Any]]],
        Tuple[List[Any], Optional[List[Any]], Any],
    ]:
        """``fulfill`` plus the post-rollback re-anchor/re-extend in ONE
        dispatch: resolve-or-replay the window, root the branches at
        ``frame + 1`` (the next rollback's steady-state target), and
        re-hypothesize the still-unconfirmed tail.  Same return value as
        ``fulfill``; the window afterwards equals ``refill(frame + 1,
        steps[0], confirmed[1:])``.

        With ``live_inputs``, the tick's trailing live advance rides the same
        dispatch: the return gains a third element — the live state
        ``advance(steps[-1], live_inputs)`` — and the window also extends one
        hypothesized frame for the live frame (``frame + n``), exactly as a
        subsequent ``advance_and_extend`` would have."""
        n = len(confirmed)
        assert self.window_valid(frame, n)
        m = n - 1
        with_live = live_inputs is not None
        tail = list(confirmed[1:])
        if with_live:
            tail.append(live_inputs)
        hyps = self._window_hypotheses(frame + 1, tail) if tail else None
        key = (n, with_checksums, with_live)
        fn = self._fulfill_refill_cache.get(key)
        if fn is None:
            fn = self._fulfill_refill_cache[key] = self._build_fulfill_refill(
                n, with_checksums, with_live
            )
        args = [
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
            np.int32(frame - self._root_frame),
            load_state,
            _stack_pytrees(confirmed),
            hyps,
            self._hit_count,
        ]
        if with_live:
            args.append(live_inputs)
        (
            steps,
            sums,
            self._hit_count,
            self._states,
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
            live,
        ) = fn(*args)
        self._root_frame = frame + 1
        self._count = m + (1 if with_live else 0)
        if with_live:
            return steps, sums, live
        return steps, sums

    def refill(self, frame: int, state: Any, local_inputs: Sequence[Any]) -> None:
        """Re-anchor at ``(frame, state)`` and re-extend the still-unconfirmed
        tail (``local_inputs``, one per frame from ``frame`` on) as one fused
        dispatch — the post-rollback replacement for root + N×extend."""
        m = min(len(local_inputs), self.max_window)
        local_inputs = list(local_inputs)[:m]
        self._root_frame = frame
        if m == 0:
            self._states = self._root_fn(state)
            self._count = 0
            return
        hyps = self._window_hypotheses(frame, local_inputs)
        sess = _stack_pytrees(local_inputs)
        fn = self._refill_cache.get(m)
        if fn is None:
            fn = self._refill_cache[m] = self._build_refill(m)
        self._states, traj, prefixes = fn(state, hyps, sess)
        if self._traj_buf is None:
            # allocate from the first hypothesis row; states are already [K,..]
            self._ensure_buffers(
                jax.tree_util.tree_map(lambda l: l[0], hyps)
            )
        put = lambda buf, val: jax.tree_util.tree_map(
            lambda b, v: jax.lax.dynamic_update_slice_in_dim(b, v, 0, axis=0),
            buf,
            val,
        )
        self._traj_buf = put(self._traj_buf, traj)
        self._inp_buf = put(self._inp_buf, hyps)
        self._prefix_buf = jax.lax.dynamic_update_slice_in_dim(
            self._prefix_buf, prefixes, 0, axis=0
        )
        self._count = m

    def warmup(
        self,
        state: Any,
        example_inputs: Any,
        depths: Sequence[int],
        with_checksums: bool,
    ) -> None:
        """Pre-compile every program a live session can dispatch — the fused
        extend, advance+extend, and per-depth fulfill/refill — so no jit
        compile ever stalls the poll/ack pump mid-session.  Runs on scratch
        data; all window state (including the device hit counter) is restored
        afterwards."""
        saved = (
            self._root_frame,
            self._count,
            self._states,
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
            self._hit_count,
        )
        try:
            # fresh scratch buffers: the fused programs donate their ring
            # buffers on TPU, so the saved live buffers must never be
            # handed to them here (they would be invalidated)
            self._traj_buf = None
            self._inp_buf = None
            self._prefix_buf = None
            self.root(0, state)
            self.advance_and_extend(state, example_inputs)
            for n in sorted(set(depths)):
                if not 1 <= n <= self.max_window:
                    continue
                for live in (None, example_inputs):
                    self.root(0, state)
                    for _ in range(n):
                        self.extend(example_inputs)
                    self.fulfill_and_refill(
                        0, [example_inputs] * n, state, with_checksums,
                        live_inputs=live,
                    )
            jax.block_until_ready(self._states)
        finally:
            (
                self._root_frame,
                self._count,
                self._states,
                self._traj_buf,
                self._inp_buf,
                self._prefix_buf,
                self._hit_count,
            ) = saved

    # ------------------------------------------------------------------
    # diagnostic / test API (reads device→host; not for the live path)
    # ------------------------------------------------------------------

    def resolve(
        self, frame: int, confirmed: Sequence[Any]
    ) -> Optional[List[Any]]:
        """Match hypotheses against the ``confirmed`` input arrays for the
        frames from ``frame`` on; returns the matched branch's per-step states
        or None.  Reads the hit flag back to the host — use ``fulfill`` on
        live paths."""
        n = len(confirmed)
        if not self.window_valid(frame, n):
            return None
        fn = self._resolve_cache.get(n)
        if fn is None:

            def resolve_n(
                traj_buf, inp_buf, prefix_buf, offset, confirmed_stacked
            ):
                sl = lambda buf: jax.tree_util.tree_map(
                    lambda b: jax.lax.dynamic_slice_in_dim(
                        b, offset, n, axis=0
                    ),
                    buf,
                )
                win_inp, win_traj = sl(inp_buf), sl(traj_buf)
                match = jnp.where(
                    offset > 0,
                    prefix_buf[jnp.maximum(offset - 1, 0)],
                    jnp.ones((self.K,), bool),
                )
                for t in range(n):
                    match = match & self._match(
                        jax.tree_util.tree_map(lambda l: l[t], win_inp),
                        jax.tree_util.tree_map(lambda l: l[t], confirmed_stacked),
                    )
                hit = jnp.any(match)
                idx = jnp.argmax(match)
                traj = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, idx, axis=1, keepdims=False
                    ),
                    win_traj,
                )
                return hit, traj

            fn = self._resolve_cache[n] = jax.jit(resolve_n)
        hit, traj = fn(
            self._traj_buf,
            self._inp_buf,
            self._prefix_buf,
            np.int32(frame - self._root_frame),
            _stack_pytrees(confirmed),
        )
        if not bool(jax.device_get(hit)):
            return None
        return [
            jax.tree_util.tree_map(lambda l, _t=t: l[_t], traj)
            for t in range(n)
        ]
