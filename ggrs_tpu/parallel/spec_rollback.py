"""Speculative rollback: skip the replay entirely when a branch guessed right.

The reference rolls back and resimulates every time a prediction was wrong
(/root/reference/src/sessions/p2p_session.rs:658-714) — and its single
repeat-last predictor is wrong whenever a remote player changes input.  On
TPU we can afford K predictions at once (`parallel.speculation`): this module
keeps K branch trajectories *incrementally extended each tick* under K
different remote-input hypotheses, so when confirmed inputs arrive and a
rollback is requested, a matching branch turns the whole
load→(advance, save)^N replay into a device-side select.  Misses fall back
to the fused replay — correctness never depends on a hit.

``SpeculativeRollback`` is session-agnostic: it works on input *arrays* (the
same ones the user's ``advance`` consumes).  ``DeviceRequestExecutor`` uses
it through the ``speculation`` constructor argument, keying branches to the
frames of Save/Load requests.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

AdvanceFn = Callable[[Any, Any], Any]
# branch_inputs(branch_k, tick_local_inputs_array) -> full inputs array for
# branch k this frame (local players' real inputs merged with hypothesis k's
# remote inputs)
BranchInputsFn = Callable[[int, Any], Any]


class SpeculativeRollback:
    """K incrementally-extended branch trajectories rooted at a saved frame.

    Usage per tick:
      - ``root(frame, state)`` whenever the rollback anchor moves (a Save of
        the confirmed frame);
      - ``extend(local_inputs)`` once per advanced frame: every branch steps
        under its own hypothesis (ONE vmap dispatch for all K);
      - on rollback to ``frame``: ``resolve(frame, confirmed)`` with the
        confirmed full-input arrays for the window — returns the matched
        branch's trajectory or None (miss → caller replays).
    """

    def __init__(
        self,
        advance: AdvanceFn,
        num_branches: int,
        branch_inputs: BranchInputsFn,
        max_window: int = 16,
    ) -> None:
        assert num_branches >= 1
        self.K = num_branches
        self.max_window = max_window
        self._branch_inputs = branch_inputs
        self._root_frame: Optional[int] = None
        self._states: Any = None  # [K, ...] current branch states
        self._traj: List[Any] = []  # per-step [K, ...] states (post-advance)
        self._inputs: List[Any] = []  # per-step [K, ...] hypothesized inputs

        self._step_all = jax.jit(
            lambda states, inputs_k: jax.vmap(advance)(states, inputs_k)
        )

    # ------------------------------------------------------------------

    @property
    def window(self) -> int:
        return len(self._traj)

    @property
    def root_frame(self) -> Optional[int]:
        return self._root_frame

    def root(self, frame: int, state: Any) -> None:
        """Re-anchor all branches at ``state`` (the save of ``frame``)."""
        self._root_frame = frame
        self._states = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                jnp.asarray(leaf)[None, ...], (self.K,) + jnp.asarray(leaf).shape
            ),
            state,
        )
        self._traj = []
        self._inputs = []

    def extend(self, local_inputs: Any) -> None:
        """Advance every branch one frame under its hypothesis."""
        if self._root_frame is None or len(self._traj) >= self.max_window:
            return
        per_branch = [self._branch_inputs(k, local_inputs) for k in range(self.K)]
        inputs_k = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves]), *per_branch
        )
        self._states = self._step_all(self._states, inputs_k)
        self._traj.append(self._states)
        self._inputs.append(inputs_k)

    def resolve(
        self, frame: int, confirmed: Sequence[Any]
    ) -> Optional[List[Any]]:
        """Match hypotheses against the ``confirmed`` input arrays for the
        frames after ``frame``.  On a hit, returns the per-step states of the
        matching branch (``len(confirmed)`` entries, post-advance each step);
        on any miss condition, returns None."""
        n = len(confirmed)
        if (
            self._root_frame is None
            or frame != self._root_frame
            or n == 0
            or n > len(self._traj)
        ):
            return None

        match = jnp.ones((self.K,), bool)
        for step, conf in enumerate(confirmed):
            hyp = self._inputs[step]

            def leaf_eq(h: jax.Array, c: Any) -> jax.Array:
                c = jnp.asarray(c)
                return jnp.all(
                    (h == c[None, ...]).reshape(self.K, -1), axis=1
                )

            eqs = jax.tree_util.tree_map(leaf_eq, hyp, conf)
            match = match & jax.tree_util.tree_reduce(
                jnp.logical_and, eqs, jnp.ones((self.K,), bool)
            )
        idx = jnp.argmax(match)
        if not bool(jnp.any(match)):  # one scalar read per rollback
            return None
        take = lambda tree: jax.tree_util.tree_map(
            lambda leaf: jax.lax.dynamic_index_in_dim(
                leaf, idx, axis=0, keepdims=False
            ),
            tree,
        )
        return [take(self._traj[step]) for step in range(n)]
