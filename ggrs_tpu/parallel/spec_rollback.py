"""Speculative rollback: skip the replay entirely when a branch guessed right.

The reference rolls back and resimulates every time a prediction was wrong
(/root/reference/src/sessions/p2p_session.rs:658-714) — and its single
repeat-last predictor is wrong whenever a remote player changes input.  On
TPU we can afford K predictions at once (`parallel.speculation`): this module
keeps K branch trajectories *incrementally extended each tick* under K
different remote-input hypotheses, so when confirmed inputs arrive and a
rollback is requested, a matching branch turns the whole
load→(advance, save)^N replay into a device-side select.  Misses fall back
to the fused replay — correctness never depends on a hit.

``SpeculativeRollback`` is session-agnostic: it works on input *arrays* (the
same ones the user's ``advance`` consumes).  ``DeviceRequestExecutor`` uses it
through its ``speculation`` constructor argument: it anchors (``root``) the
branches at the first save of each rollback burst, ``extend``s them on every
executed advance, and ``resolve``s against the burst inputs on every Load —
see ``ops.executor`` and ``tests/test_spec_integration.py``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

AdvanceFn = Callable[[Any, Any], Any]
# branch_inputs(branch_k, frame, tick_inputs_array) -> full inputs array for
# branch k at ``frame`` (local players' real inputs merged with hypothesis
# k's remote inputs; the session's own prediction arrives as ``tick_inputs``
# so the identity function is the "trust the predictor" branch)
BranchInputsFn = Callable[[int, int, Any], Any]


class SpeculativeRollback:
    """K incrementally-extended branch trajectories rooted at a saved frame.

    Usage per tick:
      - ``root(frame, state)`` whenever the rollback anchor moves (a Save of
        the confirmed frame);
      - ``extend(local_inputs)`` once per advanced frame: every branch steps
        under its own hypothesis (ONE vmap dispatch for all K);
      - on rollback to ``frame``: ``resolve(frame, confirmed)`` with the
        confirmed full-input arrays for the window — returns the matched
        branch's trajectory or None (miss → caller replays).
    """

    def __init__(
        self,
        advance: AdvanceFn,
        num_branches: int,
        branch_inputs: BranchInputsFn,
        max_window: int = 16,
    ) -> None:
        assert num_branches >= 1
        self.K = num_branches
        self.max_window = max_window
        self._branch_inputs = branch_inputs
        self._root_frame: Optional[int] = None
        self._states: Any = None  # [K, ...] current branch states
        self._traj: List[Any] = []  # per-step [K, ...] states (post-advance)
        self._inputs: List[Any] = []  # per-step [K, ...] hypothesized inputs
        # per-step cumulative [K] mask: hypothesis equalled the session's own
        # input array for every step so far (supports resolving at an offset
        # past the root, see resolve())
        self._prefix_ok: List[jax.Array] = []

        self._step_all = jax.jit(
            lambda states, inputs_k: jax.vmap(advance)(states, inputs_k)
        )

    def _match_step(self, hyp: Any, target: Any) -> jax.Array:
        """[K] mask: which branches' step hypothesis equals ``target``."""

        def leaf_eq(h: jax.Array, c: Any) -> jax.Array:
            c = jnp.asarray(c)
            return jnp.all((h == c[None, ...]).reshape(self.K, -1), axis=1)

        eqs = jax.tree_util.tree_map(leaf_eq, hyp, target)
        return jax.tree_util.tree_reduce(
            jnp.logical_and, eqs, jnp.ones((self.K,), bool)
        )

    # ------------------------------------------------------------------

    @property
    def window(self) -> int:
        return len(self._traj)

    @property
    def root_frame(self) -> Optional[int]:
        return self._root_frame

    def invalidate(self) -> None:
        """Drop the anchor and all trajectories.  Callers MUST invalidate on
        any rollback that is not fulfilled by ``resolve`` + a fresh ``root``:
        a rollback disproves the predicted inputs the prefix masks were
        validated against, so the whole window is unsound from then on.
        ``extend`` no-ops and ``resolve`` misses until the next ``root``."""
        self._root_frame = None
        self._states = None
        self._traj = []
        self._inputs = []
        self._prefix_ok = []

    def root(self, frame: int, state: Any) -> None:
        """Re-anchor all branches at ``state`` (the save of ``frame``)."""
        self._root_frame = frame
        self._states = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                jnp.asarray(leaf)[None, ...], (self.K,) + jnp.asarray(leaf).shape
            ),
            state,
        )
        self._traj = []
        self._inputs = []
        self._prefix_ok = []

    def extend(self, local_inputs: Any) -> None:
        """Advance every branch one frame under its hypothesis.  The frame
        being hypothesized is ``root_frame + window`` (extensions are
        sequential from the anchor)."""
        if self._root_frame is None or len(self._traj) >= self.max_window:
            return
        frame = self._root_frame + len(self._traj)
        per_branch = [
            self._branch_inputs(k, frame, local_inputs) for k in range(self.K)
        ]
        inputs_k = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves]), *per_branch
        )
        self._states = self._step_all(self._states, inputs_k)
        # which branches hypothesized exactly what the session itself used
        # this frame (local real inputs + the predictor's remote guesses)
        step_ok = self._match_step(inputs_k, local_inputs)
        prev = self._prefix_ok[-1] if self._prefix_ok else jnp.ones((self.K,), bool)
        self._traj.append(self._states)
        self._inputs.append(inputs_k)
        self._prefix_ok.append(prev & step_ok)

    def resolve(
        self, frame: int, confirmed: Sequence[Any]
    ) -> Optional[List[Any]]:
        """Match hypotheses against the ``confirmed`` input arrays for the
        frames from ``frame`` on.  On a hit, returns the per-step states of
        the matching branch (``len(confirmed)`` entries, post-advance each
        step, the first being the state at ``frame + 1``); on any miss
        condition, returns None.

        ``frame`` may lie *past* the root: rollback targets are the first
        mispredicted frame, so every frame between the root and the target
        was predicted correctly — a branch is then valid iff its hypotheses
        equalled the session's own inputs over that prefix (tracked
        incrementally in ``_prefix_ok``) and the confirmed inputs from the
        target on."""
        n = len(confirmed)
        if self._root_frame is None or n == 0:
            return None
        offset = frame - self._root_frame
        if offset < 0 or offset + n > len(self._traj):
            return None

        match = (
            self._prefix_ok[offset - 1]
            if offset > 0
            else jnp.ones((self.K,), bool)
        )
        for t, conf in enumerate(confirmed):
            match = match & self._match_step(self._inputs[offset + t], conf)
        idx = jnp.argmax(match)
        if not bool(jnp.any(match)):  # one scalar read per rollback
            return None
        take = lambda tree: jax.tree_util.tree_map(
            lambda leaf: jax.lax.dynamic_index_in_dim(
                leaf, idx, axis=0, keepdims=False
            ),
            tree,
        )
        return [take(self._traj[offset + t]) for t in range(n)]
