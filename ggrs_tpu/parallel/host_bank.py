"""Host session pool: step B P2P sessions' per-tick protocol + sync
mechanism in ONE ctypes crossing per pool tick.

The round-5 capacity knee was ~90% host bookkeeping, and the per-operation
native cores measured perf-neutral because ~200 ctypes crossings per
session-tick hand back what the C++ saves (docs/ROUND5.md §4).  This module
is the located fix: ``HostSessionPool`` drives every pooled session's tick —
input enqueue, prediction/confirmation watermarks, endpoint timers, ack
trim, outbound InputMessage assembly — through ``native/session_bank.cpp``
off a single packed command buffer per tick.

POLICY STAYS HERE, in Python: GgrsEvent emission, the disconnect consensus
(:meth:`P2PSession._update_player_disconnects` semantics, applied as next
tick's control ops), wait-recommendation pacing, and the construction of the
``GgrsRequest`` lists the game fulfills.  The request grammar and the public
per-session observables (``current_frame``, ``last_confirmed_frame``,
``events``, landed frames) are unchanged from ``sessions/p2p.py``.

FALLBACK: when the native library is unavailable (``GGRS_TPU_NO_NATIVE``,
no toolchain) or any session's shape is outside the bank's mechanism
(sparse saving, lockstep, spectators, desync detection, handshake,
variable-size inputs), the pool transparently drives ordinary per-session
``P2PSession`` objects — the untouched semantic reference.  Parity between
the two paths is pinned by tests/test_session_bank.py: bit-identical wire
bytes, frames, and events under seeded loss/dup/reorder traffic.

Known one-tick-late behaviors on the native path (documented divergence,
exercised only in disconnect scenarios; the fallback is exact): reactions
to ``Disconnected`` protocol events and disconnect-consensus adjustments
are computed from this tick's mirrors and applied as next tick's control
ops.

FAULT ISOLATION (the supervision layer): sharing one C++ bank across B
sessions must not share one blast radius.  The native bank reports
per-session error codes in its output records instead of failing the tick
(session_bank.cpp); on a slot fault this pool QUARANTINES the slot (its
command segment shrinks to a skip flag; the other B-1 sessions keep the
one-crossing-per-tick path), harvests the slot's last committed state
(``ggrs_bank_harvest`` — a one-off extra crossing), and EVICTS it to a
freshly-built Python ``P2PSession`` that resumes the same match from the
last committed frame via the adoption seam
(``P2PSession.adopt_resume_state``).  Eviction retries with backoff a
bounded number of times; an unrecoverable slot is marked DEAD and its
request lists go empty.  The same per-slot containment wraps the Python
fallback path (a session whose tick raises is marked dead; the rest keep
ticking).  Chaos hooks (``inject_datagram``, ``inject_slot_error``) let
tests and ``scripts/chaos.py`` drive faults through the real tick path;
tests/test_bank_faults.py pins blast radius = 1 slot with the survivors
bit-identical to a fault-free run.

NATIVE I/O (DESIGN.md §15): with ``native_io=True`` each slot's UDP fd is
attached to the kernel-batched datapath (native/net_batch.cpp) and the
tick crossing becomes ``ggrs_bank_pump``: datagrams flow socket →
crossing → socket through recvmmsg/sendmmsg with ZERO Python on the
packet path — same wire bytes, same send order (pinned by
tests/test_native_io.py under seeded loss/dup/reorder), one receive
drain + one send flush per slot per tick instead of one syscall per
datagram.  Fallback is per-slot and automatic: unattachable sockets
(in-memory networks, wrappers without fileno, unresolvable addresses,
non-Linux, GGRS_TPU_NO_NATIVE_IO) keep the exact Python shuttle below.

DESCRIPTOR PLANE (DESIGN.md §21): the quiet tick's remaining per-slot
Python is gone on both sides of the crossing.  ``stage_inputs`` stages
all B local inputs through ONE ``ggrs_bank_stage_inputs`` crossing (a
packed jump-table of staging records; the cmd stream then carries a
flag byte per slot instead of inline input bytes); ``advance_all``
returns a lazy :class:`RequestPlan` built from the tick output's two
leading fixed-stride tables (the §19 header + a per-slot request
descriptor), materializing a slot's pooled ``GgrsRequest`` objects only
when indexed — ``BatchedRequestExecutor`` consumes the flat columns
directly and builds its device dispatch with NumPy; and fast slots'
outbound datagrams flush through one ``ggrs_net_send_table`` crossing
(fd-backed sockets, zero-copy out of the tick output buffer) or one
``send_datagram_batch`` call per socket.  Parity with the reference
decoder (``GGRS_TPU_NO_FASTPATH=1``) is pinned by
tests/test_descriptor_plane.py.

OBSERVABILITY (PR 3, DESIGN.md §12): the pool is the obs subsystem's main
instrumented surface.  Counters/gauges land in a ``ggrs_tpu.obs.Registry``
(constructor argument; the process-wide default when omitted), a per-slot
``FlightRecorder`` keeps the last events (state changes, faults, rollback
decisions, outbound wire digests) and is dumped on quarantine/eviction,
and ``scrape()`` harvests every slot's protocol/sync counters — ping,
kbps, send-queue length, last-acked frame, rollback depth, frame
advantage both ways — through ``ggrs_bank_stats`` in ONE extra ctypes
crossing per scrape (cached per tick; ``advance_all``'s own crossing
count is untouched).  ``network_stats(index, handle)`` rides the same
harvest and returns the exact ``NetworkStats`` shape
``P2PSession.network_stats`` does, for NATIVE, QUARANTINED and EVICTED
slots alike.  Everything here is observational only: the chaos suite pins
survivors' wire bytes bit-identical with metrics enabled vs disabled.
"""

from __future__ import annotations

import copy
import ctypes
import os
import pickle
import random
import socket as _pysocket
import struct
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.errors import (
    BadPlayerHandle,
    GgrsError,
    InvalidRequest,
    NotSynchronized,
    StatsUnavailable,
)
from ..core.sync_layer import SavedStates
from ..core.types import (
    AdvanceFrame,
    Disconnected,
    Frame,
    GgrsRequest,
    InputStatus,
    LoadGameState,
    NetworkInterrupted,
    NetworkResumed,
    NULL_FRAME,
    SaveGameState,
    SessionState,
    WaitRecommendation,
)
from ..net import _native
from ..net.messages import RawMessage
from ..net.protocol import (
    MAX_CHECKSUM_HISTORY_SIZE,
    UDP_HEADER_SIZE,
    draw_magic,
)
from ..net.stats import NetworkStats
from ..obs.recorder import (
    EV_DESYNC,
    EV_EVICT,
    EV_FAULT,
    EV_ROLLBACK,
    EV_STATE,
    EV_WIRE,
    FlightRecorder,
)
from ..obs.registry import Registry, default_registry
from ..obs.trace import NULL_TRACER, Tracer
from ..obs.forensics import DesyncReport, build_desync_report
# timeline event name (DESIGN.md §28) — aliased: the flight recorder
# above already owns the bare EV_* namespace in this module
from ..obs.timeline import EV_DEMOTE_LOCKSTEP as TL_DEMOTE_LOCKSTEP
from ..utils.tracing import get_logger, trace_span
from ..sessions.p2p import (
    MAX_EVENT_QUEUE_SIZE,
    MIN_RECOMMENDATION,
    RECOMMENDATION_INTERVAL,
)

_logger = get_logger("obs")

_STATUS = (
    InputStatus.CONFIRMED,
    InputStatus.PREDICTED,
    InputStatus.DISCONNECTED,
)

# bank event kinds (session_bank.cpp EvKind)
_EV_INTERRUPTED = 1
_EV_RESUMED = 2
_EV_DISCONNECTED = 3
_EV_CHECKSUM = 4

# ---- vectorized policy plane (DESIGN.md §19) -----------------------------
# Packed per-tick output header: one fixed-stride record per slot leads the
# tick output (session_bank.cpp kHdr*), classified here with a handful of
# NumPy ops.  Quiet slots — live, no events, no spectator streams, no
# consensus, no status-mirror changes — take a fast path that refills
# pooled GgrsRequest objects (per-kind per-slot caches; rollback-resim
# ticks reuse the same objects too) and jumps over the events / status
# mirror / spectator-tail sections instead of parsing them positionally.
_HDR_DTYPE = np.dtype(list(_native.BANK_HDR_FIELDS))
# ---- descriptor plane (DESIGN.md §21) -----------------------------------
# The request descriptor table (one fixed-stride record per slot, after the
# header table), the batched input-staging record, and the batched outbound
# send record — all mirrored from session_bank.cpp / net_batch.cpp and
# pinned by the ggrs-verify layout contract.
_REQ_DTYPE = np.dtype(list(_native.BANK_REQ_FIELDS))
_STAGE_DTYPE = np.dtype(list(_native.BANK_STAGE_FIELDS))
_SEND_DTYPE = np.dtype(list(_native.NET_SEND_FIELDS))
_RECV_DTYPE = np.dtype(list(_native.NET_RECV_FIELDS))
# per-session command flag bytes (session_bank.cpp kFlag*, mirrored as
# _native.CMD_FLAG_*; ggrs-verify pins the pairs equal)
_CMD_INPUTS = bytes([_native.CMD_FLAG_INPUTS])
_CMD_SKIP = bytes([_native.CMD_FLAG_SKIP])
_CMD_STAGED = bytes([_native.CMD_FLAG_INPUTS | _native.CMD_FLAG_STAGED])
# resume bundles cross process (and, with the fleet layer, host)
# boundaries: pin the pickle protocol so a mixed-version fleet reads
# every bundle.  This layer cannot import fleet, so the value re-declares
# fleet.rpc.PICKLE_PROTOCOL — ggrs-verify's py<->py mirror check pins
# the pair equal.
_BUNDLE_PICKLE_PROTOCOL = 4
_HDR_FAST_WANT = _native.BANK_HDR_LIVE
_HDR_FAST_MASK = (
    _HDR_FAST_WANT
    | _native.BANK_HDR_EVENTS
    | _native.BANK_HDR_SPEC
    | _native.BANK_HDR_CONSENSUS
    | _native.BANK_HDR_DIRTY
    | _native.BANK_HDR_SKIP
)

# Lazy event decoding: the policy section stages cheap tagged tuples in the
# mirror's event queue; real GgrsEvent objects are constructed only when a
# consumer actually drains them (``events()``, eviction's pending_events,
# the export bundle).  Tags deliberately unhashable-free plain strings.
_LZ_INTERRUPTED = "i"
_LZ_RESUMED = "r"
_LZ_DISCONNECTED = "d"
_LZ_WAIT = "w"


def _materialize_events(queue) -> List[Any]:
    """Construct the public ``GgrsEvent`` objects from a mirror's staged
    event queue (lazily-decoded tuples; already-constructed events pass
    through untouched — eviction hand-off re-queues real objects)."""
    out: List[Any] = []
    for ev in queue:
        if type(ev) is not tuple:
            out.append(ev)
        elif ev[0] == _LZ_INTERRUPTED:
            out.append(NetworkInterrupted(addr=ev[1],
                                          disconnect_timeout=ev[2]))
        elif ev[0] == _LZ_RESUMED:
            out.append(NetworkResumed(addr=ev[1]))
        elif ev[0] == _LZ_DISCONNECTED:
            out.append(Disconnected(addr=ev[1]))
        else:  # _LZ_WAIT
            out.append(WaitRecommendation(skip_frames=ev[1]))
    return out

# receive staging caps shared with NativeEndpointCore: a session whose
# worst-case input packet could overflow them must stay on the fallback
# (the bank drops cap-exceeding packets instead of re-decoding in Python)
_RECV_CAP_BYTES = 1 << 16
_RECV_CAP_FRAMES = 512
_WORST_CASE_FRAMES = 192  # 128-deep pending window with generous slack

# slot supervision states (the fault-isolation layer)
SLOT_NATIVE = "native"          # stepped by the bank (or the py fallback)
SLOT_QUARANTINED = "quarantined"  # faulted; eviction pending/backing off
SLOT_EVICTED = "evicted"        # resumed on a per-session Python P2PSession
SLOT_DEAD = "dead"              # unrecoverable; request lists stay empty
SLOT_MIGRATED = "migrated"      # exported to another pool (fleet layer);
#                                 behaves like dead here — the match lives on

# The declared supervision transition table (DESIGN.md §9, §22): every
# ``_set_slot_state`` call site performs an edge from this table.  The
# ggrs-model conformance lint (analysis/conformance.py) proves the
# code-performed transitions are a subset of it, and the §9 supervision
# model (analysis/machines.py) is built by parsing this tuple from
# source — so an edge added here without a model update, or a call site
# added without an edge here, fails `scripts/ggrs_verify.py`.  DEAD and
# MIGRATED are absorbing: no edge leaves them.
SLOT_TRANSITIONS = (
    (SLOT_NATIVE, SLOT_QUARANTINED),   # bank fault -> quarantine
    (SLOT_NATIVE, SLOT_DEAD),          # match retired / fallback tick fault
    (SLOT_NATIVE, SLOT_MIGRATED),      # live-migration commit
    (SLOT_NATIVE, SLOT_EVICTED),       # load-shed demotion -> lockstep tier
    (SLOT_QUARANTINED, SLOT_EVICTED),  # eviction succeeded
    (SLOT_QUARANTINED, SLOT_DEAD),     # eviction attempts exhausted
    (SLOT_QUARANTINED, SLOT_MIGRATED),
    (SLOT_EVICTED, SLOT_DEAD),         # fallback tick fault / match retired
    (SLOT_EVICTED, SLOT_MIGRATED),
)
_SLOT_TRANSITION_SET = frozenset(SLOT_TRANSITIONS)

# eviction retry policy: attempt n+1 waits n * backoff ticks PLUS a
# deterministic per-slot jitter draw; after the bounded attempts the slot
# is marked dead.  The jitter decorrelates a shard-wide failure (N slots
# quarantined on the same tick) so the retries do not all land on the same
# tick cadence, and EVICT_MAX_PER_TICK clamps how many eviction attempts
# one supervision pass may run — the rest stay quarantined and retry next
# tick (a retry storm must never turn one bad tick into a stalled pool).
EVICT_MAX_ATTEMPTS = 3
EVICT_BACKOFF_TICKS = 8
EVICT_MAX_PER_TICK = 4


def _evict_jitter(index: int, attempt: int) -> int:
    """Deterministic backoff jitter in ``[0, EVICT_BACKOFF_TICKS)``: a
    stateless hash of (slot, attempt) so identical runs stay bit-identical
    (the control/chaos comparison contract) while co-quarantined slots
    draw different delays."""
    h = ((index + 1) * 2654435761 + attempt * 40503) & 0xFFFFFFFF
    h ^= h >> 16
    return h % EVICT_BACKOFF_TICKS


def _select_resume_frame(h: Dict[str, Any], saved_states):
    """Resume from the newest frame whose save the game actually
    fulfilled.  Normally that is the confirmed watermark, but a fault
    tick can raise the watermark and then have its own save op
    suppressed (native fault after set_last_confirmed, or a send
    failure dropping the parsed requests) — then the watermark-1 cell
    is the newest committed state, and the harvest keeps that frame's
    inputs precisely for this case.  Frames at or below the watermark
    can never hold misprediction state (the watermark cannot pass the
    first incorrect frame), so either cell is sound to resume from.
    Shared by eviction (``_evict``) and the fleet export seam
    (``export_resume_state``); returns ``(frame, cell)``."""
    for r in (h["last_confirmed"], h["last_confirmed"] - 1):
        if r < 0:
            continue
        c = saved_states.get_cell(r)
        if c.frame != r:
            continue
        if any(blobs and start > r for start, blobs in h["player_inputs"]):
            continue  # harvested inputs do not reach back to r
        return r, c
    raise RuntimeError(
        f"no committed resumable frame at or below "
        f"{h['last_confirmed']} (unfulfilled saves?)"
    )


class SlotFault:
    """One fault-log entry for a pool slot."""

    __slots__ = ("tick", "code", "detail")

    def __init__(self, tick: int, code: int, detail: str):
        self.tick = tick
        self.code = code
        self.detail = detail

    def __repr__(self) -> str:  # pragma: no cover
        return f"SlotFault(tick={self.tick}, code={self.code}, {self.detail!r})"


def _uvarint_len(v: int) -> int:
    n = 1
    while v >= 0x80:
        v >>= 7
        n += 1
    return n


def _phase_names(n_ph: int):
    """The ``n_ph`` phase names for a timing tail: ``_native.BANK_PHASES``
    padded with generic names when the loaded library is newer than this
    driver (shared by the tick-tail and stats-tail parsers)."""
    names = _native.BANK_PHASES
    if n_ph <= len(names):
        return names[:n_ph]
    return names + tuple(f"phase{i}" for i in range(len(names), n_ph))


def _bank_eligible(builder, hub_active: bool = False) -> bool:
    """Can this builder's session run on the native bank mechanism?  The
    checks mirror the bank's scope; anything outside it uses the Python
    sessions (identical semantics, per-session cost).

    ``hub_active``: a ``broadcast.SpectatorHub`` owns spectator relaying
    for this pool AND the loaded library carries the broadcast entry
    points.  A match with spectators is then bank-eligible — the bank fans
    the confirmed-input stream out natively inside the tick crossing.
    Hubless callers (and pre-broadcast libraries) keep the historical
    behavior: spectator matches fall back to per-session Python sessions,
    whose own relay path is the semantic reference."""
    cfg = builder._config
    from ..core.sync_layer import _native_sync_semantics_ok
    from ..core.types import Spectator

    if not _native_sync_semantics_ok(cfg):
        return False
    if builder._sparse_saving or builder._max_prediction < 1:
        return False  # sparse saving / lockstep: fallback policy paths
    if builder._desync_detection.enabled or builder._sync_handshake:
        return False
    if builder._local_players < 1 or builder._num_players > 64:
        return False
    if not hub_active and any(
        isinstance(t, Spectator) for t in builder._player_reg.handles.values()
    ):
        return False
    # worst-case packet must fit the native staging caps
    size = cfg.native_input_size
    per_frame = builder._num_players * (size + _uvarint_len(size))
    if _WORST_CASE_FRAMES * per_frame > _RECV_CAP_BYTES:
        return False
    if _WORST_CASE_FRAMES > _RECV_CAP_FRAMES:
        return False
    return True


class RequestPlan:
    """One tick's request lists as a lazily-materializing sequence
    (descriptor plane, DESIGN.md §21).

    ``advance_all()`` returns this on the descriptor path.  It behaves
    like the ``List[List[GgrsRequest]]`` it replaces — ``len``, indexing,
    iteration, and in-place assignment all work — but a fast-path slot's
    pooled ``GgrsRequest`` objects are only constructed when someone
    actually indexes that slot (``plan[i]`` / ``pool.requests_for(i)``).
    ``BatchedRequestExecutor`` never does: it consumes the flat descriptor
    columns below directly and builds its device dispatch with NumPy,
    constructing zero request objects for quiet slots.

    Lifetime: like the pooled request lists before it, a plan is valid
    until the NEXT ``advance_all`` on its pool (the columns view the
    pool's reused output buffer).  Materializing a stale plan raises.

    Executor-facing columns (all referring to the tick output buffer):

    ``quiet_rows``/``quiet_frames``  slot indices whose tick is exactly
        [save f, advance], and f per row;
    ``resim_rows``  ``(slot, load_frame, n_adv, trailing, adv_off,
        adv_stride)`` per rollback-resim slot (absolute buffer offsets);
    ``save_only_rows``  ``(slot, frame)`` per prediction-limit slot;
    ``eager_rows``  slots whose lists were materialized at build time
        (slow/other/skip slots) — consume via ``plan[i]``;
    ``gather_quiet()``  the quiet rows' advance payloads as
        ``(statuses [k, players] u8, blobs [k, players, isize] u8)``,
        one fancy-index gather, uniform pools only.
    """

    __slots__ = (
        "pool", "tick_no", "lists", "buffer", "players", "input_size",
        "uniform", "quiet_rows", "quiet_frames", "quiet_offs",
        "quiet_adv_off", "resim_rows", "save_only_rows", "eager_rows",
        "offs_l", "live_l",
    )

    def __init__(self, pool, n: int):
        self.pool = pool
        self.tick_no = pool._tick_no
        self.lists: List[Optional[List[GgrsRequest]]] = [None] * n
        self.buffer: Optional[np.ndarray] = None
        self.players = 0
        self.input_size = 0
        self.uniform = False
        self.quiet_rows: Optional[np.ndarray] = None
        self.quiet_frames: Optional[np.ndarray] = None
        self.quiet_offs: Optional[np.ndarray] = None
        self.quiet_adv_off: Optional[np.ndarray] = None
        self.resim_rows: List[Tuple[int, int, int, bool, int, int]] = []
        self.save_only_rows: List[Tuple[int, int]] = []
        self.eager_rows: List[int] = []
        self.offs_l: List[int] = []
        self.live_l: List[bool] = []

    def __len__(self) -> int:
        return len(self.lists)

    def __getitem__(self, i):
        if isinstance(i, slice):
            # list parity: a slice of request lists, members materialized
            return [self[k] for k in range(*i.indices(len(self.lists)))]
        lst = self.lists[i]
        if lst is None:
            lst = self.lists[i] = self.pool._materialize_slot(self, i)
        return lst

    def __setitem__(self, i: int, value: List[GgrsRequest]) -> None:
        self.lists[i] = value

    def __iter__(self):
        for i in range(len(self.lists)):
            yield self[i]

    def saved_states(self, i: int):
        """Slot ``i``'s ``SavedStates`` ring — where the executor's
        descriptor path fulfills save cells without request objects."""
        return self.pool._mirrors[i].saved_states

    def gather_quiet(self) -> Tuple[np.ndarray, np.ndarray]:
        """All quiet rows' advance payloads in one fancy-index gather."""
        rows = self.quiet_rows
        k = int(rows.size)
        players, isize = self.players, self.input_size
        base = self.quiet_offs + self.quiet_adv_off
        span = players * (1 + isize)
        flat = self.buffer[base[:, None] + np.arange(span)]
        statuses = flat[:, :players]
        blobs = flat[:, players:].reshape(k, players, isize)
        return statuses, blobs


class _EndpointMirror:
    """Python-side view of one bank endpoint: identity plus the state the
    consensus / event policy reads."""

    __slots__ = (
        "addr", "handles", "magic", "running",
        "peer_disc", "peer_last", "pending_checksums",
    )

    def __init__(self, addr, handles: List[int], magic: int, players: int):
        self.addr = addr
        self.handles = handles
        self.magic = magic
        self.running = True
        self.peer_disc = [False] * players
        self.peer_last = [NULL_FRAME] * players
        self.pending_checksums: Dict[Frame, int] = {}


class _SpectatorMirror:
    """Python-side view of one native fan-out (spectator) endpoint: the
    identity plus the hub-facing state (attach handles, liveness, the ack
    watermark the catchup-lag gauge reads, and the one-tick datagram
    deferral that reproduces the Python session's flush order)."""

    __slots__ = ("addr", "magic", "handles", "running", "last_acked",
                 "deferred")

    def __init__(self, addr, magic: int, handles: List[int]):
        self.addr = addr
        self.magic = magic
        self.handles = handles  # builder spectator handles ([] = hub-joined)
        self.running = True
        self.last_acked: Frame = NULL_FRAME
        self.deferred: List[bytes] = []  # fan-out datagrams, sent next tick


class _SessionMirror:
    """Python-side policy state for one bank session."""

    __slots__ = (
        "config", "socket", "num_players", "max_prediction", "input_size",
        "local_handles", "local_handle_set", "endpoints", "addr_to_ep",
        "saved_states", "current_frame", "last_confirmed", "frames_ahead",
        "local_disc", "local_last", "event_queue", "next_recommended_sleep",
        "staged_inputs", "pending_ctrl",
        "spectators", "addr_to_spec", "next_spec_frame", "send_raw",
        # vectorized policy plane (DESIGN.md §19): the byte length of this
        # slot's status-mirror section (to jump to the broadcast tail
        # without parsing) and the pooled request-object caches the fast
        # path refills in place — valid until the next advance_all, like
        # the scrape records
        "mirror_len", "pooled_list", "pool_saves", "pool_loads",
        "pool_advs",
        # descriptor plane (DESIGN.md §21): the set of handles staged
        # NATIVELY this tick (ggrs_bank_stage_inputs — the blobs live in
        # the bank, only membership is tracked here), the socket's batched
        # raw-send entry when it has one, and the cached input encoder
        "staged_native", "send_batch", "encode",
    )

    def __init__(self, config, socket, num_players, max_prediction,
                 local_handles):
        self.config = config
        self.socket = socket
        self.num_players = num_players
        self.max_prediction = max_prediction
        self.input_size = config.native_input_size
        self.local_handles = local_handles
        self.local_handle_set = set(local_handles)
        self.endpoints: List[_EndpointMirror] = []
        self.addr_to_ep: Dict[Any, int] = {}
        self.saved_states = SavedStates(max_prediction)
        self.current_frame: Frame = 0
        self.last_confirmed: Frame = NULL_FRAME
        self.frames_ahead = 0
        self.local_disc = [False] * num_players
        self.local_last = [NULL_FRAME] * num_players
        self.event_queue: deque = deque()
        self.next_recommended_sleep: Frame = 0
        self.staged_inputs: Dict[int, bytes] = {}
        self.pending_ctrl: List[Tuple[int, int, Frame]] = []
        # broadcast fan-out (hub-owned): mirrors of the slot's native
        # spectator endpoints, plus the next-frame cursor the attach policy
        # reads (native truth, refreshed from every tick's broadcast tail)
        self.spectators: List[_SpectatorMirror] = []
        self.addr_to_spec: Dict[Any, int] = {}
        self.next_spec_frame: Frame = 0
        # raw datagram send: the socket's send_datagram when it has one
        # (no RawMessage wrapper, no re-encode), else a send_to shim —
        # bound once at finalization, called per outbound datagram
        send = getattr(socket, "send_datagram", None)
        if send is None:
            send = lambda data, addr, _s=socket: _s.send_to(  # noqa: E731
                RawMessage(data), addr
            )
        self.send_raw = send
        # batched outbound (§21): one send_datagram_batch call per slot
        # per tick when the socket offers it; None keeps the per-datagram
        # send_raw path (wrapped/recording sockets — the reference leg)
        self.send_batch = getattr(socket, "send_datagram_batch", None)
        # batched staging (§21)
        self.staged_native: set = set()
        self.encode = config.input_encode
        # vectorized policy plane: filled by _finalize on the native path.
        # The pools grow to the deepest tick seen (rollback resims append
        # extra save/advance pairs) and are reused in place from then on.
        self.mirror_len = 0
        self.pooled_list: List[Any] = []
        self.pool_saves: List[SaveGameState] = []
        self.pool_loads: List[LoadGameState] = []
        self.pool_advs: List[AdvanceFrame] = []

    def push_event(self, event) -> None:
        """Queue one event — either a real GgrsEvent or a lazily-decoded
        tag tuple (``_materialize_events`` constructs the public objects
        when a consumer drains the queue)."""
        self.event_queue.append(event)
        while len(self.event_queue) > MAX_EVENT_QUEUE_SIZE:
            self.event_queue.popleft()


class HostSessionPool:
    """B pooled host sessions, one mechanism crossing per tick.

    Usage (single-threaded, like every session object)::

        pool = HostSessionPool()
        for builder, socket in matches:
            pool.add_session(builder, socket)
        ...
        pool.add_local_input(i, handle, value)     # per session, per tick
        request_lists = pool.advance_all()          # ONE native crossing
        events = pool.events(i)

    ``request_lists[i]`` follows the exact ``GgrsRequest`` grammar of
    ``P2PSession.advance_frame``; feed it to any executor, including
    ``parallel.BatchedRequestExecutor`` (see ``parallel.HostedPool``).

    On the native path all sessions' timers run off ONE clock read per tick
    (builder 0's clock): pooled sessions must share a timebase.  Builders
    whose clocks read visibly apart at finalize fall back to per-session
    Python sessions, where each honors its own clock.
    """

    def __init__(self, retire_dead_matches: bool = False,
                 metrics: Optional[Registry] = None,
                 flight_recorder_size: int = 256,
                 tracer: Optional[Tracer] = None,
                 native_io: bool = False,
                 evict_max_per_tick: Optional[int] = None) -> None:
        # per-pool override of the eviction storm clamp (None = the
        # module default) — the fleet layer passes FleetTuning's value
        # through so one dataclass owns every backoff/clamp knob
        self._evict_max_per_tick = (
            EVICT_MAX_PER_TICK if evict_max_per_tick is None
            else evict_max_per_tick
        )
        # native_io (DESIGN.md §15): attach each slot's UDP fd to the
        # kernel-batched datapath (net_batch.cpp) so datagrams flow
        # socket -> crossing -> socket with zero Python on the packet path
        # (one recvmmsg + one sendmmsg per slot per tick instead of one
        # syscall per datagram).  Per-slot automatic fallback to the
        # Python shuttle whenever the fd is not native-attachable:
        # in-memory fault networks, wrapped sockets, unresolvable peer
        # addresses, non-Linux builds, GGRS_TPU_NO_NATIVE_IO=1.
        self.native_io = native_io
        self._use_pump = False
        self._net_handles: List[Optional[int]] = []
        self._io_attached: List[bool] = []
        self._io_live: List[int] = []  # attached slot indices (the io-delta
        # walk is driven by this list, not range(B) — DESIGN.md §19)
        self._io_prev: Dict[Tuple[int, int], int] = {}  # (slot, word) deltas
        # final counter snapshots of detached/evicted slots: io_stats()
        # totals must never regress when a NetBatch is released
        self._io_final: Dict[int, Dict[str, Any]] = {}
        # ---- datapath gen 2 (DESIGN.md §23): one-crossing inbound drain
        # over all non-attached fd-backed sockets (ggrs_net_recv_table) +
        # shared dispatch sockets + GSO fan-out.  The drain tables are
        # rebuilt by _refresh_drain() on any membership/state change.
        self._drain_ok = False
        self._drain_fd_tab = b""      # packed NET_FD_STRIDE entries
        self._drain_route_tab = b""   # packed NET_ROUTE_STRIDE entries
        self._drain_n_fds = 0
        self._drain_n_routes = 0
        self._drain_fd_fault: List[List[int]] = []  # fd_idx -> slots to
        # fault on a fatal recv errno (one slot per private fd; every
        # routed slot for a shared dispatch fd)
        self._drain_covered: List[bool] = []  # slot served by the drain
        self._drain_covered_keys: List[int] = []  # covered slot indices
        self._drain_wire: List[Optional[Dict]] = []  # slot ->
        # {(ip, port): ('e'|'s', idx)} — the Python-side half of the demux
        self._drain_deliver: Dict[int, Any] = {}  # quarantined/evicted
        # co-tenant on a shared hub -> its view (records go to _pending)
        self._drain_recs: Optional[ctypes.Array] = None
        self._drain_recs_cap = 0
        self._drain_slab: Optional[ctypes.Array] = None
        self._drain_slab_cap = 0
        self._drain_totals = dict.fromkeys(
            _native.NET_RECV_TABLE_STAT_FIELDS, 0
        )
        self._drain_hist = [0] * (len(_native.IO_BATCH_BUCKETS) + 1)
        self.drain_crossings = 0  # ggrs_net_recv_table invocations
        self.drain_ns = 0  # wall ns in _drain_inbound (profiling split)
        self._send_flags: List[int] = []  # per-slot NET_SEND_FIELDS flags
        self._gso_totals = {"gso_sends": 0, "gso_segments": 0}
        self._gro_on = False  # UDP_GRO armed on >=1 covered hub (§23d)
        self._decode_pool = None  # parallel slow-slot decode plane (§24)
        self.decode_parallel_ticks = 0  # ticks that fanned decode out
        self._builders: List[Tuple[Any, Any]] = []
        self._finalized = False
        self._native_active = False
        self._bank = None
        self._lib = None
        self._mirrors: List[_SessionMirror] = []
        self._sessions: List[Any] = []  # fallback P2PSessions
        # ---- input plane (DESIGN.md §27) ----
        # device-batched prediction over the Python-path slots: gathered
        # once per tick in _advance_all_fallback, served to the queues
        self._prediction_plane = None
        # slots demoted to the lockstep tier (load-shedding): index ->
        # tick demoted, for stats; the session itself lives in _evicted
        self._lockstep_slots: Dict[int, int] = {}
        # match-lifecycle timeline seam (DESIGN.md §28): the owning shard
        # installs a callable(etype, slot, detail) to translate pool-level
        # lifecycle moments (lockstep demotion) into match-keyed timeline
        # events; None when the pool runs unsupervised
        self.timeline_sink = None
        self._clock = None
        self._out_buf: Optional[ctypes.Array] = None
        self._out_len = ctypes.c_size_t(0)
        self._invalid: Optional[str] = None
        self.crossings = 0  # ggrs_bank_tick invocations (the count test)
        self.harvests = 0   # eviction harvest crossings (one-off per fault)
        self.stat_crossings = 0  # ggrs_bank_stats invocations (scrapes)
        # ---- vectorized policy plane (DESIGN.md §19) ----
        # _has_hdr: the loaded library leads the tick output with the
        # packed per-slot header table (and appends peer mirrors to the
        # harvest); _vectorized: classify slots from that table and
        # fast-path the quiet ones (GGRS_TPU_NO_FASTPATH=1 forces the
        # legacy per-slot parse — the parity fuzz's reference leg).
        # Tracing uses the legacy parse too: the per-slot spans ARE the
        # point of a traced tick.
        self._has_hdr = False
        self._hdr_stride = 0
        self._vectorized = False
        self.fast_slot_ticks = 0  # slots served by the fast path (counter)
        self.fast_ticks = 0       # ticks where every live slot was fast
        # ---- descriptor plane (DESIGN.md §21) ----
        # _has_req: the library emits the per-slot request descriptor
        # table (and the vectorized decode returns a lazy RequestPlan);
        # _has_stage: ggrs_bank_stage_inputs + the kFlagStaged cmd flag +
        # the harvest staged tail are available (the stage_inputs batched
        # staging API goes native).  Both probed like the header.
        self._has_req = False
        self._req_stride = 0
        self._has_stage = False
        self._uniform = False  # all mirrors share (players, input_size) —
        # the executor's bulk input gather requires it
        self.plan_ticks = 0        # advance_all calls decoded via a plan
        self.desc_slow_slots = 0   # plan-tick slots that needed the eager
        # per-slot reference decoder (slow/other/skip records)
        # per-slot input stagers: add_local_input dispatches through this
        # table (one bound callable per slot, rebuilt on supervision
        # transitions) instead of re-validating slot state and handle
        # membership on every call — the B-proportional staging walk fix
        self._stagers: List[Any] = []
        # the most recent descriptor-plane tick's RequestPlan (also what
        # advance_all returned); requests_for() and the staleness guard
        # read it
        self._plan: Optional[RequestPlan] = None
        # per-slot native outbound eligibility (§21c): a non-attached but
        # fd-backed socket whose endpoint addresses resolve rides the
        # one-crossing ggrs_net_send_table flush; everything else batches
        # per slot (send_datagram_batch) or keeps the per-datagram path
        self._send_fds: List[Optional[int]] = []
        self._ep_wire: List[Optional[List[Tuple[int, int]]]] = []
        # ---- observability (DESIGN.md §12) ----
        # metrics: explicit Registry for isolation (tests, multi-pool
        # processes) or the process-wide default; Registry(enabled=False)
        # turns the whole layer off (null instruments, no recorders)
        self.metrics = metrics if metrics is not None else default_registry()
        m = self.metrics
        self._obs_on = m.enabled
        self._flight_capacity = flight_recorder_size
        self._recorders: List[Optional[FlightRecorder]] = []
        # ---- tracing (DESIGN.md §14) ----
        # tracer: tick -> crossing -> slot spans on the Python side; when
        # the library carries ggrs_bank_set_timing, the native per-phase
        # timings ride the tick output's timing tail (zero extra crossings)
        # and are re-emitted as child spans of the crossing.  The shared
        # NULL_TRACER default keeps the hot path at one no-op call per tick.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._trace_native = False  # timing tail armed on the loaded bank
        self._phase_totals: Optional[Tuple[int, Dict[str, int]]] = None
        self._last_phase_ns: Optional[Dict[str, int]] = None
        # /healthz source: last completed pool tick on time.monotonic()
        self.last_tick_at: Optional[float] = None
        # desync forensics: slot -> the report built when a desync-class
        # fault quarantined it (DesyncReport; scripts/chaos.py artifacts)
        self._desync_reports: Dict[int, DesyncReport] = {}
        self._m_ticks = m.counter(
            "ggrs_pool_ticks_total", "pool ticks driven (advance_all calls)")
        _cross = m.counter(
            "ggrs_pool_crossings_total",
            "ctypes crossings by kind (tick / harvest / stats)",
            labels=("kind",))
        self._m_cross_tick = _cross.labels(kind="tick")
        self._m_cross_harvest = _cross.labels(kind="harvest")
        self._m_cross_stats = _cross.labels(kind="stats")
        self._m_faults = m.counter(
            "ggrs_pool_slot_faults_total", "per-slot faults by error code",
            labels=("code",))
        self._m_transitions = m.counter(
            "ggrs_pool_slot_transitions_total",
            "supervision state transitions", labels=("src", "dst"))
        self._m_slot_state = m.gauge(
            "ggrs_pool_slot_state", "slots currently in each supervision "
            "state", labels=("state",))
        self._m_evictions = m.counter(
            "ggrs_pool_evictions_total",
            "slots successfully evicted to the Python fallback")
        self._m_evict_failures = m.counter(
            "ggrs_pool_eviction_failures_total", "failed eviction attempts")
        self._m_evict_latency = m.histogram(
            "ggrs_pool_eviction_latency_ticks",
            "ticks from quarantine to successful eviction",
            buckets=(1, 2, 4, 8, 16, 32, 64))
        self._m_demotions = m.counter(
            "ggrs_pool_lockstep_demotions_total",
            "healthy slots demoted to the lockstep tier (load-shedding)")
        # ---- prediction accuracy (DESIGN.md §28): the Python tier's
        # input queues count mispredict episodes / rollback depth, the
        # device plane counts adopt-vs-decline; both fold into these at
        # scrape cadence (zero extra crossings) ----
        _mis = m.counter(
            "ggrs_predict_mispredicts_total",
            "rollback episodes caused by a wrong input prediction, by "
            "the source that produced it (plane = device-batched table, "
            "scalar = the config predictor)", labels=("source",))
        self._m_mis_plane = _mis.labels(source="plane")
        self._m_mis_scalar = _mis.labels(source="scalar")
        _served = m.counter(
            "ggrs_predict_served_total",
            "device prediction-plane row outcomes: adopted from the "
            "batched table vs declined to the scalar fallback",
            labels=("outcome",))
        self._m_pred_adopt = _served.labels(outcome="adopted")
        self._m_pred_fallback = _served.labels(outcome="fallback")
        self._m_mis_depth = m.counter(
            "ggrs_predict_rollback_frames_total",
            "rollback depth (frames re-simulated) attributed to "
            "mispredicted inputs")
        # last folded cumulative totals: (mispredicts, plane_mispredicts,
        # depth_frames, plane_hits, plane_fallbacks)
        self._predict_seen = [0, 0, 0, 0, 0]
        _req = m.counter(
            "ggrs_pool_requests_total",
            "GgrsRequests returned to the game, by kind",
            labels=("kind",))
        self._m_req_save = _req.labels(kind="save")
        self._m_req_load = _req.labels(kind="load")
        self._m_req_advance = _req.labels(kind="advance")
        self._m_rollbacks = m.counter(
            "ggrs_pool_rollbacks_total",
            "rollback decisions executed by pooled slots")
        # ---- broadcast (DESIGN.md §13): fan-out + journal observability ----
        self._m_fanout_dgrams = m.counter(
            "ggrs_fanout_datagrams_total",
            "confirmed-input datagrams fanned out to spectators",
            labels=("slot",))
        self._m_fanout_bytes = m.counter(
            "ggrs_fanout_bytes_total",
            "wire bytes fanned out to spectators", labels=("slot",))
        self._m_spectators = m.gauge(
            "ggrs_spectators_attached",
            "spectator endpoints attached per slot", labels=("slot",))
        self._m_spec_lag = m.gauge(
            "ggrs_spectator_catchup_lag",
            "frames broadcast but not yet acked by the viewer",
            labels=("slot", "spectator"))
        # ---- batched I/O (DESIGN.md §15): refreshed from the scrape's
        # per-slot io tail (the native counters ride the SAME one-crossing
        # stats harvest; nothing here touches the packet path) ----
        self._m_io_syscalls = m.counter(
            "ggrs_io_syscalls_total",
            "socket syscalls by kind (sendto/recvfrom = per-datagram "
            "Python path; recvmmsg/sendmmsg = kernel-batched native path)",
            labels=("kind",))
        self._m_io_dgrams = m.counter(
            "ggrs_io_datagrams_total",
            "datagrams moved by the kernel-batched datapath, by direction",
            labels=("dir",))
        self._m_io_send_errors = m.counter(
            "ggrs_io_send_errors_total",
            "transient native send failures counted as packet loss")
        self._m_io_oversized = m.counter(
            "ggrs_io_oversized_total",
            "natively-sent datagrams above the ideal UDP size")
        self._m_io_recv_batch = m.histogram(
            "ggrs_io_recv_batch_size",
            "datagrams per recvmmsg call", buckets=_native.IO_BATCH_BUCKETS)
        self._m_io_send_batch = m.histogram(
            "ggrs_io_send_batch_size",
            "datagrams per sendmmsg call", buckets=_native.IO_BATCH_BUCKETS)
        self._m_io_recvmmsg = self._m_io_syscalls.labels(kind="recvmmsg")
        self._m_io_sendmmsg = self._m_io_syscalls.labels(kind="sendmmsg")
        self._m_io_dgrams_in = self._m_io_dgrams.labels(dir="in")
        self._m_io_dgrams_out = self._m_io_dgrams.labels(dir="out")
        self._m_fast_slots = m.counter(
            "ggrs_pool_fastpath_slots_total",
            "slot ticks served by the vectorized quiet path (no per-slot "
            "body parse)")
        # datapath gen 2 (§23): the one-crossing inbound drain + GSO
        self._m_drain_crossings = m.counter(
            "ggrs_io_drain_crossings_total",
            "ggrs_net_recv_table invocations (one per pool tick when the "
            "batched inbound drain is active)")
        self._m_drain_dgrams = m.counter(
            "ggrs_io_drain_datagrams_total",
            "datagrams moved by the one-crossing inbound drain")
        self._m_drain_unroutable = m.counter(
            "ggrs_io_drain_unroutable_total",
            "dispatch-socket datagrams dropped for an unclaimed source")
        self._m_drain_batch = m.histogram(
            "ggrs_io_drain_batch_size",
            "datagrams per recvmmsg call on the batched inbound drain",
            buckets=_native.IO_BATCH_BUCKETS)
        self._m_gso_sends = m.counter(
            "ggrs_io_gso_sends_total",
            "UDP_SEGMENT segmented sends on the batched outbound path")
        self._m_gso_segments = m.counter(
            "ggrs_io_gso_segments_total",
            "datagrams coalesced into UDP_SEGMENT segmented sends")
        self._quarantined_at: Dict[int, int] = {}  # index -> quarantine tick
        self._stats_cache: Optional[Tuple[int, List[Dict[str, Any]]]] = None
        self._setter_cache: Dict[int, Any] = {}  # slot -> prebound gauge sets
        # slot -> prebound spectator catchup-lag Gauge.set list: label
        # resolution (str() + dict walk) off the scrape loop, like
        # _setter_cache — part of the B=256 allocation-free scrape pin
        self._spec_setter_cache: Dict[int, List[Any]] = {}
        # slot -> prebound (datagrams.inc, bytes.inc): label resolution off
        # the per-tick fan-out send loop, like _setter_cache for scrapes
        self._fanout_counters: Dict[int, Tuple[Any, Any]] = {}
        self._scrape_buf: Optional[ctypes.Array] = None  # persistent (GC)
        self._bank_records: Optional[List[Dict[str, Any]]] = None
        # scrape-refreshed gauges (set by scrape(), one label set per slot /
        # endpoint — the Prometheus-facing view of the stat harvest)
        self._m_slot_frame = m.gauge(
            "ggrs_slot_current_frame", "slot's post-tick frame",
            labels=("slot",))
        self._m_slot_occupancy = m.gauge(
            "ggrs_slot_prediction_occupancy",
            "frames of prediction window in use (current - confirmed)",
            labels=("slot",))
        self._m_slot_rollbacks = m.gauge(
            "ggrs_slot_rollbacks", "rollbacks executed by this slot",
            labels=("slot",))
        self._m_slot_rollback_depth = m.gauge(
            "ggrs_slot_max_rollback_depth",
            "deepest single rollback this slot has executed",
            labels=("slot",))
        self._m_ep_ping = m.gauge(
            "ggrs_endpoint_ping_ms", "round-trip time per remote endpoint",
            labels=("slot", "endpoint"))
        self._m_ep_queue = m.gauge(
            "ggrs_endpoint_send_queue_len",
            "unacked outbound inputs per remote endpoint",
            labels=("slot", "endpoint"))
        self._m_ep_kbps = m.gauge(
            "ggrs_endpoint_kbps_sent", "estimated outbound bandwidth",
            labels=("slot", "endpoint"))
        self._m_ep_behind = m.gauge(
            "ggrs_endpoint_frames_behind",
            "frame advantage from each perspective",
            labels=("slot", "endpoint", "side"))
        # ---- supervision state (fault isolation) ----
        # retire_dead_matches: when every remote endpoint of a slot has
        # disconnected the match is over; True retires the slot (state dead,
        # empty request lists) instead of letting it run free on dummy
        # inputs forever.  Default False preserves P2PSession semantics.
        self.retire_dead_matches = retire_dead_matches
        self._tick_no = 0
        self._slot_state: List[str] = []
        # incremental supervision (DESIGN.md §19): the post-tick walk is
        # driven by the slots that actually need attention — quarantined
        # (eviction pending) and evicted (their Python session must tick)
        # — instead of range(B).  Maintained by _set_slot_state; dead /
        # migrated slots leave the set (nothing here ticks for them).
        self._attention: set = set()
        # state-transition feed for incremental consumers (fleet shards'
        # forensics sweep): (slot, old, new, tick), bounded, drained via
        # drain_state_transitions()
        self._state_transitions: List[Tuple[int, str, str, int]] = []
        self._fault_log: List[List[SlotFault]] = []
        self._evicted: Dict[int, Any] = {}       # index -> P2PSession
        self._pending_load: Dict[int, GgrsRequest] = {}
        self._evict_attempts: Dict[int, int] = {}
        self._evict_next_try: Dict[int, int] = {}
        self._inject_dgrams: Dict[int, List[Tuple[int, bytes]]] = {}
        self._inject_err: Dict[int, int] = {}
        # ---- broadcast subsystem seams (ggrs_tpu/broadcast) ----
        # _spectator_hub: the SpectatorHub that owns relay policy for this
        # pool (set by SpectatorHub.__init__, must precede finalization);
        # _has_spec: the loaded library carries the broadcast entry points
        # AND the hub is attached, so the tick crossing speaks the broadcast
        # command/output layout; _journal_sinks: per-slot confirmed-stream
        # consumers (MatchJournal.append_frames signature); _journal_recovery
        # holds per-slot callables that synthesize a harvest-shaped resume
        # dict from the journal tail when ggrs_bank_harvest itself fails
        # (crash recovery — the chaos suite kills a slot's native state).
        self._spectator_hub: Optional[Any] = None
        self._has_spec = False
        self._has_io_layout = False
        self._journal_sinks: Dict[int, Any] = {}
        self._journal_recovery: Dict[int, Any] = {}

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    def add_session(self, builder, socket) -> int:
        """Register one session described by a fully-populated
        ``SessionBuilder`` plus its socket.  Returns the session index."""
        if self._finalized:
            raise InvalidRequest("pool already finalized; add sessions first")
        self._builders.append((builder, socket))
        return len(self._builders) - 1

    def _finalize(self) -> None:
        self._finalized = True
        self._slot_state = [SLOT_NATIVE] * len(self._builders)
        self._net_handles = [None] * len(self._builders)
        self._io_attached = [False] * len(self._builders)
        self._fault_log = [[] for _ in self._builders]
        self._recorders = [
            FlightRecorder(self._flight_capacity) if self._obs_on else None
            for _ in self._builders
        ]
        if self._builders:
            self._m_slot_state.labels(state=SLOT_NATIVE).inc(
                len(self._builders)
            )
        lib = None if os.environ.get("GGRS_TPU_NO_NATIVE") else (
            _native.bank_lib()
        )
        if lib is not None and hasattr(lib, "ggrs_bank_hdr_stride"):
            if int(lib.ggrs_bank_hdr_stride()) != _HDR_DTYPE.itemsize:
                # library/driver layout skew (a newer .so than this
                # driver): we cannot parse its header table, so degrade
                # like every other layout mismatch — per-session Python
                # sessions, never a half-initialized bank
                _logger.warning(
                    "bank header stride %d != %d (library/driver skew); "
                    "pool falls back to per-session Python sessions",
                    int(lib.ggrs_bank_hdr_stride()), _HDR_DTYPE.itemsize,
                )
                lib = None
        if lib is not None and hasattr(lib, "ggrs_bank_req_stride"):
            # the request descriptor table is emitted unconditionally by a
            # descriptor-plane library, so a stride mismatch shifts EVERY
            # body offset — same degradation as a header skew
            if (
                int(lib.ggrs_bank_req_stride()) != _REQ_DTYPE.itemsize
                or int(lib.ggrs_bank_stage_stride()) != _STAGE_DTYPE.itemsize
            ):
                _logger.warning(
                    "bank descriptor strides (req %d, stage %d) != driver "
                    "(%d, %d) (library/driver skew); pool falls back to "
                    "per-session Python sessions",
                    int(lib.ggrs_bank_req_stride()),
                    int(lib.ggrs_bank_stage_stride()),
                    _REQ_DTYPE.itemsize, _STAGE_DTYPE.itemsize,
                )
                lib = None
        # The bank runs every session's timers off ONE clock read per tick
        # (builder 0's clock) — that is the pool's contract.  Builders whose
        # clocks are visibly on a different timebase (a frozen test clock
        # pooled with a real one reads hours apart) stay on the per-session
        # fallback, where each session honors its own clock.  Distinct
        # callables over the same timebase (per-builder lambdas reading one
        # counter) read within the tolerance and pool fine.
        def same_timebase() -> bool:
            if not self._builders:
                return False
            first = self._builders[0][0]._clock
            t0 = first()
            for b, _ in self._builders:
                if b._clock is first:
                    continue
                if abs(b._clock() - t0) > 100:
                    return False
            return True

        hub_active = (
            self._spectator_hub is not None
            and lib is not None
            and hasattr(lib, "ggrs_bank_attach_spectator")
        )
        eligible = lib is not None and same_timebase() and all(
            _bank_eligible(b, hub_active=hub_active)
            and hasattr(s, "receive_all_datagrams")
            for b, s in self._builders
        )
        if not eligible:
            for builder, socket in self._builders:
                self._sessions.append(builder.start_p2p_session(socket))
            self._stagers = [
                self._make_stager(i) for i in range(len(self._builders))
            ]
            return

        self._lib = lib
        self._bank = lib.ggrs_bank_new()
        if not self._bank:
            raise MemoryError("ggrs_bank_new failed")
        self._native_active = True
        # the broadcast command/output layout is spoken whenever the
        # library carries the entry points — spectator tables may be empty
        self._has_spec = hasattr(lib, "ggrs_bank_attach_spectator")
        # a library built with the batched datapath emits a per-slot io
        # tail on every stats dump (u8 flag + counters when attached)
        self._has_io_layout = hasattr(lib, "ggrs_bank_pump")
        # packed per-tick header (DESIGN.md §19): presence-probed like the
        # other layout extensions; a prebuilt pre-header library emits the
        # body-only output and the pool keeps the legacy parse throughout.
        # (A stride MISMATCH was already rejected above, before the bank
        # committed to the native path.)
        self._has_hdr = hasattr(lib, "ggrs_bank_hdr_stride")
        if self._has_hdr:
            self._hdr_stride = int(lib.ggrs_bank_hdr_stride())
            self._vectorized = not os.environ.get("GGRS_TPU_NO_FASTPATH")
        # descriptor plane (§21): request descriptor table + batched
        # staging + harvest staged tail (strides already skew-checked)
        self._has_req = hasattr(lib, "ggrs_bank_req_stride")
        if self._has_req:
            self._req_stride = int(lib.ggrs_bank_req_stride())
            self._has_stage = True
        # arm the in-crossing phase timers only when someone is tracing:
        # disarmed, the tick performs zero clock reads and emits the exact
        # pre-timing output layout (the on/off wire pin rides on this)
        if self.tracer.enabled and hasattr(lib, "ggrs_bank_set_timing"):
            lib.ggrs_bank_set_timing(self._bank, 1)
            self._trace_native = True
        from ..core.types import Remote, Spectator

        for builder, socket in self._builders:
            cfg = builder._config
            # builder-level validation parity (start_p2p_session's checks)
            for handle in range(builder._num_players):
                if handle not in builder._player_reg.handles:
                    raise InvalidRequest(
                        "Not enough players have been added. Keep registering "
                        "players up to the defined player number."
                    )
            local_handles = sorted(
                h for h, t in builder._player_reg.handles.items()
                if not isinstance(t, (Remote, Spectator))
            )
            arr = (ctypes.c_int32 * max(1, len(local_handles)))(*local_handles)
            idx = lib.ggrs_bank_add_session(
                self._bank, builder._num_players, cfg.native_input_size,
                builder._max_prediction, builder._fps,
                builder._disconnect_timeout_ms,
                builder._disconnect_notify_start_ms,
                arr, len(local_handles), builder._input_delay,
            )
            if idx < 0:
                raise RuntimeError(f"ggrs_bank_add_session failed: {idx}")
            mirror = _SessionMirror(
                cfg, socket, builder._num_players, builder._max_prediction,
                local_handles,
            )
            # endpoints: same address grouping, iteration order, and magic
            # draws as start_p2p_session -> PeerProtocol.__init__, so the
            # wire bytes (magic included) match the fallback bit-for-bit
            remote_by_addr: Dict[Any, List[int]] = {}
            for handle, ptype in builder._player_reg.handles.items():
                if isinstance(ptype, Remote):
                    remote_by_addr.setdefault(ptype.addr, []).append(handle)
            now = builder._clock()
            for addr, handles in remote_by_addr.items():
                rng = builder._rng if builder._rng is not None else (
                    random.Random()
                )
                magic = draw_magic(rng)
                handles = sorted(handles)
                harr = (ctypes.c_int32 * len(handles))(*handles)
                ep_idx = lib.ggrs_bank_add_endpoint(
                    self._bank, idx, magic, harr, len(handles), now
                )
                if ep_idx < 0:
                    raise RuntimeError(
                        f"ggrs_bank_add_endpoint failed: {ep_idx}"
                    )
                mirror.addr_to_ep[addr] = int(ep_idx)
                mirror.endpoints.append(
                    _EndpointMirror(addr, handles, magic,
                                    builder._num_players)
                )
            # builder-declared spectators (hub-owned relay): native fan-out
            # endpoints, created AFTER the remotes with the same rng draws
            # start_p2p_session would make, so the remote endpoints' magic
            # numbers — and hence the host's remote-facing wire bytes —
            # are bit-identical to the per-session baseline
            spectator_by_addr: Dict[Any, List[int]] = {}
            for handle, ptype in builder._player_reg.handles.items():
                if isinstance(ptype, Spectator):
                    spectator_by_addr.setdefault(ptype.addr, []).append(
                        handle
                    )
            for addr, handles in spectator_by_addr.items():
                rng = builder._rng if builder._rng is not None else (
                    random.Random()
                )
                magic = draw_magic(rng)
                sp_idx = lib.ggrs_bank_attach_spectator(
                    self._bank, idx, magic, now
                )
                if sp_idx < 0:
                    raise RuntimeError(
                        f"ggrs_bank_attach_spectator failed: {sp_idx}"
                    )
                mirror.addr_to_spec[addr] = int(sp_idx)
                mirror.spectators.append(
                    _SpectatorMirror(addr, magic, sorted(handles))
                )
            if mirror.spectators:
                self._m_spectators.labels(slot=str(idx)).set(
                    len(mirror.spectators)
                )
            # fast-path geometry: the status-mirror section's byte length
            # (u8 n_eps + per-endpoint u8 state + players*(u8,i64) + the
            # local players*(u8,i64) tail) — the jump from the outbound
            # sections to the broadcast tail without a positional parse
            mirror.mirror_len = (
                1
                + len(mirror.endpoints) * (1 + 9 * mirror.num_players)
                + 9 * mirror.num_players
            )
            self._mirrors.append(mirror)
        self._clock = self._builders[0][0]._clock
        # output buffer sized to the worst realistic tick (rollback resim
        # descriptors + a full outbound volley per endpoint), grown never:
        # a too-small buffer poisons the pool loudly instead
        per_session = 0
        for m in self._mirrors:
            adv_bytes = m.num_players * (1 + m.input_size)
            per_session = max(
                per_session,
                4096
                + (m.max_prediction + 4) * (16 + adv_bytes)
                + len(m.endpoints) * (2048 + 32 * m.num_players)
                + len(m.spectators) * 2048
                + (m.max_prediction + 4) * (16 + adv_bytes),  # journal tap
            )
        self._out_buf = ctypes.create_string_buffer(
            max(1 << 16, per_session * len(self._mirrors)
                + (self._hdr_stride + self._req_stride)
                * len(self._mirrors))
        )
        # uniform pools (every mirror shares (players, input_size)) unlock
        # the executor's bulk input gather over the quiet rows
        self._uniform = len({
            (m.num_players, m.input_size) for m in self._mirrors
        }) == 1
        self._stagers = [
            self._make_stager(i) for i in range(len(self._mirrors))
        ]
        # ---- batched socket datapath (DESIGN.md §15) ----
        # opt-in, per-slot, and failure is always a clean per-slot fallback
        # to the Python shuttle — never an error.  net_lib() is None when
        # the platform has no recvmmsg/sendmmsg, the library predates the
        # datapath, or GGRS_TPU_NO_NATIVE_IO is set.
        if self.native_io and _native.net_lib() is lib and lib is not None:
            for i, m in enumerate(self._mirrors):
                self._try_attach_io(i, m)
            # pump only when someone actually attached: with zero attached
            # slots the pump is semantically the tick but pays a per-tick
            # cmd re-parse for its pre-drain scan
            self._use_pump = any(self._io_attached)
        # batched outbound eligibility (§21c) — after the io attach pass,
        # so NetBatch-attached slots (whose sends never re-enter Python)
        # are excluded
        self._send_fds = [None] * len(self._mirrors)
        self._ep_wire = [None] * len(self._mirrors)
        self._send_flags = [0] * len(self._mirrors)
        for i in range(len(self._mirrors)):
            self._refresh_send_fd(i)
        # ---- datapath gen 2 (§23) ----
        # GSO posture: the env override is applied once, process-wide (the
        # probe result itself is cached in the library); the per-feature
        # fallback matrix is reported by io_capabilities()
        if lib is not None and hasattr(lib, "ggrs_net_set_gso"):
            lib.ggrs_net_set_gso(
                0 if os.environ.get("GGRS_TPU_NO_GSO") else -1
            )
        self._refresh_drain()
        # parallel slow-slot decode plane (§24): backend resolved once
        # per pool (env kill switch / force inside the constructor);
        # "serial" means the pool object exists for the capability
        # matrix but every decode stays on the inline _parse_slot
        # reference — zero new machinery on the default GIL-build path
        if self._decode_pool is None and self._native_active:
            from .decode_pool import DecodePool

            self._decode_pool = DecodePool()

    def _refresh_send_fd(self, index: int) -> None:
        """(Re)compute slot ``index``'s native batched-outbound
        eligibility: an fd-backed, non-NetBatch-attached socket whose
        endpoint addresses resolve to (ipv4, port) sends through the
        one-crossing ``ggrs_net_send_table`` flush (§21c).  Everything
        else — in-memory networks, wrapped sockets, unresolvable
        addresses, non-Linux, GGRS_TPU_NO_NATIVE_IO — keeps the Python
        batch/per-datagram paths."""
        if not self._send_fds:
            return
        self._send_fds[index] = None
        self._ep_wire[index] = None
        if self._send_flags:
            self._send_flags[index] = 0
        m = self._mirrors[index]
        lib = self._lib
        if (
            lib is None
            or not hasattr(lib, "ggrs_net_send_table")
            or not hasattr(lib, "ggrs_net_supported")
            or not lib.ggrs_net_supported()
            or os.environ.get("GGRS_TPU_NO_NATIVE_IO")
            or self._io_attached[index]
        ):
            return
        fileno = getattr(m.socket, "fileno", None)
        if fileno is None:
            return
        try:
            fd = fileno()
        except Exception:
            return
        if not isinstance(fd, int) or fd < 0:
            return
        try:
            wire = [
                self._resolve_wire_addr(ep.addr) for ep in m.endpoints
            ]
        except (TypeError, ValueError, OSError):
            return
        self._send_fds[index] = fd
        self._ep_wire[index] = wire
        if self._send_flags and getattr(m.socket, "is_dispatch", False):
            # shared dispatch fd (§23b): a fatal errno on one record must
            # fault only the owning slot, so the native flush skips the
            # record instead of abandoning the co-tenants' run
            self._send_flags[index] = _native.NET_SEND_FLAG_DISPATCH

    @staticmethod
    def _resolve_wire_addr(addr) -> Tuple[int, int]:
        """(s_addr word, host-order port) for an ``(ipv4, port)`` tuple;
        raises for anything the native datapath cannot address (hostnames,
        in-memory addresses) — the caller falls back to the shuttle."""
        host, port = addr
        packed = _pysocket.inet_aton(host)
        # "little" = host order: the native side stores this u32 straight
        # into sin_addr.s_addr, so the bytes must round-trip unchanged.
        # Sound because the native fast paths REFUSE to build on
        # big-endian hosts (wire_common.h static_assert) — no library,
        # no attach, no wrong-endian address.
        return int.from_bytes(packed, "little"), int(port)

    def _try_attach_io(self, index: int, m: _SessionMirror) -> None:
        """Attach one slot's socket to the native datapath: the fd must be
        a real one and every remote/spectator address must resolve to
        (ipv4, port).  Any miss leaves the slot on the Python shuttle."""
        lib = self._lib
        if getattr(m.socket, "is_dispatch", False):
            # shared dispatch fd (§23b): a whole-fd NetBatch attach would
            # couple co-tenant faults; dispatch slots ride the table
            # paths, whose per-record dispatch flag keeps §9 isolation
            return
        fileno = getattr(m.socket, "fileno", None)
        if fileno is None:
            return
        try:
            fd = fileno()
        except Exception:
            return
        if not isinstance(fd, int) or fd < 0:
            return
        try:
            eps = [
                (idx,) + self._resolve_wire_addr(addr)
                for addr, idx in m.addr_to_ep.items()
            ]
            sps = [
                (idx,) + self._resolve_wire_addr(addr)
                for addr, idx in m.addr_to_spec.items()
            ]
        except (TypeError, ValueError, OSError):
            return
        handle = lib.ggrs_net_attach(fd, 64)
        if not handle:
            return
        if lib.ggrs_bank_attach_socket(self._bank, index, handle) != 0:
            lib.ggrs_net_free(handle)
            return
        for idx, ip, port in eps:
            lib.ggrs_bank_map_addr(self._bank, index, 0, idx, ip, port)
        for idx, ip, port in sps:
            lib.ggrs_bank_map_addr(self._bank, index, 1, idx, ip, port)
        self._net_handles[index] = handle
        self._io_attached[index] = True
        self._io_live.append(index)

    def _refresh_drain(self) -> None:
        """(Re)build the gen-2 one-crossing inbound drain plan (§23a):
        the packed fd table (every SLOT_NATIVE, non-NetBatch-attached,
        fd-backed socket — dispatch hubs contribute their sibling fds
        once, marked slot ``-1``), the sorted (ip, port) -> slot route
        table the native demux binary-searches, and the per-slot wire
        maps the Python side uses to turn records into the cmd stream's
        ``(ep_idx, data)`` sections.  Any ineligible slot simply stays on
        the per-slot ``receive_all_datagrams`` reference drain — the
        per-feature fallback, never an error."""
        self._drain_ok = False
        if not self._finalized or not self._native_active:
            return
        # dispatch claims first, OUTSIDE the native gate: the hub's
        # reference Python demux needs them even when ggrs_net_recv_table
        # is unavailable (per-feature degradation)
        for i, m in enumerate(self._mirrors):
            sock = m.socket
            if getattr(sock, "is_dispatch", False) and hasattr(
                sock, "claim"
            ):
                for addr in m.addr_to_ep:
                    sock.claim(addr)
                for addr in m.addr_to_spec:
                    sock.claim(addr)
        lib = self._lib
        if (
            lib is None
            or not hasattr(lib, "ggrs_net_recv_table")
            or not hasattr(lib, "ggrs_net_supported")
            or not lib.ggrs_net_supported()
            or os.environ.get("GGRS_TPU_NO_NATIVE_IO")
            or os.environ.get("GGRS_TPU_NO_RECV_TABLE")
        ):
            return
        n = len(self._mirrors)
        fd_rows: List[Tuple[int, int]] = []
        fd_fault: List[List[int]] = []
        route_rows: List[Tuple[int, int, int]] = []
        covered = [False] * n
        wire_maps: List[Optional[Dict]] = [None] * n
        deliver: Dict[int, Any] = {}  # slot -> hub view (pending queue)
        dispatch_idx: Dict[int, int] = {}  # shared fd -> fd table index
        hubs: List[Any] = []  # covered dispatch hubs (GRO candidates)
        for i, m in enumerate(self._mirrors):
            sock = m.socket
            if self._slot_state[i] != SLOT_NATIVE or self._io_attached[i]:
                # §9 on a SHARED fd: a quarantined/evicted co-tenant's
                # inbound still arrives on the hub socket the native
                # drain keeps reading — dropping its routes would starve
                # the Python-path session (its datagrams become
                # unroutable drops).  Keep its routes and deliver its
                # records into the view's pending queue, where the
                # evicted session's receive path already looks.
                if (
                    getattr(sock, "is_dispatch", False)
                    and self._slot_state[i] in (SLOT_QUARANTINED,
                                                SLOT_EVICTED)
                ):
                    try:
                        for addr in m.addr_to_ep:
                            ip, port = self._resolve_wire_addr(addr)
                            route_rows.append((ip, port, i))
                        for addr in m.addr_to_spec:
                            ip, port = self._resolve_wire_addr(addr)
                            route_rows.append((ip, port, i))
                    except (TypeError, ValueError, OSError):
                        continue
                    deliver[i] = sock
                continue
            fileno = getattr(sock, "fileno", None)
            if fileno is None:
                continue
            try:
                fd = fileno()
            except Exception:
                continue
            if not isinstance(fd, int) or fd < 0:
                continue
            try:
                wire: Dict[Tuple[int, int], Tuple[str, int]] = {}
                for addr, idx in m.addr_to_ep.items():
                    wire[self._resolve_wire_addr(addr)] = ("e", idx)
                for addr, idx in m.addr_to_spec.items():
                    wire[self._resolve_wire_addr(addr)] = ("s", idx)
            except (TypeError, ValueError, OSError):
                continue
            if getattr(sock, "is_dispatch", False):
                hub = getattr(sock, "hub", None)
                if hub is None:
                    continue
                for fd2 in hub.filenos():
                    at = dispatch_idx.get(fd2)
                    if at is None:
                        dispatch_idx[fd2] = len(fd_rows)
                        fd_rows.append((fd2, -1))
                        fd_fault.append([i])
                    elif i not in fd_fault[at]:
                        fd_fault[at].append(i)
                if hub not in hubs:
                    hubs.append(hub)
                for ip, port in wire:
                    route_rows.append((ip, port, i))
            else:
                fd_rows.append((fd, i))
                fd_fault.append([i])
            covered[i] = True
            wire_maps[i] = wire
        if not fd_rows:
            return
        pack = struct.pack
        route_rows.sort(key=lambda r: (r[0] << 16) | r[1])
        self._drain_fd_tab = b"".join(
            pack("<ii", fd, slot) for fd, slot in fd_rows
        )
        self._drain_route_tab = b"".join(
            pack("<IHHi", ip, port, 0, slot)
            for ip, port, slot in route_rows
        )
        self._drain_n_fds = len(fd_rows)
        self._drain_n_routes = len(route_rows)
        self._drain_fd_fault = fd_fault
        self._drain_covered = covered
        self._drain_covered_keys = [
            i for i, c in enumerate(covered) if c
        ]
        self._drain_wire = wire_maps
        self._drain_deliver = deliver
        # GRO (§23d): every covered hub's inbound is now drained by the
        # native recv table — which splits coalesced trains back into
        # wire datagrams — so it is safe, and ONLY now, to let the kernel
        # coalesce.  Hubs on the reference Python drain must never see
        # GRO (drain() reads into a RECV_BUFFER_SIZE buffer).  The
        # crossing's ring posture is process-wide, refreshed per plan
        # like the GSO posture in _finalize.
        gro_on = False
        if (
            hubs
            and not os.environ.get("GGRS_TPU_NO_GRO")
            and hasattr(lib, "ggrs_net_gro_supported")
            and lib.ggrs_net_gro_supported()
        ):
            for hub in hubs:
                if hub.enable_gro():
                    gro_on = True
        self._gro_on = gro_on
        if hasattr(lib, "ggrs_net_set_gro"):
            lib.ggrs_net_set_gro(1 if gro_on else 0)
        if self._drain_recs is None:
            # a GRO drain can legally turn ONE message into 64 records /
            # 64 KiB of slab, and the crossing reserves that worst case
            # before each syscall — size the buffers so the reserve never
            # clamps a recvmmsg below the ring's full 64-message window
            # (recs: 64 msgs x 64 segs; slab: 64 msgs x 64 KiB = 4 MiB),
            # else an armed drain batches WORSE than the plain ring on
            # traffic the kernel happens not to coalesce
            if gro_on:
                self._drain_recs_cap = max(4096, 4 * len(fd_rows))
                self._drain_slab_cap = max(4 << 20, 4096 * len(fd_rows))
            else:
                self._drain_recs_cap = max(256, 4 * len(fd_rows))
                self._drain_slab_cap = max(1 << 18, 4096 * len(fd_rows))
            self._drain_recs = ctypes.create_string_buffer(
                self._drain_recs_cap * _native.NET_RECV_STRIDE
            )
            self._drain_slab = ctypes.create_string_buffer(
                self._drain_slab_cap
            )
        self._drain_ok = True

    def _drain_inbound(self) -> Optional[Dict[int, Tuple[list, list]]]:
        """The gen-2 inbound drain: ONE ctypes crossing pulls every
        covered slot's pending datagrams (recvmmsg per fd, dispatch demux
        in C) and this routine walks the packed record table once to
        build each slot's ``(datagrams, spec_datagrams)`` cmd sections —
        zero per-slot Python calls.  A fatal recv errno faults exactly
        the owning slot(s) BEFORE the tick snapshot, so the faulted slot
        skips this tick (§9); the drain itself never raises.  Returns
        None when the drain plan is stale/disabled (caller falls back to
        the reference per-slot drain)."""
        if not self._drain_ok:
            return None
        lib = self._lib
        nb = len(_native.IO_BATCH_BUCKETS) + 1
        # every covered slot gets a key (the consumer reads membership as
        # "already drained" — a missing key would re-drain the socket on
        # the shuttle path); the two lists are allocated only for slots
        # with traffic this tick
        out: Dict[int, Optional[Tuple[list, list]]] = dict.fromkeys(
            self._drain_covered_keys
        )
        stats = (ctypes.c_uint64 * _native.NET_RECV_TABLE_STATS)()
        fatal = (ctypes.c_int32 * 64)()
        n_fatal = ctypes.c_int32(0)
        wire_maps = self._drain_wire
        # local snapshot: a fault below triggers _refresh_drain(), which
        # REPLACES these tables — the indices in this call's record/fatal
        # buffers refer to the plan the crossing actually ran against
        fault_map = self._drain_fd_fault
        deliver = self._drain_deliver
        for _round in range(8):  # regrow-and-continue bound (backpressure)
            ctypes.memset(stats, 0, ctypes.sizeof(stats))
            n_recs = lib.ggrs_net_recv_table(
                self._drain_fd_tab, self._drain_n_fds,
                self._drain_route_tab, self._drain_n_routes,
                self._drain_recs, self._drain_recs_cap,
                self._drain_slab, self._drain_slab_cap,
                stats, fatal, 32, ctypes.byref(n_fatal),
            )
            self.drain_crossings += 1
            if n_recs < 0:
                # builder bug (corrupt tables): disable the drain and let
                # this tick run the reference path rather than poison it
                self._drain_ok = False
                return None
            slab = self._drain_slab
            if n_recs:
                # one vectorized parse of the record table, then plain-int
                # column lists for the routing walk (a B=512 dispatch pool
                # sees ~2B records per tick — per-record unpack_from was
                # the walk's hottest line)
                arr = np.frombuffer(
                    self._drain_recs, dtype=_RECV_DTYPE, count=n_recs
                )
                slot_l = arr["slot"].tolist()
                ip_l = arr["ip"].tolist()
                port_l = arr["port"].tolist()
                off_l = arr["off"].tolist()
                len_l = arr["len"].tolist()
            for k in range(n_recs):
                slot = slot_l[k]
                ip = ip_l[k]
                port = port_l[k]
                off = off_l[k]
                wire = wire_maps[slot]
                if wire is None:
                    # quarantined/evicted co-tenant on a shared hub: hand
                    # the record to the view's pending queue — the slot's
                    # Python session drains it exactly where the hub's
                    # reference demux would have put it
                    view = deliver.get(slot)
                    if view is not None:
                        src = (
                            _pysocket.inet_ntoa(ip.to_bytes(4, "little")),
                            port,
                        )
                        view._pending.append(
                            (src, slab[off:off + len_l[k]])
                        )
                    continue
                dst = wire.get((ip, port))
                if dst is None:
                    continue  # unknown source: the reference drain's drop
                kind, idx = dst
                data = slab[off:off + len_l[k]]
                entry = out[slot]
                if entry is None:
                    entry = out[slot] = ([], [])
                if kind == "e":
                    entry[0].append((idx, data))
                else:
                    entry[1].append((idx, data))
            t = self._drain_totals
            t["recv_calls"] += int(stats[0])
            t["datagrams"] += int(stats[1])
            t["unroutable"] += int(stats[2])
            t["backpressure_stops"] += int(stats[3])
            # GRO tail lives at words [12..13], AFTER the histogram (a
            # pre-GRO .so leaves them zeroed — the memset above)
            t["gro_datagrams"] += int(stats[12])
            t["gro_segments"] += int(stats[13])
            for b in range(nb):
                self._drain_hist[b] += int(stats[4 + b])
            if self._obs_on:
                self._m_drain_crossings.inc()
                if stats[1]:
                    self._m_drain_dgrams.inc(int(stats[1]))
                if stats[2]:
                    self._m_drain_unroutable.inc(int(stats[2]))
                hist = getattr(self._m_drain_batch, "_default", None)
                if hist is not None and stats[0]:
                    for b in range(nb):
                        hist.counts[b] += int(stats[4 + b])
                    hist.count += int(stats[0])
                    hist.sum += int(stats[1])
            for k in range(min(int(n_fatal.value), 32)):
                fd_idx = fatal[2 * k]
                err = fatal[2 * k + 1]
                for slot in fault_map[fd_idx]:
                    self._on_slot_fault(
                        slot, _native.BANK_ERR_IO,
                        f"batched inbound drain errno {err}",
                    )
            if int(n_fatal.value):
                # supervision transitions invalidated the plan (and the
                # faulted slots must not be re-drained this tick)
                break
            if not int(stats[3]):
                break
            # backpressure: the kernel still holds datagrams — double the
            # record/slab capacity and keep draining (appending)
            self._drain_recs_cap *= 2
            self._drain_recs = ctypes.create_string_buffer(
                self._drain_recs_cap * _native.NET_RECV_STRIDE
            )
            self._drain_slab_cap *= 2
            self._drain_slab = ctypes.create_string_buffer(
                self._drain_slab_cap
            )
        return out

    def io_capabilities(self) -> Dict[str, bool]:
        """The gen-2 per-feature capability/fallback matrix (§23): which
        datapath tiers THIS pool can use right now.  Every False here is
        a per-feature fallback to the tier below, never an error."""
        lib = self._lib
        native = bool(
            lib is not None
            and hasattr(lib, "ggrs_net_supported")
            and lib.ggrs_net_supported()
            and not os.environ.get("GGRS_TPU_NO_NATIVE_IO")
        )
        return {
            "native_io": native,
            "recv_table": bool(
                native
                and hasattr(lib, "ggrs_net_recv_table")
                and not os.environ.get("GGRS_TPU_NO_RECV_TABLE")
            ),
            "send_table": bool(
                native and hasattr(lib, "ggrs_net_send_table")
            ),
            "dispatch": any(
                getattr(m.socket, "is_dispatch", False)
                for m in self._mirrors
            ),
            "reuseport": hasattr(_pysocket, "SO_REUSEPORT"),
            "gso": bool(
                native
                and hasattr(lib, "ggrs_net_gso_supported")
                and lib.ggrs_net_gso_supported()
                and not os.environ.get("GGRS_TPU_NO_GSO")
            ),
            # kernel probe ok + not killed; _gro_on says whether THIS
            # pool actually armed it (needs a covered dispatch hub)
            "gro": bool(
                native
                and hasattr(lib, "ggrs_net_gro_supported")
                and lib.ggrs_net_gro_supported()
                and not os.environ.get("GGRS_TPU_NO_GRO")
            ),
            "gro_active": self._gro_on,
            # parallel slow-slot decode plane (§24): backend the pool's
            # DecodePool resolved ("serial" is the bit-identical
            # fallback; the kill switch forces it)
            "parallel_decode": bool(
                self._decode_pool is not None
                and self._decode_pool.backend != "serial"
            ),
            "decode_backend": (
                self._decode_pool.backend
                if self._decode_pool is not None else "serial"
            ),
        }

    @staticmethod
    def _io_words_to_dict(words) -> Dict[str, Any]:
        """One NetBatch counter dump (22 u64s) as the scrape's io-record
        shape."""
        nf = len(_native.IO_STAT_FIELDS)
        nb = len(_native.IO_BATCH_BUCKETS) + 1
        io: Dict[str, Any] = dict(zip(_native.IO_STAT_FIELDS, words[:nf]))
        io["recv_batches"] = list(words[nf:nf + nb])
        io["send_batches"] = list(words[nf + nb:nf + 2 * nb])
        return io

    def _detach_io(self, index: int) -> None:
        """Per-slot automatic fallback: return the slot to the Python
        shuttle (eviction, or a late-attached spectator address the
        native side cannot route) and release its NetBatch.  The final
        counter snapshot is retained (and folded into the registry) so
        ``io_stats()`` totals never regress across a detach."""
        if not self._io_attached[index]:
            return
        self._lib.ggrs_bank_detach_socket(self._bank, index)
        self._io_attached[index] = False
        if index in self._io_live:
            self._io_live.remove(index)
        handle = self._net_handles[index]
        self._net_handles[index] = None
        if handle:
            words = (ctypes.c_uint64 * _native.IO_STAT_WORDS)()
            self._lib.ggrs_net_stats(handle, words)
            io = self._io_words_to_dict(list(words))
            self._io_final[index] = io
            # flush the tail accrued since the last scrape into the
            # registry counters before the source disappears
            self._apply_io_metrics([dict(index=index, io=io)])
            self._lib.ggrs_net_free(handle)
        # drop the slot's delta-tracking keys: a later attach on this fd
        # (e.g. the match re-admitted on a destination pool) starts its
        # NetBatch counters at zero, and stale high-water marks here would
        # silently swallow its deltas — the classic re-attach leak
        for k in [k for k in self._io_prev if k[0] == index]:
            del self._io_prev[k]
        if not any(self._io_attached):
            # last attached slot gone: drop back to the plain tick entry
            # (the pump's pre-drain scan would walk the cmd for nothing)
            self._use_pump = False
        # the slot is back on the Python shuttle: it may now qualify for
        # the batched one-crossing outbound flush and the gen-2 batched
        # inbound drain instead
        self._refresh_send_fd(index)
        self._refresh_drain()

    # ------------------------------------------------------------------
    # per-tick API
    # ------------------------------------------------------------------

    @property
    def native_active(self) -> bool:
        if not self._finalized:
            self._finalize()
        return self._native_active

    def __len__(self) -> int:
        return len(self._builders)

    def _make_stager(self, index: int):
        """One slot's input-staging dispatch (the B-proportional staging
        walk fix, §21 satellite): the slot-state branch and the
        handle→slot validation are resolved HERE, once per supervision
        transition, instead of on every ``add_local_input`` call.  The
        returned callable is what ``add_local_input`` (and the per-item
        fallback of ``stage_inputs``) invokes."""
        state = self._slot_state[index]
        if state in (SLOT_DEAD, SLOT_MIGRATED):
            def drop(handle, value):
                return  # dead/migrated: accept and drop (nothing ticks)
            return drop
        if not self._native_active:
            return self._sessions[index].add_local_input
        if state == SLOT_EVICTED:
            return self._evicted[index].add_local_input
        m = self._mirrors[index]
        local_set = m.local_handle_set
        staged = m.staged_inputs
        encode = m.encode

        def stage(handle, value):
            if handle not in local_set:
                raise InvalidRequest(
                    "The player handle you provided is not referring to a "
                    "local player."
                )
            staged[handle] = encode(value)

        return stage

    def add_local_input(self, index: int, handle: int, value) -> None:
        if not self._finalized:
            self._finalize()
        self._stagers[index](handle, value)

    def stage_inputs(self, items) -> None:
        """Batched input staging (descriptor plane, DESIGN.md §21): stage
        many ``(session_index, handle, value)`` local inputs in ONE native
        crossing per pool tick instead of B ``add_local_input`` calls.

        On the native descriptor path the encoded blobs go straight into
        the bank via ``ggrs_bank_stage_inputs`` — one packed fixed-stride
        table plus a joined payload (the PR 10 jump-table idiom) — and the
        tick's command stream carries a flag byte per slot instead of the
        inline input bytes.  Slots that are not bank-resident (evicted,
        dead, the whole-pool Python fallback) route through their per-slot
        stager, so the call is always semantically ``add_local_input`` per
        item.  Per slot per tick, inputs must come entirely through ONE
        mechanism — ``add_local_input`` staging after ``stage_inputs`` for
        the same slot makes the inline path win and drops the native
        staging for that slot (both sides discard it in lockstep)."""
        if not self._finalized:
            self._finalize()
        if not (self._native_active and self._has_stage):
            stagers = self._stagers
            for index, handle, value in items:
                stagers[index](handle, value)
            return
        mirrors = self._mirrors
        slot_state = self._slot_state
        slots: List[int] = []
        handles: List[int] = []
        blobs: List[bytes] = []
        lens: List[int] = []
        # pass 1: validate + encode EVERYTHING before any state mutates —
        # a bad item mid-list must leave the pool exactly as it was (a
        # partially-updated staged_native set would make the next
        # advance_all emit kFlagStaged for a slot the bank never staged,
        # poisoning the whole pool with kBankErrCmd)
        for index, handle, value in items:
            if slot_state[index] != SLOT_NATIVE:
                self._stagers[index](handle, value)
                continue
            m = mirrors[index]
            if handle not in m.local_handle_set:
                raise InvalidRequest(
                    "The player handle you provided is not referring to a "
                    "local player."
                )
            blob = m.encode(value)
            if len(blob) != m.input_size:
                raise InvalidRequest(
                    f"encoded input is {len(blob)} bytes but slot "
                    f"{index}'s input size is {m.input_size}"
                )
            slots.append(index)
            handles.append(handle)
            blobs.append(blob)
            lens.append(len(blob))
        n = len(slots)
        if not n:
            return
        desc = np.empty(n, _STAGE_DTYPE)
        desc["slot"] = slots
        desc["handle"] = handles
        desc["frame"] = NULL_FRAME
        lens_arr = np.asarray(lens, np.uint32)
        desc["len"] = lens_arr
        offs = np.zeros(n, np.uint32)
        np.cumsum(lens_arr[:-1], out=offs[1:])
        desc["off"] = offs
        payload = b"".join(blobs)
        rc = self._lib.ggrs_bank_stage_inputs(
            self._bank, desc.ctypes.data, n, payload, len(payload)
        )
        if rc < 0:
            # should be unreachable after the validation above (a native
            # reject means this builder drifted from the bank): drop the
            # Python-side membership so the next tick takes the inline
            # path — the bank discards its partial staging on
            # !kFlagStaged — instead of a poisoned kFlagStaged cmd
            for index in slots:
                mirrors[index].staged_native.clear()
            raise InvalidRequest(
                f"ggrs_bank_stage_inputs rejected the staging table "
                f"({rc}): slot/handle/length mismatch"
            )
        for index, handle in zip(slots, handles):
            mirrors[index].staged_native.add(handle)

    def advance_all(self) -> List[List[GgrsRequest]]:
        """Run every session's tick (poll + advance); returns the B request
        lists in session order.  Native path: exactly one ctypes crossing
        for every bank-resident slot; evicted slots tick their Python
        session; quarantined/dead slots return empty lists."""
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            return self._advance_all_fallback()
        self._check_valid()
        self._tick_no += 1
        self._m_ticks.inc()
        tracer = self.tracer
        tracing = tracer.enabled
        t_tick = tracer.now_ns() if tracing else 0

        pack = struct.pack
        # validate EVERY bank-resident session's staged inputs before any
        # destructive step (ctrl-op swap, socket drain): raising mid-build
        # would silently lose pending disconnect ops and drained datagrams
        # on a caller retry.  (Evicted sessions enforce their own contract.)
        # A slot's inputs come through ONE mechanism per tick: the inline
        # staged dict (add_local_input) when non-empty, else the native
        # staging set (stage_inputs, §21) when complete.
        use_staged: List[bool] = [False] * len(self._mirrors)
        for i, m in enumerate(self._mirrors):
            state = self._slot_state[i]
            if state == SLOT_EVICTED:
                # same pre-crossing check for evicted sessions: their
                # advance_frame raising AFTER the bank crossing would lose
                # the healthy slots' request lists for this tick
                self._evicted[i].validate_local_inputs()
                continue
            if state not in (SLOT_NATIVE, SLOT_QUARANTINED):
                continue
            if not m.local_handles:
                continue  # nothing to stage: the inline path sends the
                # plain flag byte with zero input bytes, as always
            if m.staged_inputs:
                for handle in m.local_handles:
                    if handle not in m.staged_inputs:
                        raise InvalidRequest(
                            f"Missing local input for handle {handle} "
                            "while calling advance_frame()."
                        )
                if m.staged_native:
                    # inline wins: the native copy is stale and the bank
                    # drops it at slot-tick start on the !kFlagStaged path
                    m.staged_native.clear()
            elif (
                self._has_stage
                and len(m.staged_native) == len(m.local_handles)
            ):
                use_staged[i] = True
            else:
                missing = next(
                    h for h in m.local_handles
                    if h not in m.staged_native
                )
                raise InvalidRequest(
                    f"Missing local input for handle {missing} while "
                    "calling advance_frame()."
                )
        # gen-2 batched inbound (§23a): ONE crossing drains every covered
        # fd-backed socket BEFORE the tick snapshot — a fatal recv errno
        # faults the owning slot(s) here, so they skip this tick cleanly
        if self._drain_ok:
            _dt0 = time.perf_counter_ns()
            drained = self._drain_inbound()
            self.drain_ns += time.perf_counter_ns() - _dt0
        else:
            drained = None
        # snapshot which slots the bank steps this tick: the parse below
        # must use the build-time view even if new faults land mid-parse
        ticked = [s == SLOT_NATIVE for s in self._slot_state]
        cmd_parts: List[bytes] = []
        for i, m in enumerate(self._mirrors):
            if not ticked[i]:
                cmd_parts.append(_CMD_SKIP)  # no fields follow
                continue
            if use_staged[i]:
                # batched staging (§21): the bank already holds this
                # slot's input bytes — the cmd carries only the flag
                cmd_parts.append(_CMD_STAGED)
            else:
                cmd_parts.append(_CMD_INPUTS)
                cmd_parts.extend(
                    m.staged_inputs[h] for h in m.local_handles
                )
            ctrl = m.pending_ctrl
            m.pending_ctrl = []
            inj = self._inject_err.pop(i, None)
            if inj is not None:
                ctrl = ctrl + [(2, 0, inj)]  # op 2: simulated slot fault
            cmd_parts.append(pack("<H", len(ctrl)))
            for op, ep_idx, frame in ctrl:
                cmd_parts.append(pack("<BHq", op, ep_idx, frame))
            datagrams = []
            spec_datagrams = []
            if drained is not None and i in drained:
                # gen-2: this slot's inbound was already pulled by the
                # one-crossing batched drain above — routed record table,
                # zero per-slot Python calls (None = covered, no traffic)
                rec = drained[i]
                if rec is not None:
                    datagrams, spec_datagrams = rec
            elif not self._io_attached[i]:
                # the Python shuttle: drain + route per datagram here.
                # Attached slots drain INSIDE the crossing (recvmmsg) —
                # only injected chaos traffic rides the cmd sections.
                addr_to_spec = m.addr_to_spec
                for from_addr, data in m.socket.receive_all_datagrams():
                    ep_idx = m.addr_to_ep.get(from_addr)
                    if ep_idx is not None:
                        datagrams.append((ep_idx, data))
                    elif addr_to_spec:
                        sp_idx = addr_to_spec.get(from_addr)
                        if sp_idx is not None:
                            spec_datagrams.append((sp_idx, data))
            datagrams.extend(self._inject_dgrams.pop(i, ()))
            cmd_parts.append(pack("<H", len(datagrams)))
            for ep_idx, data in datagrams:
                cmd_parts.append(pack("<HI", ep_idx, len(data)))
                cmd_parts.append(data)
            if self._has_spec:
                # inbound viewer traffic (acks, quality, keep-alives, sync
                # probes) rides the SAME crossing
                cmd_parts.append(pack("<H", len(spec_datagrams)))
                for sp_idx, data in spec_datagrams:
                    cmd_parts.append(pack("<HI", sp_idx, len(data)))
                    cmd_parts.append(data)
        cmd = b"".join(cmd_parts)

        self.crossings += 1
        self._m_cross_tick.inc()
        t_cross = tracer.now_ns() if tracing else 0
        # the pump is the tick crossing plus native socket I/O for
        # attached slots — still exactly ONE crossing per pool tick
        crossing = (
            self._lib.ggrs_bank_pump if self._use_pump
            else self._lib.ggrs_bank_tick
        )
        rc = crossing(
            self._bank, self._clock(), cmd, len(cmd),
            self._out_buf, len(self._out_buf), ctypes.byref(self._out_len),
        )
        if rc == _native.BANK_ERR_BUFFER_TOO_SMALL:
            # kErrBufferTooSmall: the tick RAN and its output is
            # retained natively — grow and fetch (the one case that costs a
            # second crossing, e.g. a stalled peer's whole-window volley)
            self._out_buf = ctypes.create_string_buffer(
                max(self._out_len.value, 2 * len(self._out_buf))
            )
            rc = self._lib.ggrs_bank_fetch_out(
                self._bank, self._out_buf, len(self._out_buf),
                ctypes.byref(self._out_len),
            )
        if tracing:
            # the crossing span, then the native per-phase timings laid
            # end-to-end inside it (they were measured inside this very
            # window, so they nest under it and sum to the in-crossing
            # time; the gap to the crossing span is pure ctypes overhead)
            dur = tracer.now_ns() - t_cross
            tracer.add_complete("bank.crossing", t_cross, dur, cat="native",
                                args={"tick": self._tick_no})
            if self._trace_native and rc == 0:
                off = t_cross
                phases = self._parse_timing_tail()
                for name, ns in phases:
                    if name == "staging":
                        # staging accrued OUTSIDE the tick window (the
                        # stage_inputs crossings since the last tick): a
                        # sibling span ending at the crossing start, never
                        # nested inside it — the in-crossing phases still
                        # sum to the measured crossing time
                        if ns:
                            tracer.add_complete(
                                "bank.staging", t_cross - ns, ns,
                                cat="native",
                            )
                        continue
                    if ns:
                        tracer.add_complete(
                            f"bank.{name}", off, ns, cat="native"
                        )
                    off += ns
                self._last_phase_ns = dict(phases)
        if rc != 0:
            # the only whole-bank failure left is a malformed command stream
            # (a bug in THIS builder, no per-session blame possible)
            self._invalid = f"ggrs_bank_tick failed: {rc}"
            raise RuntimeError(self._invalid)
        # decode: the descriptor plane's lazy RequestPlan by default
        # (DESIGN.md §21 — classification AND request programs read from
        # the two flat tables, request objects only materialized on
        # demand); the legacy sequential parse under tracing (the
        # per-slot spans ARE the point), on pre-descriptor libraries, and
        # under GGRS_TPU_NO_FASTPATH (the parity fuzz's reference leg)
        if self._vectorized and self._has_req and not tracing:
            request_lists, retire_mask = self._parse_output_plan(ticked)
            self._plan = request_lists
        else:
            request_lists = self._parse_output(ticked)
            retire_mask = None
            self._plan = None
        self._supervise(request_lists, retire_mask)
        if tracing:
            tracer.add_complete("pool.tick", t_tick,
                                tracer.now_ns() - t_tick, cat="py")
        self.last_tick_at = time.monotonic()
        return request_lists

    def _parse_timing_tail(self) -> List[Tuple[str, int]]:
        """The tick output's timing tail: ``(phase, ns)`` pairs in bank
        order.  The count byte sits LAST so the tail parses from the end
        of the buffer, independent of the session records before it."""
        end = self._out_len.value
        n_ph = self._out_buf[end - 1][0]
        vals = struct.unpack_from(
            f"<{n_ph}Q", self._out_buf, end - 1 - 8 * n_ph
        )
        return list(zip(_phase_names(n_ph), vals))

    def _parse_output(self, ticked: List[bool]) -> List[List[GgrsRequest]]:
        """Legacy sequential parse: every slot's body record, in order.
        The reference decoder (the vectorized path is pinned
        bit-identical to it by tests/test_policy_plane.py) and the
        tracing-mode parse — per-slot spans are the point of a traced
        tick."""
        buf = memoryview(self._out_buf).cast("B")[: self._out_len.value]
        n = len(self._mirrors)
        pos = n * (
            self._hdr_stride + self._req_stride
        ) if self._has_hdr else 0
        request_lists: List[List[GgrsRequest]] = []
        tracer = self.tracer
        tracing = tracer.enabled
        # parallel decode plane (§24): with the header table's rec_len
        # jump chain every slot's byte range is known up front, so the
        # NO_FASTPATH/legacy path fans ALL slots across the DecodePool.
        # A TRACED pool stays on the interleaved reference decoder —
        # per-slot spans are the point of tracing, and fanning the byte
        # walk out would destroy that attribution.
        decs = None
        if not tracing and self._has_hdr and n > 1:
            hdr = np.frombuffer(self._out_buf, dtype=_HDR_DTYPE, count=n)
            offs = np.empty(n, np.int64)
            offs[0] = pos
            if n > 1:
                offs[1:] = pos + np.cumsum(
                    hdr["rec_len"][:-1], dtype=np.int64
                )
            decs = self._decode_slow_slots(
                buf, list(range(n)), offs.tolist(), ticked
            )
        for idx in range(n):
            t_slot = tracer.now_ns() if tracing else 0
            if decs is not None:
                requests, pos, current = self._apply_slot(
                    decs[idx], idx, ticked[idx]
                )
            else:
                requests, pos, current = self._parse_slot(
                    buf, pos, idx, ticked[idx]
                )
            request_lists.append(requests)
            if tracing:
                tracer.add_complete(
                    "pool.slot", t_slot, tracer.now_ns() - t_slot,
                    cat="py", args={"slot": idx, "frame": current},
                )
        return request_lists

    def _parse_output_plan(self, ticked: List[bool]):
        """Descriptor-plane tick decode (DESIGN.md §21): classify all B
        slots from the packed header table AND read their request
        programs from the request descriptor table — both flat NumPy
        views — then run only the irreducible per-slot work (outbound
        sends, journal taps, the wait-recommendation policy, frame
        mirrors) for fast slots, constructing ZERO request objects for
        them.  The returned :class:`RequestPlan` materializes a slot's
        pooled ``GgrsRequest`` list only when indexed;
        ``BatchedRequestExecutor`` consumes the descriptor columns
        directly instead.

        Outbound is batched (§21c): fast slots' datagrams go out through
        one ``send_datagram_batch`` call per slot (in-memory / batchable
        sockets), or ride ONE ``ggrs_net_send_table`` crossing for the
        whole tick (fd-backed sockets that are not NetBatch-attached) —
        the send-table payload is the tick output buffer itself, zero
        copies.  Per-socket send order is unchanged (records stay in slot
        order); slow slots keep the reference per-datagram path.

        Returns ``(plan, retire_mask)`` like the legacy fast path."""
        mirrors = self._mirrors
        n = len(mirrors)
        plan = RequestPlan(self, n)
        if n == 0:
            return plan, None
        hdr = np.frombuffer(self._out_buf, dtype=_HDR_DTYPE, count=n)
        req = np.frombuffer(self._out_buf, dtype=_REQ_DTYPE, count=n,
                            offset=n * self._hdr_stride)
        flags = hdr["flags"]
        pattern = req["pattern"]
        fast = (flags & _HDR_FAST_MASK) == _HDR_FAST_WANT
        # a fast slot must also carry a CLASSIFIED request program —
        # kReqOther (frame-0 double save, future shapes) takes the
        # reference decoder so a wrong descriptor can never be consumed
        fast &= pattern != _native.REQ_OTHER
        base = n * (self._hdr_stride + self._req_stride)
        rec_len = hdr["rec_len"]
        offs = np.empty(n, np.int64)
        offs[0] = base
        if n > 1:
            offs[1:] = base + np.cumsum(rec_len[:-1], dtype=np.int64)
        out_len = self._out_len.value
        plan.buffer = np.frombuffer(self._out_buf, np.uint8, count=out_len)
        plan.uniform = self._uniform
        m0 = mirrors[0]
        plan.players = m0.num_players
        plan.input_size = m0.input_size
        # the plan retains the per-slot offsets/liveness until the next
        # advance_all: keep them as the numpy arrays (compact) and take
        # throwaway int lists only for the hot loops below
        plan.offs_l = offs
        offs_l = offs.tolist()
        fast_l = fast.tolist()
        plan.live_l = fast
        self.plan_ticks += 1
        n_fast = int(np.count_nonzero(fast))
        if n_fast == 0:
            # nothing fast this tick (fault storm, first tick's frame-0
            # shapes): sequential reference parse of every slot — cheaper
            # than the column extraction + two-pass walk below when every
            # slot is slow anyway
            buf = memoryview(self._out_buf).cast("B")[:out_len]
            decs = self._decode_slow_slots(
                buf, list(range(n)), offs_l, ticked
            )
            for idx in range(n):
                if decs is not None:
                    reqs, _, _ = self._apply_slot(
                        decs[idx], idx, ticked[idx]
                    )
                else:
                    reqs, _, _ = self._parse_slot(
                        buf, offs_l[idx], idx, ticked[idx]
                    )
                plan.lists[idx] = reqs
                plan.eager_rows.append(idx)
            self.desc_slow_slots += n
            plan.quiet_rows = np.empty(0, np.int64)
            plan.quiet_frames = np.empty(0, np.int64)
            plan.quiet_offs = np.empty(0, np.int64)
            plan.quiet_adv_off = np.empty(0, np.int64)
            retire_mask = None
            if self.retire_dead_matches:
                retire_mask = [True] * n  # every slot was slow-parsed
            return plan, retire_mask

        # executor-facing columns (views into this tick's tables — valid,
        # like the plan itself, until the next advance_all)
        quiet = fast & (pattern == _native.REQ_QUIET)
        plan.quiet_rows = np.flatnonzero(quiet)
        plan.quiet_frames = req["frame"][quiet]
        plan.quiet_offs = offs[quiet]
        plan.quiet_adv_off = req["adv_off"][quiet].astype(np.int64)

        # request-kind metrics, vectorized from the descriptor columns
        # (eager slots count inside _parse_slot as before)
        resim = fast & (pattern == _native.REQ_RESIM)
        save_only = fast & (pattern == _native.REQ_SAVE_ONLY)
        trailing = (req["rflags"] & _native.REQ_FLAG_TRAILING_ADV) != 0
        n_adv_col = req["n_adv"].astype(np.int64)
        n_quiet = int(plan.quiet_rows.size)
        n_resim = int(np.count_nonzero(resim))
        n_save = n_quiet + int(np.count_nonzero(save_only)) + int(
            (n_adv_col[resim] - trailing[resim]).sum()
        )
        n_adv_total = n_quiet + int(n_adv_col[resim].sum())
        if n_save:
            self._m_req_save.inc(n_save)
        if n_resim:
            self._m_req_load.inc(n_resim)
            self._m_rollbacks.inc(n_resim)
        if n_adv_total:
            self._m_req_advance.inc(n_adv_total)

        buf = memoryview(self._out_buf).cast("B")[:out_len]
        fa_l = hdr["fa"].tolist()
        cur_l = hdr["current"].tolist()
        conf_l = hdr["confirmed"].tolist()
        flags_l = flags.tolist()
        pattern_l = pattern.tolist()
        trailing_l = trailing.tolist()
        ops_end_l = req["ops_end"].tolist()
        # plain-int columns once, not per-row structured indexing (resim
        # ticks visit hundreds of rows on a rollback-heavy pool)
        rframe_l = req["frame"].tolist()
        n_adv_l = req["n_adv"].tolist()
        adv_off_l = req["adv_off"].tolist()
        adv_stride_l = req["adv_stride"].tolist()
        CONF = _native.BANK_HDR_CONF
        unpack_from = struct.unpack_from
        recorders = self._recorders
        lists = plan.lists
        eager = plan.eager_rows

        # ---- pass 1: eager slots through the reference decoder; fast
        # slots' outbound staged/sent + per-slot pass-2 work queued ----
        table_rows: List[Tuple[int, int, int, int, int]] = []  # native tbl
        table_slots: List[int] = []
        pass2: List[Tuple[int, int]] = []  # (slot, pos after out sections)
        flush_failed: Dict[int, Tuple[int, str]] = {}  # slot -> code, msg
        # parallel decode plane (§24): every slow slot's byte range is
        # known up front (the offs jump chain), so their pure decode fans
        # out across the DecodePool BEFORE the slot walk; the walk below
        # then applies each decoded record in slot order, interleaved
        # with the fast slots exactly where the serial decoder ran —
        # side-effect order is untouched because decode is pure
        slow_rows = [idx for idx in range(n) if not fast_l[idx]]
        decs = self._decode_slow_slots(buf, slow_rows, offs_l, ticked)
        for idx in range(n):
            if not fast_l[idx]:
                if decs is not None:
                    requests, _, _ = self._apply_slot(
                        decs[idx], idx, ticked[idx]
                    )
                else:
                    requests, _, _ = self._parse_slot(
                        buf, offs_l[idx], idx, ticked[idx]
                    )
                lists[idx] = requests
                eager.append(idx)
                continue
            m = mirrors[idx]
            off = offs_l[idx]
            pos = off + ops_end_l[idx]
            rec = recorders[idx] if recorders else None
            fd = self._send_fds[idx]
            wire = self._ep_wire[idx]
            batch: Optional[List[Tuple[Any, Any]]] = (
                [] if (fd is None and m.send_batch is not None) else None
            )
            send_raw = m.send_raw
            endpoints = m.endpoints
            failed: Optional[str] = None
            for _section in (0, 1):
                (n_out,) = unpack_from("<H", buf, pos)
                pos += 2
                for _ in range(n_out):
                    ep_idx, dlen = unpack_from("<HI", buf, pos)
                    pos += 6
                    if failed is not None:
                        pos += dlen
                        continue
                    if rec is not None:
                        # forensics caveat: on the BATCHED tiers the
                        # flush outcome is only known after the whole
                        # slot staged, so a mid-flush fatal leaves EV_WIRE
                        # entries for datagrams that never hit the wire —
                        # always bounded by the EV_FAULT marker the flush
                        # failure records right after them
                        rec.record(
                            self._tick_no, EV_WIRE,
                            (ep_idx, dlen,
                             zlib.crc32(buf[pos : pos + dlen])),
                        )
                    if fd is not None:
                        # native send table: the datagram bytes stay in
                        # the output buffer; only (fd, addr, off, len) is
                        # recorded — flushed once for the whole tick
                        ip, port = wire[ep_idx]
                        table_rows.append((fd, ip, port, pos, dlen))
                        table_slots.append(idx)
                    elif batch is not None:
                        batch.append(
                            (buf[pos : pos + dlen], endpoints[ep_idx].addr)
                        )
                    else:
                        try:
                            send_raw(bytes(buf[pos : pos + dlen]),
                                     endpoints[ep_idx].addr)
                        except Exception as e:
                            failed = f"socket send failed: {e!r}"
                    pos += dlen
            if failed is None and batch:
                # one batched call per slot per tick (§21c): the socket
                # walks the list internally — same per-socket send order
                try:
                    m.send_batch(batch)
                except Exception as e:
                    failed = f"socket send failed: {e!r}"
            if failed is not None:
                flush_failed[idx] = (0, failed)
            pass2.append((idx, pos))

        # ---- the one native outbound crossing for fd-backed slots ----
        if table_rows:
            desc = np.empty(len(table_rows), _SEND_DTYPE)
            cols = list(zip(*table_rows))
            desc["fd"] = cols[0]
            desc["ip"] = cols[1]
            desc["port"] = cols[2]
            # dispatch-mode rows carry kSendFlagDispatch: a fatal errno on
            # the SHARED fd faults only the owning record's slot, the run
            # continues for co-tenants (§23b)
            send_flags = self._send_flags
            desc["flags"] = [send_flags[s] for s in table_slots]
            desc["off"] = cols[3]
            desc["len"] = cols[4]
            stats3 = (ctypes.c_uint64 * _native.NET_SEND_STATS)()
            fatal = (ctypes.c_int32 * 32)()
            rc = self._lib.ggrs_net_send_table(
                desc.ctypes.data, len(table_rows), self._out_buf, out_len,
                stats3, fatal, 16,
            )
            if rc < 0:
                # table refused whole (corrupt offsets = builder bug):
                # fault every participating slot rather than lose sends
                # silently (dict.fromkeys: deterministic slot order)
                for idx in dict.fromkeys(table_slots):
                    flush_failed.setdefault(
                        idx, (0, f"ggrs_net_send_table failed: {rc}")
                    )
            else:
                for k in range(min(rc, 16)):
                    slot = table_slots[fatal[2 * k]]
                    flush_failed.setdefault(
                        slot,
                        (_native.BANK_ERR_IO,
                         "socket send failed: batched flush errno "
                         f"{fatal[2 * k + 1]}"),
                    )
                if rc > 16:
                    # more fatal fds than the report buffer holds (a
                    # host-wide EPERM-class condition): the unreported
                    # slots' datagrams were abandoned too — fault them
                    # ALL rather than let ~B-16 slots run policy on
                    # sends that never happened
                    for idx in dict.fromkeys(table_slots):
                        flush_failed.setdefault(
                            idx,
                            (_native.BANK_ERR_IO,
                             "socket send failed: batched flush fatal "
                             f"overflow ({rc} fatal fds)"),
                        )
            if self._obs_on and stats3[1]:
                self._m_io_send_errors.inc(int(stats3[1]))
            if self._obs_on and stats3[2]:
                self._m_io_oversized.inc(int(stats3[2]))
            if stats3[3]:
                self._gso_totals["gso_sends"] += int(stats3[3])
                self._gso_totals["gso_segments"] += int(stats3[4])
                if self._obs_on:
                    self._m_gso_sends.inc(int(stats3[3]))
                    self._m_gso_segments.inc(int(stats3[4]))

        # ---- pass 2: journal taps, policy, frame mirrors, forensics ----
        for idx, pos in pass2:
            m = mirrors[idx]
            failed = idx in flush_failed
            if failed:
                # reference-decoder parity (_parse_slot): a send fault
                # suppresses the slot's requests and policy, but the
                # journal tap below still appends (the confirmed records
                # are in hand — dropping them would gap the journal) and
                # the frame mirrors still update; staged inputs are KEPT
                # for the eviction path.  Natively-staged inputs were
                # already consumed by the crossing's trailing advance —
                # reconstruct them into the inline dict from the advance
                # payload in the tick output, what eviction will re-feed
                # (the reference leg keeps its dict the same way).  With
                # input_delay > 0 the payload carries the DELAYED frame's
                # value rather than this tick's — a documented
                # approximation on this fault-within-a-fault corner; it
                # keeps eviction fed instead of raising, and delay-0
                # pools (the common case) re-feed the exact reference
                # bytes.
                if m.staged_native and trailing_l[idx]:
                    isize = m.input_size
                    po = offs_l[idx] + adv_off_l[idx]
                    if pattern_l[idx] == _native.REQ_RESIM:
                        po += (n_adv_l[idx] - 1) * adv_stride_l[idx]
                    bo = po + m.num_players
                    for h in m.local_handles:
                        m.staged_inputs[h] = bytes(
                            buf[bo + h * isize : bo + (h + 1) * isize]
                        )
                    m.staged_native.clear()
                code, detail = flush_failed[idx]
                self._on_slot_fault(idx, code, detail)
                lists[idx] = []
            hf = flags_l[idx]
            players, isize = m.num_players, m.input_size
            blob_len = players * isize
            if hf & CONF:
                # journal tap: read the confirmed-record section directly
                # (no spectators on a fast slot, so the intervening
                # sections are fixed-size)
                pos += 2 + m.mirror_len  # n_events(=0) + status mirrors
                (next_spec,) = unpack_from("<q", buf, pos)
                m.next_spec_frame = next_spec
                pos += 9 + 4  # + n_specs(=0) + n_spec_out/evts(=0)
                (n_conf,) = unpack_from("<H", buf, pos)
                pos += 2
                (conf_start,) = unpack_from("<q", buf, pos)
                pos += 8
                conf_records = []
                for _ in range(n_conf):
                    cflags = bytes(buf[pos : pos + players])
                    pos += players
                    conf_records.append(
                        (cflags, bytes(buf[pos : pos + blob_len]))
                    )
                    pos += blob_len
                sink = self._journal_sinks.get(idx)
                if sink is not None:
                    sink.append_frames(conf_start, conf_records)
            current = cur_l[idx]
            if not failed:
                pat = pattern_l[idx]
                if pat == _native.REQ_RESIM:
                    lf = rframe_l[idx]
                    plan.resim_rows.append((
                        idx, lf, n_adv_l[idx], trailing_l[idx],
                        offs_l[idx] + adv_off_l[idx], adv_stride_l[idx],
                    ))
                    rec = recorders[idx] if recorders else None
                    if rec is not None:
                        rec.record(
                            self._tick_no, EV_ROLLBACK,
                            f"load frame {lf} (was at {m.current_frame})",
                        )
                elif pat == _native.REQ_SAVE_ONLY:
                    plan.save_only_rows.append((idx, rframe_l[idx]))
                # ---- policy (the fast-slot subset: no events, no
                # consensus — just the wait recommendation) ----
                advanced = trailing_l[idx]
                fa = fa_l[idx]
                m.frames_ahead = fa
                pre_current = current - (1 if advanced else 0)
                if (
                    pre_current > m.next_recommended_sleep
                    and fa >= MIN_RECOMMENDATION
                ):
                    m.next_recommended_sleep = (
                        pre_current + RECOMMENDATION_INTERVAL
                    )
                    m.push_event((_LZ_WAIT, fa))
                if advanced:
                    if m.staged_inputs:
                        m.staged_inputs.clear()
                    if m.staged_native:
                        m.staged_native.clear()
            m.current_frame = current
            m.last_confirmed = conf_l[idx]

        if flush_failed:
            # a faulted fast slot's device program must be suppressed
            # exactly like its requests: prune it from the executor-facing
            # quiet columns and route it through the eager rows instead,
            # so the executor reads plan[idx] — the empty list, or the
            # evicted session's replacement if _supervise swaps it in
            # this same tick
            dead = np.fromiter(flush_failed, np.int64,
                               count=len(flush_failed))
            keep = ~np.isin(plan.quiet_rows, dead)
            plan.quiet_rows = plan.quiet_rows[keep]
            plan.quiet_frames = plan.quiet_frames[keep]
            plan.quiet_offs = plan.quiet_offs[keep]
            plan.quiet_adv_off = plan.quiet_adv_off[keep]
            plan.eager_rows.extend(flush_failed)

        self.fast_slot_ticks += n_fast
        self.desc_slow_slots += n - n_fast
        self._m_fast_slots.inc(n_fast)
        # "every LIVE slot was fast": skip records (quarantined / evicted
        # / dead slots) are never fast and must not pin this counter at
        # zero for the rest of a degraded pool's life
        n_skip = int(np.count_nonzero(
            (flags & _native.BANK_HDR_SKIP) != 0
        ))
        if n_fast == n - n_skip:
            self.fast_ticks += 1
        retire_mask = None
        if self.retire_dead_matches:
            # endpoint liveness can only have changed on a dirty or
            # slow-parsed slot — the retirement walk skips the rest
            retire_mask = (
                ((flags & _native.BANK_HDR_DIRTY) != 0) | ~fast
            ).tolist()
        return plan, retire_mask

    def requests_for(self, index: int) -> List[GgrsRequest]:
        """The most recent tick's request list for slot ``index`` — the
        lazy-materialization surface of the descriptor plane (§21).
        Identical to indexing the object ``advance_all`` returned; valid,
        like that object, until the next ``advance_all``."""
        plan = self._plan
        if plan is None:
            raise InvalidRequest(
                "no request plan: advance_all has not produced a "
                "descriptor-plane tick yet"
            )
        return plan[index]

    def _materialize_slot(self, plan: RequestPlan,
                          idx: int) -> List[GgrsRequest]:
        """Build slot ``idx``'s pooled ``GgrsRequest`` list from its body
        record — the deferred half of the descriptor plane.  Pooled
        per-kind objects are refilled in place (valid until the next
        ``advance_all``, like the scrape records); metrics were already
        counted from the descriptor columns at plan build."""
        if plan.tick_no != self._tick_no or plan is not self._plan:
            raise InvalidRequest(
                "stale RequestPlan: request lists are only valid until "
                "the next advance_all"
            )
        if not plan.live_l[idx]:
            return []
        m = self._mirrors[idx]
        buf = memoryview(self._out_buf).cast("B")[: len(plan.buffer)]
        off = plan.offs_l[idx]
        unpack_from = struct.unpack_from
        players, isize = m.num_players, m.input_size
        decode = m.config.input_decode
        get_cell = m.saved_states.get_cell
        (n_ops,) = unpack_from("<H", buf, off + 33)
        pos = off + 35
        requests = m.pooled_list
        requests.clear()
        saves, loads, advs = m.pool_saves, m.pool_loads, m.pool_advs
        si = li = ai = 0
        blob_len = players * isize
        for _ in range(n_ops):
            kind = buf[pos]
            pos += 1
            if kind == 2:
                if ai == len(advs):
                    advs.append(AdvanceFrame(inputs=[None] * players))
                adv = advs[ai]
                ai += 1
                inputs = adv.inputs
                bo = pos + players
                for p in range(players):
                    inputs[p] = (
                        decode(bytes(
                            buf[bo + p * isize : bo + (p + 1) * isize]
                        )),
                        _STATUS[buf[pos + p]],
                    )
                pos = bo + blob_len
                requests.append(adv)
            else:
                (frame,) = unpack_from("<q", buf, pos)
                pos += 8
                cell = get_cell(frame)
                if kind == 0:
                    if si == len(saves):
                        saves.append(
                            SaveGameState(cell=None, frame=NULL_FRAME)
                        )
                    req = saves[si]
                    si += 1
                else:
                    assert cell.frame == frame, (
                        f"rollback loads frame {frame} but its cell "
                        f"holds {cell.frame} — was the save fulfilled?"
                    )
                    if li == len(loads):
                        loads.append(
                            LoadGameState(cell=None, frame=NULL_FRAME)
                        )
                    req = loads[li]
                    li += 1
                req.cell = cell
                req.frame = frame
                requests.append(req)
        return requests

    def _parse_slot(self, buf, pos, idx, ticked_slot):
        """Positional parse of ONE slot's body record starting at
        ``pos`` — the reference decoder for a single slot, shared by the
        sequential legacy parse and the vectorized path's slow slots.
        Returns ``(requests, end_pos, current_frame)``."""
        m = self._mirrors[idx]
        unpack_from = struct.unpack_from
        players, isize = m.num_players, m.input_size
        err, landed, frames_ahead, current, confirmed, consensus, n_ops = (
            unpack_from("<iqiqqBH", buf, pos)
        )
        pos += 35
        # live: the bank actually stepped this slot and it didn't fault.
        # A faulted slot's record is status-only (its ops/outbound/events
        # were suppressed natively); parse positionally either way.
        live = ticked_slot and err == 0
        if ticked_slot and err != 0:
            self._on_slot_fault(idx, err)
        requests: List[GgrsRequest] = []
        advanced = False
        decode = m.config.input_decode
        rec = self._recorders[idx] if self._recorders else None
        for _ in range(n_ops):
            kind = buf[pos]
            pos += 1
            if kind == 2:
                statuses = bytes(buf[pos : pos + players])
                pos += players
                blob = bytes(buf[pos : pos + players * isize])
                pos += players * isize
                requests.append(AdvanceFrame(inputs=[
                    (decode(blob[p * isize : (p + 1) * isize]),
                     _STATUS[statuses[p]])
                    for p in range(players)
                ]))
                advanced = True
                self._m_req_advance.inc()
            else:
                (frame,) = unpack_from("<q", buf, pos)
                pos += 8
                cell = m.saved_states.get_cell(frame)
                if kind == 0:
                    requests.append(SaveGameState(cell=cell, frame=frame))
                    advanced = False
                    self._m_req_save.inc()
                else:
                    assert cell.frame == frame, (
                        f"rollback loads frame {frame} but its cell "
                        f"holds {cell.frame} — was the save fulfilled?"
                    )
                    requests.append(LoadGameState(cell=cell, frame=frame))
                    advanced = False
                    self._m_req_load.inc()
                    self._m_rollbacks.inc()
                    if rec is not None:
                        rec.record(
                            self._tick_no, EV_ROLLBACK,
                            f"load frame {frame} (was at "
                            f"{m.current_frame})",
                        )
        # outbound.  Broadcast layout (has_spec): the poll-phase remote
        # datagrams send immediately; the adv-phase (input) sends wait
        # until the spectator queues — LAST tick's deferred fan-out plus
        # this tick's spectator poll messages — have gone out, which is
        # the Python session's exact per-socket order (poll's
        # send_all_messages flushes remotes then spectators, then
        # advance_frame sends the remote input messages inline; the
        # fan-out messages it queues flush at the NEXT tick's poll).
        has_spec = self._has_spec
        send_raw = m.send_raw  # socket.send_datagram (raw bytes, no
        # RawMessage wrapper / re-encode) or the send_to shim
        send_failed: Optional[str] = None
        (n_out_poll,) = unpack_from("<H", buf, pos)
        pos += 2
        for _ in range(n_out_poll):
            ep_idx, dlen = unpack_from("<HI", buf, pos)
            pos += 6
            data = bytes(buf[pos : pos + dlen])
            pos += dlen
            if send_failed is not None:
                continue  # slot already faulted; keep consuming bytes
            if rec is not None:
                # wire digest: a tuple of scalars, formatted lazily by
                # dump() — cheap enough to leave on for healthy slots
                rec.record(self._tick_no, EV_WIRE,
                           (ep_idx, dlen, zlib.crc32(data)))
            try:
                send_raw(data, m.endpoints[ep_idx].addr)
            except Exception as e:  # a send fault is THIS slot's fault
                send_failed = f"socket send failed: {e!r}"
        adv_out: List[Tuple[int, bytes]] = []
        if has_spec:
            (n_out_adv,) = unpack_from("<H", buf, pos)
            pos += 2
            for _ in range(n_out_adv):
                ep_idx, dlen = unpack_from("<HI", buf, pos)
                pos += 6
                adv_out.append((ep_idx, bytes(buf[pos : pos + dlen])))
                pos += dlen
        # stage event records; dispatch AFTER the status mirrors below
        # are parsed — _on_protocol_disconnected reads m.local_last, and
        # p2p.py's _handle_event sees the status as updated by this
        # tick's EvInputs, not last tick's
        (n_events,) = unpack_from("<H", buf, pos)
        pos += 2
        staged_events = []
        for _ in range(n_events):
            kind, ep_idx = unpack_from("<BH", buf, pos)
            pos += 3
            if kind == _EV_INTERRUPTED:
                (remaining,) = unpack_from("<q", buf, pos)
                pos += 8
                staged_events.append((kind, ep_idx, remaining))
            elif kind == _EV_CHECKSUM:
                frame, lo, hi = unpack_from("<qQQ", buf, pos)
                pos += 24
                staged_events.append((kind, ep_idx, (frame, lo, hi)))
            else:
                staged_events.append((kind, ep_idx, None))
        (n_eps,) = unpack_from("<B", buf, pos)
        pos += 1
        for e in range(n_eps):
            ep = m.endpoints[e]
            ep.running = buf[pos] == 0
            pos += 1
            for h in range(players):
                disc, lf = unpack_from("<Bq", buf, pos)
                pos += 9
                ep.peer_disc[h] = bool(disc)
                ep.peer_last[h] = lf
        for h in range(players):
            disc, lf = unpack_from("<Bq", buf, pos)
            pos += 9
            m.local_disc[h] = bool(disc)
            m.local_last[h] = lf

        # ---- broadcast tail (DESIGN.md §13): spectator mirror, the
        # phase-tagged fan-out streams, hub events, journal tap ----
        if has_spec:
            next_spec, n_specs = unpack_from("<qB", buf, pos)
            pos += 9
            m.next_spec_frame = next_spec
            for e in range(n_specs):
                st, la = unpack_from("<Bq", buf, pos)
                pos += 9
                sp = m.spectators[e]
                sp.running = st == 0
                sp.last_acked = la
            (n_spec_out,) = unpack_from("<H", buf, pos)
            pos += 2
            spec_poll: List[List[bytes]] = [[] for _ in range(n_specs)]
            spec_adv: List[List[bytes]] = [[] for _ in range(n_specs)]
            for _ in range(n_spec_out):
                sp_idx, phase, dlen = unpack_from("<HBI", buf, pos)
                pos += 7
                (spec_adv if phase else spec_poll)[sp_idx].append(
                    bytes(buf[pos : pos + dlen])
                )
                pos += dlen
            (n_spec_events,) = unpack_from("<H", buf, pos)
            pos += 2
            spec_events: List[Tuple[int, int, Any]] = []
            for _ in range(n_spec_events):
                kind, sp_idx = unpack_from("<BH", buf, pos)
                pos += 3
                payload = None
                if kind == _EV_INTERRUPTED:
                    (payload,) = unpack_from("<q", buf, pos)
                    pos += 8
                spec_events.append((kind, sp_idx, payload))
            (n_conf,) = unpack_from("<H", buf, pos)
            pos += 2
            conf_start: Frame = NULL_FRAME
            conf_records: List[Tuple[bytes, bytes]] = []
            if n_conf:
                (conf_start,) = unpack_from("<q", buf, pos)
                pos += 8
                blob_len = players * isize
                for _ in range(n_conf):
                    flags = bytes(buf[pos : pos + players])
                    pos += players
                    conf_records.append((
                        flags, bytes(buf[pos : pos + blob_len]),
                    ))
                    pos += blob_len
            if live and m.spectators:
                # spectator sends: per viewer, last tick's deferred
                # fan-out datagrams then this tick's poll messages —
                # then the remote input messages, then stash this
                # tick's fan-out for the next (the Python flush order)
                fan = self._fanout_counters.get(idx)
                if fan is None:
                    fan = (
                        self._m_fanout_dgrams.labels(slot=str(idx)).inc,
                        self._m_fanout_bytes.labels(slot=str(idx)).inc,
                    )
                    self._fanout_counters[idx] = fan
                fan_d, fan_b = fan
                # gen-2 fan-out (§23c): when the slot's socket rides the
                # native send table, stage every viewer datagram as a
                # table row and flush ONCE — the native side coalesces
                # same-viewer equal-size runs into GSO segmented sends
                # (sendmmsg fallback when UDP_SEGMENT is unavailable).
                # GGRS_TPU_NO_FASTPATH pins this loop per-datagram.
                fd = (
                    self._send_fds[idx] if self._vectorized
                    and self._send_fds else None
                )
                spec_rows: Optional[List[Tuple[int, int, bytes]]] = None
                if fd is not None:
                    try:
                        spec_wire = [
                            self._resolve_wire_addr(sp.addr)
                            for sp in m.spectators
                        ]
                        spec_rows = []
                    except (TypeError, ValueError, OSError):
                        spec_rows = None  # unresolvable viewer: reference
                for e, sp in enumerate(m.spectators):
                    to_send = sp.deferred
                    sp.deferred = []
                    if e < n_specs:
                        to_send = to_send + spec_poll[e]
                    for data in to_send:
                        if send_failed is not None:
                            continue
                        if rec is not None:
                            rec.record(
                                self._tick_no, EV_WIRE,
                                (f"spec{e}", len(data),
                                 zlib.crc32(data)),
                            )
                        if spec_rows is not None:
                            # same forensics caveat as §21c: the flush
                            # outcome lands after the whole stage, so
                            # these counters may include datagrams a
                            # mid-flush fatal abandons (bounded by the
                            # EV_FAULT marker)
                            ip, port = spec_wire[e]
                            spec_rows.append((ip, port, data))
                            fan_d()
                            fan_b(len(data))
                            continue
                        try:
                            send_raw(data, sp.addr)
                            fan_d()
                            fan_b(len(data))
                        except Exception as exc:
                            send_failed = f"socket send failed: {exc!r}"
                if spec_rows and send_failed is None:
                    # flushed BEFORE the adv-phase endpoint sends below:
                    # the reference path interleaves on the same socket
                    # in exactly this order
                    send_failed = self._spec_send_table(
                        idx, fd, spec_rows
                    )
            elif not live:
                # a faulted/skipped slot's deferred stream is stale: the
                # fan-out window lives in the harvest's pending dumps
                # and is re-emitted by the evicted relay's retry timer
                for sp in m.spectators:
                    sp.deferred = []
        for ep_idx, data in adv_out:
            if send_failed is not None:
                continue
            if rec is not None:
                rec.record(self._tick_no, EV_WIRE,
                           (ep_idx, len(data), zlib.crc32(data)))
            try:
                send_raw(data, m.endpoints[ep_idx].addr)
            except Exception as e:
                send_failed = f"socket send failed: {e!r}"
        if has_spec and live and m.spectators:
            for e, sp in enumerate(m.spectators):
                if e < n_specs:
                    sp.deferred.extend(spec_adv[e])
            hub = self._spectator_hub
            if hub is not None and spec_events:
                for kind, sp_idx, payload in spec_events:
                    hub._on_native_event(idx, sp_idx, kind, payload)
        if has_spec and live and n_conf:
            sink = self._journal_sinks.get(idx)
            if sink is not None:
                sink.append_frames(conf_start, conf_records)
        if send_failed is not None:
            if m.staged_native and advanced:
                # batched staging (§21): the bank consumed the staged
                # inputs on the trailing advance before the Python-side
                # send failed — rebuild the inline dict from the decoded
                # advance (encode∘decode is the identity for
                # bank-eligible configs) so eviction re-feeds this
                # tick's inputs exactly like the inline-staged reference
                adv = next(
                    (r for r in reversed(requests)
                     if type(r) is AdvanceFrame), None,
                )
                if adv is not None:
                    encode = m.encode
                    for h in m.local_handles:
                        m.staged_inputs[h] = encode(adv.inputs[h][0])
                m.staged_native.clear()
            self._on_slot_fault(idx, 0, send_failed)
            live = False

        # ---- policy (Python): events, wait recommendation, consensus ----
        # applied only for live slots; a faulted/skipped record carries
        # no events and its policy state is frozen pending supervision
        if live:
            # events stage as lazy tag tuples (decoded on drain —
            # _materialize_events); only the checksum/disconnect kinds do
            # policy work here
            for kind, ep_idx, payload in staged_events:
                ep = m.endpoints[ep_idx]
                if kind == _EV_INTERRUPTED:
                    m.push_event((_LZ_INTERRUPTED, ep.addr, payload))
                elif kind == _EV_RESUMED:
                    m.push_event((_LZ_RESUMED, ep.addr))
                elif kind == _EV_DISCONNECTED:
                    self._on_protocol_disconnected(m, ep_idx)
                elif kind == _EV_CHECKSUM:
                    frame, lo, hi = payload
                    self._store_checksum(ep, frame, lo | (hi << 64))
            pre_current = current - (1 if advanced else 0)
            m.frames_ahead = frames_ahead
            if (
                pre_current > m.next_recommended_sleep
                and frames_ahead >= MIN_RECOMMENDATION
            ):
                m.next_recommended_sleep = (
                    pre_current + RECOMMENDATION_INTERVAL
                )
                m.push_event((_LZ_WAIT, frames_ahead))
            if advanced:
                m.staged_inputs.clear()
                if m.staged_native:
                    m.staged_native.clear()
            if consensus:
                self._run_consensus(m)
        if ticked_slot:
            m.current_frame = current
            m.last_confirmed = confirmed
        if not live:
            requests = []
        return requests, pos, current

    def _apply_slot(self, dec, idx, ticked_slot):
        """Replay ONE slot's side effects from a decoded record (§24).

        The stateful half of :meth:`_parse_slot`: ``dec`` is the
        plain-data tuple ``decode_pool.decode_slot_record`` produced on
        a worker; this method performs — on the owning thread, in slot
        order — exactly the side effects the reference decoder
        interleaves with its byte walk: request construction (cells,
        pooled objects, user input_decode), sends, EV_WIRE/EV_ROLLBACK
        forensics, event staging, status/frame mirrors, journal taps,
        fault handling, policy.  Returns ``(requests, end_pos,
        current_frame)`` — ``_parse_slot``'s contract; the parity fuzz
        pins the pair bit-identical."""
        m = self._mirrors[idx]
        players, isize = m.num_players, m.input_size
        (err, landed, frames_ahead, current, confirmed, consensus, ops,
         poll_out, adv_out, staged_events, eps_t, local_t, spec,
         end_pos) = dec
        live = ticked_slot and err == 0
        if ticked_slot and err != 0:
            self._on_slot_fault(idx, err)
        requests: List[GgrsRequest] = []
        advanced = False
        decode = m.config.input_decode
        rec = self._recorders[idx] if self._recorders else None
        for kind, a, b in ops:
            if kind == 2:
                statuses, blob = a, b
                requests.append(AdvanceFrame(inputs=[
                    (decode(blob[p * isize : (p + 1) * isize]),
                     _STATUS[statuses[p]])
                    for p in range(players)
                ]))
                advanced = True
                self._m_req_advance.inc()
            else:
                frame = a
                cell = m.saved_states.get_cell(frame)
                if kind == 0:
                    requests.append(SaveGameState(cell=cell, frame=frame))
                    advanced = False
                    self._m_req_save.inc()
                else:
                    assert cell.frame == frame, (
                        f"rollback loads frame {frame} but its cell "
                        f"holds {cell.frame} — was the save fulfilled?"
                    )
                    requests.append(LoadGameState(cell=cell, frame=frame))
                    advanced = False
                    self._m_req_load.inc()
                    self._m_rollbacks.inc()
                    if rec is not None:
                        rec.record(
                            self._tick_no, EV_ROLLBACK,
                            f"load frame {frame} (was at "
                            f"{m.current_frame})",
                        )
        has_spec = self._has_spec
        send_raw = m.send_raw
        send_failed: Optional[str] = None
        for ep_idx, data in poll_out:
            if send_failed is not None:
                continue
            if rec is not None:
                rec.record(self._tick_no, EV_WIRE,
                           (ep_idx, len(data), zlib.crc32(data)))
            try:
                send_raw(data, m.endpoints[ep_idx].addr)
            except Exception as e:
                send_failed = f"socket send failed: {e!r}"
        for e, (running, prs) in enumerate(eps_t):
            ep = m.endpoints[e]
            ep.running = running == 0
            for h in range(players):
                disc, lf = prs[h]
                ep.peer_disc[h] = bool(disc)
                ep.peer_last[h] = lf
        for h in range(players):
            disc, lf = local_t[h]
            m.local_disc[h] = bool(disc)
            m.local_last[h] = lf
        if has_spec and spec is not None:
            (next_spec, n_specs, sstat, spec_poll, spec_adv, spec_events,
             conf_start, conf_records) = spec
            m.next_spec_frame = next_spec
            for e, (st, la) in enumerate(sstat):
                sp = m.spectators[e]
                sp.running = st == 0
                sp.last_acked = la
            n_conf = len(conf_records)
            if live and m.spectators:
                fan = self._fanout_counters.get(idx)
                if fan is None:
                    fan = (
                        self._m_fanout_dgrams.labels(slot=str(idx)).inc,
                        self._m_fanout_bytes.labels(slot=str(idx)).inc,
                    )
                    self._fanout_counters[idx] = fan
                fan_d, fan_b = fan
                fd = (
                    self._send_fds[idx] if self._vectorized
                    and self._send_fds else None
                )
                spec_rows: Optional[List[Tuple[int, int, bytes]]] = None
                if fd is not None:
                    try:
                        spec_wire = [
                            self._resolve_wire_addr(sp.addr)
                            for sp in m.spectators
                        ]
                        spec_rows = []
                    except (TypeError, ValueError, OSError):
                        spec_rows = None
                for e, sp in enumerate(m.spectators):
                    to_send = sp.deferred
                    sp.deferred = []
                    if e < n_specs:
                        to_send = to_send + spec_poll[e]
                    for data in to_send:
                        if send_failed is not None:
                            continue
                        if rec is not None:
                            rec.record(
                                self._tick_no, EV_WIRE,
                                (f"spec{e}", len(data),
                                 zlib.crc32(data)),
                            )
                        if spec_rows is not None:
                            ip, port = spec_wire[e]
                            spec_rows.append((ip, port, data))
                            fan_d()
                            fan_b(len(data))
                            continue
                        try:
                            send_raw(data, sp.addr)
                            fan_d()
                            fan_b(len(data))
                        except Exception as exc:
                            send_failed = f"socket send failed: {exc!r}"
                if spec_rows and send_failed is None:
                    send_failed = self._spec_send_table(
                        idx, fd, spec_rows
                    )
            elif not live:
                for sp in m.spectators:
                    sp.deferred = []
        for ep_idx, data in adv_out:
            if send_failed is not None:
                continue
            if rec is not None:
                rec.record(self._tick_no, EV_WIRE,
                           (ep_idx, len(data), zlib.crc32(data)))
            try:
                send_raw(data, m.endpoints[ep_idx].addr)
            except Exception as e:
                send_failed = f"socket send failed: {e!r}"
        if has_spec and spec is not None and live and m.spectators:
            for e, sp in enumerate(m.spectators):
                if e < n_specs:
                    sp.deferred.extend(spec_adv[e])
            hub = self._spectator_hub
            if hub is not None and spec_events:
                for kind, sp_idx, payload in spec_events:
                    hub._on_native_event(idx, sp_idx, kind, payload)
        if has_spec and spec is not None and live and n_conf:
            sink = self._journal_sinks.get(idx)
            if sink is not None:
                sink.append_frames(conf_start, conf_records)
        if send_failed is not None:
            if m.staged_native and advanced:
                adv = next(
                    (r for r in reversed(requests)
                     if type(r) is AdvanceFrame), None,
                )
                if adv is not None:
                    encode = m.encode
                    for h in m.local_handles:
                        m.staged_inputs[h] = encode(adv.inputs[h][0])
                m.staged_native.clear()
            self._on_slot_fault(idx, 0, send_failed)
            live = False
        if live:
            for kind, ep_idx, payload in staged_events:
                ep = m.endpoints[ep_idx]
                if kind == _EV_INTERRUPTED:
                    m.push_event((_LZ_INTERRUPTED, ep.addr, payload))
                elif kind == _EV_RESUMED:
                    m.push_event((_LZ_RESUMED, ep.addr))
                elif kind == _EV_DISCONNECTED:
                    self._on_protocol_disconnected(m, ep_idx)
                elif kind == _EV_CHECKSUM:
                    frame, lo, hi = payload
                    self._store_checksum(ep, frame, lo | (hi << 64))
            pre_current = current - (1 if advanced else 0)
            m.frames_ahead = frames_ahead
            if (
                pre_current > m.next_recommended_sleep
                and frames_ahead >= MIN_RECOMMENDATION
            ):
                m.next_recommended_sleep = (
                    pre_current + RECOMMENDATION_INTERVAL
                )
                m.push_event((_LZ_WAIT, frames_ahead))
            if advanced:
                m.staged_inputs.clear()
                if m.staged_native:
                    m.staged_native.clear()
            if consensus:
                self._run_consensus(m)
        if ticked_slot:
            m.current_frame = current
            m.last_confirmed = confirmed
        if not live:
            requests = []
        return requests, end_pos, current

    def _decode_slow_slots(self, buf, slots: List[int], offs_l,
                           ticked) -> Optional[Dict[int, Any]]:
        """Fan the tick's slow slots across the DecodePool (§24) and
        return ``slot -> decoded tuple`` — or None when the parallel
        plane must stay out of the way (serial backend, no pool, a
        single slot not worth the fan-out): the caller then uses the
        reference ``_parse_slot`` directly, which IS the serial
        fallback, bit for bit."""
        pool = self._decode_pool
        if pool is None or pool.backend == "serial" or len(slots) < 2:
            return None
        has_spec = self._has_spec
        mirrors = self._mirrors
        jobs = []
        for idx in slots:
            m = mirrors[idx]
            jobs.append(
                (offs_l[idx], m.num_players, m.input_size, has_spec)
            )
        decs = pool.decode_slots(buf, jobs)
        self.decode_parallel_ticks += 1
        return dict(zip(slots, decs))

    def _spec_send_table(self, idx: int, fd: int,
                         rows: List[Tuple[int, int, bytes]]) -> Optional[str]:
        """Flush one slot's staged spectator fan-out through the native
        send table (§23c) — one crossing for the whole viewer burst; the
        native side GSO-coalesces same-viewer equal-size runs and windows
        the rest through sendmmsg.  Returns a fault string (the
        ``send_failed`` contract of :meth:`_parse_slot`) or None."""
        payload = b"".join(r[2] for r in rows)
        desc = np.empty(len(rows), _SEND_DTYPE)
        desc["fd"] = fd
        desc["ip"] = [r[0] for r in rows]
        desc["port"] = [r[1] for r in rows]
        desc["flags"] = self._send_flags[idx] if self._send_flags else 0
        off = 0
        offs: List[int] = []
        lens: List[int] = []
        for _, _, data in rows:
            offs.append(off)
            lens.append(len(data))
            off += len(data)
        desc["off"] = offs
        desc["len"] = lens
        stats = (ctypes.c_uint64 * _native.NET_SEND_STATS)()
        fatal = (ctypes.c_int32 * 8)()
        rc = self._lib.ggrs_net_send_table(
            desc.ctypes.data, len(rows), payload, len(payload),
            stats, fatal, 4,
        )
        if self._obs_on and stats[1]:
            self._m_io_send_errors.inc(int(stats[1]))
        if self._obs_on and stats[2]:
            self._m_io_oversized.inc(int(stats[2]))
        if stats[3]:
            self._gso_totals["gso_sends"] += int(stats[3])
            self._gso_totals["gso_segments"] += int(stats[4])
            if self._obs_on:
                self._m_gso_sends.inc(int(stats[3]))
                self._m_gso_segments.inc(int(stats[4]))
        if rc < 0:
            return f"socket send failed: ggrs_net_send_table {rc}"
        if rc > 0:
            return (
                "socket send failed: batched fan-out errno "
                f"{fatal[1]}"
            )
        return None

    # ------------------------------------------------------------------
    # supervision: quarantine, eviction, retirement (fault isolation)
    # ------------------------------------------------------------------

    def _advance_all_fallback(self) -> List[List[GgrsRequest]]:
        """Per-session Python path with the same per-slot containment: a
        session whose tick raises is marked dead (no Python-to-Python
        eviction exists — it IS the fallback) while the rest keep ticking.
        Deliberate contract errors (``GgrsError``: missing inputs, not
        synchronized) still propagate to the caller."""
        self._tick_no += 1
        self._m_ticks.inc()
        tracer = self.tracer
        t_tick = tracer.now_ns() if tracer.enabled else 0
        # validate every live session's preconditions BEFORE any session
        # advances: a contract raise mid-loop would discard earlier
        # sessions' already-generated request lists (the native path makes
        # the same check before its crossing).  Handshaking sessions are
        # POLLED first — raising without polling would starve the handshake
        # of its sync-request/reply datagrams (in-pool peers would never
        # answer each other) and livelock the pool — then the pool raises
        # once for all of them, losslessly: nothing has advanced yet.
        synchronizing = False
        for i, s in enumerate(self._sessions):
            if self._slot_state[i] in (SLOT_DEAD, SLOT_MIGRATED):
                continue
            if s.current_state() is SessionState.SYNCHRONIZING:
                s.poll_remote_clients()
                synchronizing |= (
                    s.current_state() is SessionState.SYNCHRONIZING
                )
        if synchronizing:
            raise NotSynchronized()
        for i, s in enumerate(self._sessions):
            if self._slot_state[i] not in (SLOT_DEAD, SLOT_MIGRATED):
                s.validate_local_inputs()
        if self._prediction_plane is not None:
            # one device op predicts every registered slot's missing
            # inputs; queues fall back to the scalar strategy on any row
            # the gather didn't cover (predict/batched.py contract)
            self._prediction_plane.begin_tick()
        out: List[List[GgrsRequest]] = []
        for i, s in enumerate(self._sessions):
            if self._slot_state[i] in (SLOT_DEAD, SLOT_MIGRATED):
                out.append([])
                continue
            try:
                out.append(s.advance_frame())
            except GgrsError:
                raise
            except Exception as e:
                self._on_slot_fault(i, 0, f"{type(e).__name__}: {e}")
                # ggrs-model: transitions(quarantined->dead, evicted->dead)
                self._set_slot_state(i, SLOT_DEAD)
                out.append([])
                continue
            if self.retire_dead_matches:
                self._maybe_retire(i, s._remote_endpoints and all(
                    not ep.is_running() for ep in s._remote_endpoints
                ))
        if tracer.enabled:
            tracer.add_complete("pool.tick", t_tick,
                                tracer.now_ns() - t_tick, cat="py")
        self.last_tick_at = time.monotonic()
        return out

    def _maybe_retire(self, index: int, match_over) -> None:
        """With ``retire_dead_matches``, a slot whose every remote endpoint
        has disconnected is retired: the match is over, so empty request
        lists beat running free on dummy inputs forever.  ``match_over``
        must already be False for sessions with no remote endpoints."""
        if self.retire_dead_matches and match_over:
            self._fault_log[index].append(SlotFault(
                self._tick_no, 0,
                "match over: every remote endpoint disconnected",
            ))
            # ggrs-model: transitions(native->dead, evicted->dead)
            self._set_slot_state(index, SLOT_DEAD)

    def _supervise(self, request_lists: List[List[GgrsRequest]],
                   retire_mask: Optional[List[bool]] = None) -> None:
        """Post-tick supervision pass: retire dead matches, drive pending
        evictions, and tick evicted sessions — filling their slots of
        ``request_lists`` in place.

        Incremental (DESIGN.md §19): the walk is driven by ``_attention``
        — the quarantined/evicted slots — instead of range(B); on the
        quiet steady state this loop touches nothing.  The optional
        ``retire_mask`` (from the header's dirty bits) bounds the
        ``retire_dead_matches`` liveness check the same way: endpoint
        liveness only changes on dirty or slow-parsed ticks."""
        if self.retire_dead_matches:
            for i, state in enumerate(self._slot_state):
                if state != SLOT_NATIVE:
                    continue
                if retire_mask is not None and not retire_mask[i]:
                    continue
                m = self._mirrors[i]
                self._maybe_retire(i, m.endpoints and all(
                    not ep.running for ep in m.endpoints
                ))
        if not self._attention:
            return
        evictions_this_tick = 0
        for i in sorted(self._attention):
            state = self._slot_state[i]
            if state == SLOT_QUARANTINED:
                # retry-storm clamp: a shard-wide failure quarantines many
                # slots on one tick; at most EVICT_MAX_PER_TICK eviction
                # attempts (each a harvest crossing + session build) run
                # per supervision pass — the rest stay quarantined and are
                # picked up on following ticks, keeping the tick budget
                # bounded while the jittered backoff spreads the retries
                if evictions_this_tick < self._evict_max_per_tick:
                    if self._try_evict(i):
                        evictions_this_tick += 1
                state = self._slot_state[i]
            if state != SLOT_EVICTED:
                continue
            session = self._evicted[i]
            try:
                reqs = session.advance_frame()
            except GgrsError:
                raise
            except Exception as e:
                # the fallback faulted too (e.g. the same malicious peer):
                # blast radius stays this one slot
                self._on_slot_fault(i, 0, f"evicted tick: {type(e).__name__}: {e}")
                # ggrs-model: transitions(evicted->dead)
                self._set_slot_state(i, SLOT_DEAD)
                request_lists[i] = []
                continue
            load = self._pending_load.pop(i, None)
            if load is not None:
                # the resume tick leads with restoring the game state saved
                # at the slot's last committed frame
                reqs = [load] + reqs
            request_lists[i] = reqs
            if self.retire_dead_matches:
                self._maybe_retire(i, session._remote_endpoints and all(
                    not ep.is_running() for ep in session._remote_endpoints
                ))

    def _set_slot_state(self, index: int, new_state: str) -> None:
        """The single path for supervision transitions: flips the state,
        counts the transition, keeps the per-state gauge current, and
        appends the transition to the slot's flight recorder."""
        old = self._slot_state[index]
        if old == new_state:
            return
        if (old, new_state) not in _SLOT_TRANSITION_SET:
            # undeclared edge: loud in logs, never fatal in production —
            # the static conformance lint is the enforcing layer
            _logger.error(
                "undeclared supervision transition %s -> %s (slot %d)",
                old, new_state, index,
            )
        self._slot_state[index] = new_state
        # the staging router resolves slot state at transition time, not
        # per call (§21 satellite) — rebuild this slot's dispatch
        if self._stagers:
            self._stagers[index] = self._make_stager(index)
        # incremental supervision: only quarantined/evicted slots need the
        # post-tick walk; dead/migrated slots need nothing and native
        # slots are the bank's business
        if new_state in (SLOT_QUARANTINED, SLOT_EVICTED):
            self._attention.add(index)
        else:
            self._attention.discard(index)
        # transition feed for incremental consumers (fleet shards): bounded
        # — an undrained feed must never grow without bound, but the bound
        # must hold a whole shard-wide failure (every slot transitioning
        # on one tick) or the forensics sweep silently loses post-mortems
        self._state_transitions.append(
            (index, old, new_state, self._tick_no)
        )
        del self._state_transitions[:-max(256, 2 * len(self._slot_state))]
        if new_state != SLOT_NATIVE and self._io_attached[index]:
            # a slot leaving the bank leaves the batched datapath with it:
            # the evicted session owns the socket (per-datagram Python
            # path), so io_state() must say "python" and the NetBatch is
            # released rather than idling attached forever
            self._detach_io(index)
        # the drain plan indexes slots by state: any transition in or out
        # of SLOT_NATIVE changes which fds/routes the one-crossing
        # inbound drain may touch (a faulted slot must drop out of the
        # plan IMMEDIATELY — its socket now belongs to supervision)
        self._refresh_drain()
        self._m_transitions.labels(src=old, dst=new_state).inc()
        self._m_slot_state.labels(state=old).dec()
        self._m_slot_state.labels(state=new_state).inc()
        rec = self._recorders[index] if self._recorders else None
        if rec is not None:
            rec.record(self._tick_no, EV_STATE, f"{old} -> {new_state}")

    def _on_slot_fault(self, index: int, code: int, detail: str = "") -> None:
        """Record a fault and quarantine the slot: the bank stops stepping
        it (skip flag) while eviction — resume on the Python fallback from
        the last committed frame — is attempted with bounded backoff."""
        named = detail or _native.BANK_ERR_NAMES.get(
            code, f"bank error {code}"
        )
        self._fault_log[index].append(SlotFault(self._tick_no, code, named))
        self._m_faults.labels(code=str(code)).inc()
        rec = self._recorders[index] if self._recorders else None
        if rec is not None:
            rec.record(self._tick_no, EV_FAULT, f"code={code} {named}")
        if self._slot_state[index] == SLOT_NATIVE:
            self._set_slot_state(index, SLOT_QUARANTINED)
            self._quarantined_at[index] = self._tick_no
            self._evict_attempts[index] = 0
            self._evict_next_try[index] = self._tick_no  # try immediately
            if code == _native.BANK_ERR_SYNC:
                # desync-class fault: synthesize the forensic artifact NOW,
                # while the mirrors, journal tail, and trace window still
                # hold the state around the fault (DESIGN.md §14)
                self._build_native_desync_report(index, code, named)
            # the post-mortem: the slot's recent history, logged the moment
            # it leaves the bank (DESIGN.md §12 flight-recorder contract)
            if rec is not None:
                _logger.warning(
                    "slot %d quarantined at tick %d (code=%d %s); flight "
                    "recorder (last 32 events):\n%s",
                    index, self._tick_no, code, named, rec.dump(32),
                )

    def _build_native_desync_report(self, index: int, code: int,
                                    named: str) -> None:
        """DesyncReport for a desync-class native fault: no local checksum
        history exists on the bank path (desync detection is a fallback
        feature), so the report carries the evidence that IS available —
        the peers' reported checksums, the flight recorder, the journal
        tail, and the active trace window."""
        m = self._mirrors[index]
        rec = self._recorders[index] if self._recorders else None
        # per-peer attribution: same-frame reports from different peers
        # must not overwrite each other — a multi-endpoint window is keyed
        # by peer address (the disagreeing peer is the forensic lead)
        peer_windows = {
            ep.addr: dict(ep.pending_checksums) for ep in m.endpoints
        }
        single = m.endpoints[0].addr if len(m.endpoints) == 1 else None
        report = build_desync_report(
            kind="native-fault",
            detected_frame=m.current_frame,
            addr=single,
            remote_history=peer_windows[single] if single is not None else {},
            recorder=rec,
            journal=self._journal_sinks.get(index),
            tracer=self.tracer,
            detail=f"slot {index} quarantined by desync-class fault "
                   f"code={code} ({named}) at pool tick {self._tick_no}",
        )
        if single is None:
            report.checksum_window = {
                f"remote[{addr!r}]": window
                for addr, window in peer_windows.items() if window
            }
        self._desync_reports[index] = report
        if rec is not None:
            rec.record(self._tick_no, EV_DESYNC,
                       f"code={code} report built (frame {m.current_frame})")
        self.tracer.add_instant("pool.desync", cat="py", slot=index,
                                frame=m.current_frame, code=code)

    def desync_report(self, index: int) -> Optional[DesyncReport]:
        """The forensic report built when slot ``index`` quarantined on a
        desync-class fault, or None.  (The checksum-compare detection path
        lives on Python sessions — see ``P2PSession.desync_reports``.)"""
        return self._desync_reports.get(index)

    def _try_evict(self, index: int) -> bool:
        """One eviction attempt for a quarantined slot.  Returns True when
        an attempt actually ran (success or failure) so the caller's
        per-tick clamp counts real work, not backoff skips."""
        if self._tick_no < self._evict_next_try.get(index, 0):
            return False  # backing off
        attempt = self._evict_attempts.get(index, 0) + 1
        self._evict_attempts[index] = attempt
        self._evict_next_try[index] = (
            self._tick_no + EVICT_BACKOFF_TICKS * attempt
            + _evict_jitter(index, attempt)
        )
        rec = self._recorders[index] if self._recorders else None
        try:
            with self.tracer.span("pool.evict", slot=index):
                session, load_req = self._evict(index)
        except Exception as e:
            self._fault_log[index].append(SlotFault(
                self._tick_no, 0, f"eviction attempt {attempt} failed: {e}"
            ))
            self._m_evict_failures.inc()
            if rec is not None:
                rec.record(self._tick_no, EV_EVICT,
                           f"attempt {attempt} failed: {e}")
            if attempt >= EVICT_MAX_ATTEMPTS:
                # ggrs-model: transitions(quarantined->dead)
                self._set_slot_state(index, SLOT_DEAD)
                if rec is not None:
                    _logger.error(
                        "slot %d marked dead after %d eviction attempts; "
                        "flight recorder (last 32 events):\n%s",
                        index, attempt, rec.dump(32),
                    )
            return True
        self._evicted[index] = session
        self._pending_load[index] = load_req
        # ggrs-model: transitions(quarantined->evicted)
        self._set_slot_state(index, SLOT_EVICTED)
        self._m_evictions.inc()
        self._m_evict_latency.observe(
            self._tick_no - self._quarantined_at.get(index, self._tick_no)
        )
        self._fault_log[index].append(SlotFault(
            self._tick_no, 0,
            f"evicted to Python fallback, resuming from frame "
            f"{load_req.frame}",
        ))
        if rec is not None:
            rec.record(self._tick_no, EV_EVICT,
                       f"resumed on fallback from frame {load_req.frame}")
            _logger.warning(
                "slot %d evicted at tick %d, resuming from frame %d; flight "
                "recorder (last 32 events):\n%s",
                index, self._tick_no, load_req.frame, rec.dump(32),
            )
        return True

    def _evict(self, index: int, *, lockstep: bool = False):
        """Build a fresh ``P2PSession`` resuming from the slot's last
        committed frame: harvest the native state (read-only, retry-safe),
        adopt it through the adoption seam, feed this tick's staged inputs,
        and hand back the session plus the leading ``LoadGameState``.

        ``lockstep=True`` is the load-shed demotion variant (DESIGN.md
        §27): the same adoption seam, but the resumed session runs with
        ``max_prediction == 0`` — confirmed frames only, no saves, no
        rollbacks.  The ``LoadGameState`` handed back is the POOL's
        one-time resume protocol, not session rollback machinery: it is
        the last load this slot will ever emit."""
        m = self._mirrors[index]
        builder, socket = self._builders[index]
        if lockstep:
            # shallow copy: the registry/endpoints are rebuilt by
            # start_p2p_session below; the original builder never starts
            # another session for this slot (the slot leaves NATIVE for
            # good), so sharing the registry object is safe
            builder = copy.copy(builder)
            builder.with_max_prediction_window(0)
        try:
            h = self._harvest(index)
        except Exception:
            # crash recovery (DESIGN.md §13): the native slot's resumable
            # state is gone (corrupt harvest, dead bank memory).  A match
            # journal, when attached, can stand in — its tail window holds
            # the same confirmed inputs the harvest would have recovered,
            # so the slot resumes from the journal instead of dying.
            recover = self._journal_recovery.get(index)
            if recover is None:
                raise
            h = recover()
            self._fault_log[index].append(SlotFault(
                self._tick_no, 0,
                "harvest unavailable; resuming from journal tail "
                f"(frame {h['last_confirmed']})",
            ))
        resume, cell = _select_resume_frame(h, m.saved_states)
        session = builder.start_p2p_session(socket)
        endpoint_states = {}
        for e, ep in enumerate(m.endpoints):
            he = h["endpoints"][e]
            # peer mirrors: the harvest copy is authoritative (the
            # vectorized pool's Python mirrors may be quiet-tick stale);
            # journal-synthesized harvests lack them — fall back to the
            # mirror, which was fresh as of the fault tick's slow parse
            endpoint_states[ep.addr] = dict(
                magic=ep.magic,
                running=he["state"] == 0,
                peer_connect_status=list(zip(
                    he.get("peer_disc") or ep.peer_disc,
                    he.get("peer_last") or ep.peer_last,
                )),
                last_recv_frame=he["last_recv"],
                recv_entries=he["recv_entries"],
                last_acked_frame=he["last_acked_frame"],
                send_base=he["send_base"],
                pending=he["pending"],
                pending_checksums=ep.pending_checksums,
            )
        session.adopt_resume_state(
            frame=resume,
            last_confirmed=resume,
            saved_states=m.saved_states,
            connect_status=list(zip(h["local_disc"], h["local_last"])),
            player_inputs=h["player_inputs"],
            endpoint_states=endpoint_states,
            next_recommended_sleep=m.next_recommended_sleep,
            pending_events=_materialize_events(m.event_queue),
            next_spectator_frame=h.get("next_spectator_frame", 0),
        )
        m.event_queue.clear()
        # broadcast continuity: the relay falls back to the Python session
        # (p2p.py's own spectator path), resuming each viewer's fan-out
        # window mid-stream — builder-declared endpoints are adopted in
        # place, hub-attached viewers are grafted through the adoption seam
        if m.spectators:
            self._adopt_spectators(session, builder, m, h)
        sink = self._journal_sinks.get(index)
        if sink is not None:
            from ..broadcast.journal import JournalTap

            # the tap needs the session config: it re-ENCODES the decoded
            # inputs the relay hands it back into the journal's fixed-size
            # wire blobs
            session.adopt_spectator_endpoint(
                JournalTap.ADDR, JournalTap(sink, m.config)
            )
        decode = m.config.input_decode
        staged_native = h.get("staged_inputs") or {}
        for handle in m.local_handles:
            blob = m.staged_inputs.get(handle)
            if blob is None:
                # batched staging (§21): the blobs live in the bank; the
                # harvest's staged tail is the authoritative copy
                blob = staged_native.get(handle)
            if blob is not None:
                session.add_local_input(handle, decode(blob))
        m.staged_inputs.clear()
        m.staged_native.clear()
        # the evicted session routes through the same pooled-request /
        # lazy-event decode economics as the vectorized bank path: the
        # pool consumes its request list tick-synchronously (DESIGN.md
        # §19; the degraded-mode gap this narrows is priced by
        # bench host_bank_degraded)
        session.enable_request_pooling()
        # forensic continuity: the evicted session keeps tracing into the
        # pool's ring, recording into the slot's flight recorder, and
        # citing the slot's journal tail in any future DesyncReport
        session.attach_forensics(
            recorder=self._recorders[index] if self._recorders else None,
            tracer=self.tracer if self.tracer.enabled else None,
            journal=self._journal_sinks.get(index),
        )
        return session, LoadGameState(cell=cell, frame=resume)

    def _harvest(self, index: int) -> Dict[str, Any]:
        """One ``ggrs_bank_harvest`` crossing, parsed into the adoption
        inputs (see session_bank.cpp for the layout)."""
        self.harvests += 1
        self._m_cross_harvest.inc()
        buf = ctypes.create_string_buffer(1 << 16)
        out_len = ctypes.c_size_t(0)
        while True:
            rc = self._lib.ggrs_bank_harvest(
                self._bank, index, buf, len(buf), ctypes.byref(out_len)
            )
            if rc == _native.BANK_ERR_BUFFER_TOO_SMALL:
                buf = ctypes.create_string_buffer(
                    max(out_len.value, 2 * len(buf))
                )
                continue
            if rc != 0:
                raise RuntimeError(f"ggrs_bank_harvest failed: {rc}")
            break
        b = bytes(buf.raw[: out_len.value])
        unpack_from = struct.unpack_from
        current, confirmed, disc_frame = unpack_from("<qqq", b, 0)
        players, isize = unpack_from("<BI", b, 24)
        pos = 29
        local_disc: List[bool] = []
        local_last: List[Frame] = []
        player_inputs: List[Tuple[Frame, List[bytes]]] = []
        for _ in range(players):
            disc, last = unpack_from("<Bq", b, pos)
            pos += 9
            local_disc.append(bool(disc))
            local_last.append(last)
            start, count = unpack_from("<qI", b, pos)
            pos += 12
            blobs = [
                b[pos + i * isize : pos + (i + 1) * isize]
                for i in range(count)
            ]
            pos += count * isize
            player_inputs.append((start, blobs))
        (n_eps,) = unpack_from("<B", b, pos)
        pos += 1
        endpoints: List[Dict[str, Any]] = []
        for _ in range(n_eps):
            (state,) = unpack_from("<B", b, pos)
            pos += 1
            # harvest v2 (header-capable library): per-endpoint peer
            # status mirrors follow the state byte — authoritative for
            # eviction/export since the vectorized pool's Python mirrors
            # skip quiet-tick refreshes
            peer_disc: List[bool] = []
            peer_last: List[Frame] = []
            if self._has_hdr:
                for _p in range(players):
                    d, lf = unpack_from("<Bq", b, pos)
                    pos += 9
                    peer_disc.append(bool(d))
                    peer_last.append(lf)
            last_acked, base_len = unpack_from("<qI", b, pos)
            pos += 12
            send_base = b[pos : pos + base_len]
            pos += base_len
            (n_pending,) = unpack_from("<H", b, pos)
            pos += 2
            pending: List[Tuple[Frame, bytes]] = []
            for _ in range(n_pending):
                frame, dlen = unpack_from("<qI", b, pos)
                pos += 12
                pending.append((frame, b[pos : pos + dlen]))
                pos += dlen
            last_recv, n_recv = unpack_from("<qH", b, pos)
            pos += 10
            recv_entries: List[Tuple[Frame, bytes]] = []
            for _ in range(n_recv):
                frame, dlen = unpack_from("<qI", b, pos)
                pos += 12
                recv_entries.append((frame, b[pos : pos + dlen]))
                pos += dlen
            endpoints.append(dict(
                state=state, last_acked_frame=last_acked,
                send_base=send_base, pending=pending,
                last_recv=last_recv, recv_entries=recv_entries,
                peer_disc=peer_disc, peer_last=peer_last,
            ))
        next_spec: Frame = 0
        spectators: List[Dict[str, Any]] = []
        if self._has_spec:
            next_spec, n_specs = unpack_from("<qB", b, pos)
            pos += 9
            for _ in range(n_specs):
                (state,) = unpack_from("<B", b, pos)
                pos += 1
                last_acked, base_len = unpack_from("<qI", b, pos)
                pos += 12
                send_base = b[pos : pos + base_len]
                pos += base_len
                (n_pending,) = unpack_from("<H", b, pos)
                pos += 2
                pending = []
                for _ in range(n_pending):
                    frame, dlen = unpack_from("<qI", b, pos)
                    pos += 12
                    pending.append((frame, b[pos : pos + dlen]))
                    pos += dlen
                spectators.append(dict(
                    state=state, last_acked_frame=last_acked,
                    send_base=send_base, pending=pending,
                ))
        staged: Dict[int, bytes] = {}
        if self._has_stage:
            # staged-inputs tail (§21): inputs staged natively that no
            # advance consumed — eviction/export re-feed them exactly
            # like the Python-side staged dict
            (n_staged,) = unpack_from("<B", b, pos)
            pos += 1
            for _ in range(n_staged):
                (sh,) = unpack_from("<i", b, pos)
                pos += 4
                staged[sh] = b[pos : pos + isize]
                pos += isize
        if pos != len(b):
            raise RuntimeError("harvest buffer layout mismatch")
        return dict(
            current=current, last_confirmed=confirmed,
            disconnect_frame=disc_frame, local_disc=local_disc,
            local_last=local_last, player_inputs=player_inputs,
            endpoints=endpoints, next_spectator_frame=next_spec,
            spectators=spectators, staged_inputs=staged,
        )

    def _adopt_spectators(self, session, builder, m: _SessionMirror,
                          h: Dict[str, Any]) -> None:
        """Graft the slot's fan-out endpoints onto the evicted Python
        session: builder-declared spectator endpoints are adopted in place,
        hub-attached viewers get fresh ``PeerProtocol``s through
        ``P2PSession.adopt_spectator_endpoint``.  Each resumes its harvested
        send window (ack base + unacked pending), so the viewer sees a
        retransmission hiccup, not a reset stream.  The grafting itself is
        shared with the fleet's migration/failover adoption
        (``broadcast.hub.graft_spectator_endpoints``)."""
        from ..broadcast.hub import graft_spectator_endpoints

        spec_states = h.get("spectators") or []
        graft_spectator_endpoints(session, builder, [
            dict(
                addr=sp.addr, magic=sp.magic, handles=list(sp.handles),
                running=sp.running,
                state=spec_states[e] if e < len(spec_states) else None,
            )
            for e, sp in enumerate(m.spectators)
        ])
        for sp in m.spectators:
            sp.deferred = []

    # ------------------------------------------------------------------
    # fleet seam (ggrs_tpu/fleet): live migration export + slot release
    # ------------------------------------------------------------------

    def export_resume_state(self, index: int) -> Dict[str, Any]:
        """Process-portable resume bundle for one bank-resident slot — the
        source half of live match migration (DESIGN.md §16).  The bundle
        carries everything ``adopt_resume_bundle`` needs to resume the
        match on ANOTHER pool, possibly in another process: the harvested
        native state (falling back to the registered journal recovery when
        the harvest is dead), the resume frame's fulfilled game state
        (pickled), the endpoint/spectator wire identities (magics, connect
        mirrors, pending checksums), and this tick's staged inputs.  Plain
        data only — it must survive a serialize→deserialize round trip
        (pinned by tests/test_fleet.py).  Read-only and retry-safe; pair
        with :meth:`release_slot` once the bundle is adopted elsewhere."""
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            raise InvalidRequest(
                "export_resume_state requires the native bank"
            )
        state = self._slot_state[index]
        if state not in (SLOT_NATIVE, SLOT_QUARANTINED):
            raise InvalidRequest(
                f"slot {index} is {state}: only bank-resident slots can "
                "export a resume bundle"
            )
        m = self._mirrors[index]
        try:
            h = self._harvest(index)
        except Exception:
            # same crash-recovery ladder as eviction: the journal tail
            # stands in when the native resumable state is gone
            recover = self._journal_recovery.get(index)
            if recover is None:
                raise
            h = recover()
        resume, cell = _select_resume_frame(h, m.saved_states)
        return dict(
            version=1,
            num_players=m.num_players,
            input_size=m.input_size,
            max_prediction=m.max_prediction,
            local_handles=list(m.local_handles),
            resume_frame=resume,
            state_blob=pickle.dumps(
                (cell.data(), cell.checksum),
                protocol=_BUNDLE_PICKLE_PROTOCOL,
            ),
            harvest=h,
            next_recommended_sleep=m.next_recommended_sleep,
            # materialize: the queue holds lazy tag tuples; the bundle's
            # consumer extends a real session's event queue verbatim
            pending_events=_materialize_events(m.event_queue),
            endpoints=[
                # identity from the mirror; liveness + peer mirrors from
                # the harvest when it carries them (authoritative under
                # the vectorized parse — the Python mirrors may be
                # quiet-tick stale), mirror fallback otherwise
                dict(
                    addr=ep.addr, handles=list(ep.handles), magic=ep.magic,
                    running=(
                        h["endpoints"][e]["state"] == 0
                        if e < len(h["endpoints"]) and "state" in h["endpoints"][e]
                        else ep.running
                    ),
                    peer_disc=list(
                        h["endpoints"][e].get("peer_disc") or ep.peer_disc
                        if e < len(h["endpoints"]) else ep.peer_disc
                    ),
                    peer_last=list(
                        h["endpoints"][e].get("peer_last") or ep.peer_last
                        if e < len(h["endpoints"]) else ep.peer_last
                    ),
                    pending_checksums=dict(ep.pending_checksums),
                )
                for e, ep in enumerate(m.endpoints)
            ],
            spectators=[
                dict(addr=sp.addr, magic=sp.magic, handles=list(sp.handles),
                     running=sp.running)
                for sp in m.spectators
            ],
            staged_inputs={
                # native staging first (§21 harvest tail), inline staging
                # wins on conflict (the same precedence advance_all uses)
                **{
                    int(sh): bytes(blob)
                    for sh, blob in (h.get("staged_inputs") or {}).items()
                },
                **{
                    handle: bytes(blob)
                    for handle, blob in m.staged_inputs.items()
                },
            },
        )

    def release_slot(self, index: int, detail: str = "migrated") -> None:
        """Retire a slot whose match now lives on another pool (the commit
        point of live migration): the bank stops stepping it, its native
        I/O detaches cleanly (NetBatch freed, delta keys purged — the
        ``_detach_io`` leak check), its journal tap and staged state drop,
        and the slot lands in the MIGRATED state — request lists and
        events go empty, like dead, but the state records that the match
        itself lives on elsewhere."""
        if not self._finalized:
            self._finalize()
        state = self._slot_state[index]
        if state in (SLOT_DEAD, SLOT_MIGRATED):
            return
        if state == SLOT_EVICTED:
            self._evicted.pop(index, None)
            self._pending_load.pop(index, None)
        if self._native_active and index < len(self._mirrors):
            m = self._mirrors[index]
            m.staged_inputs.clear()
            m.staged_native.clear()
            m.event_queue.clear()
            m.pending_ctrl = []
            for sp in m.spectators:
                sp.deferred = []
        self._inject_dgrams.pop(index, None)
        self._inject_err.pop(index, None)
        if index in self._journal_sinks:
            # the destination journals through its own tap from here on
            self.set_confirmed_stream(index, None)
        self._fault_log[index].append(
            SlotFault(self._tick_no, 0, f"released: {detail}")
        )
        # ggrs-model: transitions(native->migrated, quarantined->migrated, evicted->migrated)
        self._set_slot_state(index, SLOT_MIGRATED)

    # ------------------------------------------------------------------
    # input plane: lockstep demotion + device-batched prediction
    # (DESIGN.md §27)
    # ------------------------------------------------------------------

    def demote_to_lockstep(self, index: int) -> Frame:
        """Load-shed demotion (ROADMAP item 5 hook, DESIGN.md §27):
        move a HEALTHY bank-resident slot to the lockstep tier.  The
        match keeps running — same peers, same wire address, same
        journal tap — but as a ``max_prediction == 0`` Python session:
        confirmed frames only, zero save/load work, no rollback
        re-simulation.  Cheapest possible tier for a pool shedding tick
        budget under flash-crowd load.

        Rides the eviction seam: harvest → adopt → replay this tick's
        staged inputs, landing in the EVICTED supervision state (the
        per-session fallback tier; ``in_lockstep`` distinguishes demoted
        slots from fault evictions).  Returns the resume frame; the
        caller sees the one-time adoption ``LoadGameState`` prepended to
        the slot's next request list, after which the session never
        emits another save or load (pinned by tests/test_input_plane.py).

        One-way: promotion back to the bank is a future concern — the
        fleet re-admits demoted matches by migration instead."""
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            raise InvalidRequest(
                "demote_to_lockstep requires the native bank (a fallback "
                "pool's sessions already run per-session; build them "
                "lockstep via with_max_prediction_window(0) instead)"
            )
        state = self._slot_state[index]
        if state != SLOT_NATIVE:
            raise InvalidRequest(
                f"slot {index} is {state}: only healthy bank-resident "
                "slots demote to lockstep (quarantined slots take the "
                "eviction path)"
            )
        rec = self._recorders[index] if self._recorders else None
        with self.tracer.span("pool.demote_lockstep", slot=index):
            session, load_req = self._evict(index, lockstep=True)
        assert session.in_lockstep_mode()
        self._evicted[index] = session
        self._pending_load[index] = load_req
        # ggrs-model: transitions(native->evicted)
        self._set_slot_state(index, SLOT_EVICTED)
        self._lockstep_slots[index] = self._tick_no
        self._m_demotions.inc()
        if self.timeline_sink is not None:
            try:
                self.timeline_sink(TL_DEMOTE_LOCKSTEP, index,
                                   {"frame": load_req.frame})
            except Exception:
                pass  # a broken sink must never block load-shedding
        self._fault_log[index].append(SlotFault(
            self._tick_no, 0,
            f"demoted to lockstep tier, resuming from frame "
            f"{load_req.frame}",
        ))
        if rec is not None:
            rec.record(self._tick_no, EV_EVICT,
                       f"demoted to lockstep from frame {load_req.frame}")
        return load_req.frame

    def in_lockstep(self, index: int) -> bool:
        """True when ``index`` was demoted to the lockstep tier (it runs
        a ``max_prediction == 0`` fallback session)."""
        return index in self._lockstep_slots

    def lockstep_slots(self) -> Dict[int, int]:
        """Demoted slots: index -> the pool tick the demotion ran on."""
        return dict(self._lockstep_slots)

    def attach_prediction_plane(self, plane) -> None:
        """Serve every fallback session's prediction-mode entries from
        one device-batched table (predict.batched, DESIGN.md §27): the
        plane gathers each queue's last-added input once per pool tick
        (``begin_tick`` in ``_advance_all_fallback``) and answers
        ``predict_at`` from the batched kernel's output.  Fallback pools
        only — batched predictors are deliberately not native-eligible,
        so a pool built with one always lands here."""
        if not self._finalized:
            self._finalize()
        if self._native_active:
            raise InvalidRequest(
                "the prediction plane serves the per-session fallback "
                "path; this pool runs the native bank (whose sync core "
                "predicts repeat-last in-kernel already)"
            )
        for i, session in enumerate(self._sessions):
            session.bind_prediction_plane(plane, i)
        self._prediction_plane = plane

    def prediction_plane(self):
        """The attached ``DevicePredictionPlane``, or None."""
        return self._prediction_plane

    # ------------------------------------------------------------------
    # broadcast seams (driven by ggrs_tpu.broadcast.SpectatorHub)
    # ------------------------------------------------------------------

    def _attach_spectator(self, index: int, addr, magic: int,
                          handles: Optional[List[int]] = None) -> int:
        """Attach one fan-out endpoint to slot ``index`` (native path; the
        hub owns the policy and calls this).  Must happen before the match
        confirms its first frame — the native side refuses later joins."""
        if not self._finalized:
            self._finalize()
        if not self._native_active or not self._has_spec:
            raise InvalidRequest(
                "native spectator fan-out unavailable on this pool"
            )
        m = self._mirrors[index]
        if addr in m.addr_to_spec or addr in m.addr_to_ep:
            raise InvalidRequest(f"address {addr!r} already attached")
        sp_idx = self._lib.ggrs_bank_attach_spectator(
            self._bank, index, magic, self._clock()
        )
        if sp_idx < 0:
            raise InvalidRequest(
                "spectator attach refused (match already past frame 0?): "
                f"{_native.BANK_ERR_NAMES.get(sp_idx, sp_idx)}"
            )
        m.addr_to_spec[addr] = int(sp_idx)
        m.spectators.append(_SpectatorMirror(addr, magic, handles or []))
        self._m_spectators.labels(slot=str(index)).set(len(m.spectators))
        if self._io_attached[index]:
            # the native datapath must be able to route this viewer; an
            # unresolvable address drops the WHOLE slot back to the Python
            # shuttle (per-slot automatic fallback) rather than silently
            # never fanning out to one viewer
            try:
                ip, port = self._resolve_wire_addr(addr)
            except (TypeError, ValueError, OSError):
                self._detach_io(index)
            else:
                self._lib.ggrs_bank_map_addr(
                    self._bank, index, 1, int(sp_idx), ip, port
                )
        # the drain plan's per-slot wire map must learn the new viewer
        # (and a dispatch hub must claim its source address) before the
        # next tick's one-crossing drain
        self._refresh_drain()
        return int(sp_idx)

    def _detach_spectator(self, index: int, addr) -> None:
        """Detach a viewer: the native endpoint shuts down immediately (no
        disconnect linger) and stops receiving the stream."""
        if not self._finalized:
            self._finalize()
        m = self._mirrors[index]
        sp_idx = m.addr_to_spec.get(addr)
        if sp_idx is None:
            raise InvalidRequest(f"no spectator at address {addr!r}")
        if self._native_active and self._slot_state[index] in (
            SLOT_NATIVE, SLOT_QUARANTINED
        ):
            self._lib.ggrs_bank_detach_spectator(self._bank, index, sp_idx)
        sp = m.spectators[sp_idx]
        sp.running = False
        sp.deferred = []
        self._refresh_drain()
        if index in self._evicted:
            ep = self._evicted[index]._player_reg.spectators.get(addr)
            if ep is not None:
                ep.disconnect()

    def _disconnect_spectator(self, index: int, sp_idx: int) -> None:
        """Queue the hub's disconnect decision as next tick's ctrl op (the
        same one-tick-late policy application as remote disconnects)."""
        m = self._mirrors[index]
        m.pending_ctrl.append((3, sp_idx, 0))
        m.spectators[sp_idx].running = False

    def set_confirmed_stream(self, index: int, sink,
                             recovery=None) -> None:
        """Attach a journal sink: the slot's newly-confirmed frames arrive
        at ``sink.append_frames(start_frame, records)`` FROM THE TICK
        CROSSING (zero extra crossings; records are ``(blank_flags,
        joined_inputs)`` pairs).  ``recovery``, when given, is called if
        eviction's native harvest fails and must return a harvest-shaped
        dict built from the journal tail (crash recovery)."""
        if not self._finalized:
            self._finalize()
        if sink is None:
            self._journal_sinks.pop(index, None)
            self._journal_recovery.pop(index, None)
            if self._native_active and self._has_spec:
                self._lib.ggrs_bank_set_confirmed_stream(
                    self._bank, index, 0
                )
            return
        if not self._native_active or not self._has_spec:
            raise InvalidRequest(
                "native confirmed-stream tap unavailable on this pool"
            )
        rc = self._lib.ggrs_bank_set_confirmed_stream(self._bank, index, 1)
        if rc != 0:
            raise InvalidRequest(
                "journal tap refused (match already past frame 0?): "
                f"{_native.BANK_ERR_NAMES.get(rc, rc)}"
            )
        self._journal_sinks[index] = sink
        if recovery is not None:
            self._journal_recovery[index] = recovery

    def spectator_states(self, index: int) -> List[Dict[str, Any]]:
        """Hub-facing mirror of one slot's fan-out endpoints: address,
        liveness, the viewer's ack watermark, and the catchup lag
        ((next_spectator_frame - 1) - last_acked).  On the Python-session
        paths (fallback pool, evicted slot) the live endpoints answer."""
        if not self._finalized:
            self._finalize()
        if not self._native_active or index in self._evicted:
            session = (
                self._evicted[index] if index in self._evicted
                else self._sessions[index]
            )
            tip = getattr(session, "_next_spectator_frame", 0) - 1
            out = []
            for addr, sp in session._player_reg.spectators.items():
                if not hasattr(sp, "_core"):
                    continue  # journal taps have no wire state
                la = getattr(sp._core, "last_acked_frame", None)
                la = la() if la is not None else NULL_FRAME
                out.append(dict(
                    addr=addr, running=sp.is_running(), last_acked=la,
                    catchup_lag=(
                        max(0, tip - la) if sp.is_running() else 0
                    ),
                ))
            return out
        m = self._mirrors[index]
        tip = m.next_spec_frame - 1
        return [
            dict(
                addr=sp.addr, running=sp.running, last_acked=sp.last_acked,
                catchup_lag=(
                    max(0, tip - sp.last_acked) if sp.running else 0
                ),
            )
            for sp in m.spectators
        ]

    # ------------------------------------------------------------------
    # batched socket datapath (DESIGN.md §15): observables + seams
    # ------------------------------------------------------------------

    def _io_delta(self, slot: int, key, value: int) -> int:
        """Delta of a cumulative native counter since the last scrape (the
        registry's counters are inc-only; the NetBatch reports totals)."""
        k = (slot, key)
        prev = self._io_prev.get(k, 0)
        if value > prev:
            self._io_prev[k] = value
            return value - prev
        return 0

    def _bump_io_hist(self, fam, slot: int, key: str, buckets, sum_delta):
        """Fold one slot's cumulative batch-size buckets into the pool
        histogram (sum approximated by the datagram delta — a batch-size
        histogram's sum IS its datagram count)."""
        child = getattr(fam, "_default", None)
        if child is None or getattr(fam, "kind", "") != "histogram":
            return
        total = 0
        for j, v in enumerate(buckets):
            d = self._io_delta(slot, (key, j), v)
            child.counts[j] += d
            total += d
        child.count += total
        child.sum += sum_delta

    def _apply_io_metrics(self, stats: List[Dict[str, Any]]) -> None:
        """Refresh the io instruments from per-slot NetBatch records (the
        detach path's final-snapshot flush; the per-scrape walk uses
        :meth:`_apply_io_metrics_live`, driven by the attached-slot list
        instead of range(B))."""
        if not self._obs_on:
            return
        for s in stats:
            io = s.get("io")
            if io:
                self._apply_io_record(s["index"], io)

    def _apply_io_metrics_live(self, stats: List[Dict[str, Any]]) -> None:
        """The per-scrape io-delta walk, incremental: only the slots with
        a live NetBatch attachment are visited (``self._io_live``) — at
        B=256 with no native io this is a no-op, not 256 dict probes."""
        if not self._obs_on or not self._io_live:
            return
        for slot in self._io_live:
            io = stats[slot].get("io")
            if io:
                self._apply_io_record(slot, io)

    def _apply_io_record(self, slot: int, io: Dict[str, Any]) -> None:
        """Fold one slot's cumulative NetBatch counters into the registry
        instruments (delta-encoded: the native counters are totals)."""
        recv_d = self._io_delta(slot, "recv_datagrams",
                                io["recv_datagrams"])
        send_d = self._io_delta(slot, "send_datagrams",
                                io["send_datagrams"])
        self._m_io_recvmmsg.inc(
            self._io_delta(slot, "recv_calls", io["recv_calls"]))
        self._m_io_sendmmsg.inc(
            self._io_delta(slot, "send_calls", io["send_calls"]))
        self._m_io_dgrams_in.inc(recv_d)
        self._m_io_dgrams_out.inc(send_d)
        self._m_io_send_errors.inc(
            self._io_delta(slot, "send_errors", io["send_errors"]))
        self._m_io_oversized.inc(
            self._io_delta(slot, "oversized", io["oversized"]))
        self._bump_io_hist(self._m_io_recv_batch, slot, "rb",
                           io["recv_batches"], recv_d)
        self._bump_io_hist(self._m_io_send_batch, slot, "sb",
                           io["send_batches"], send_d)

    @property
    def native_io_active(self) -> bool:
        """At least one slot's datagrams flow through the kernel-batched
        native datapath (socket → crossing → socket, zero Python)."""
        if not self._finalized:
            self._finalize()
        return any(self._io_attached)

    def io_state(self, index: int) -> str:
        """``"native"`` when the slot's socket is attached to the batched
        datapath, ``"python"`` when it rides the per-datagram shuttle."""
        if not self._finalized:
            self._finalize()
        return "native" if self._io_attached[index] else "python"

    def io_stats(self) -> Dict[str, Any]:
        """Aggregated NetBatch counters over every attached slot (from
        the one-crossing stats scrape; all zeros when nothing is
        attached).  Keys: ``_native.IO_STAT_FIELDS``, plus the gen-2
        additions (§23): ``drain`` (batched-inbound totals +
        ``crossings``), ``gso`` (segmented-send totals), and
        ``capabilities`` (the per-feature fallback matrix)."""
        out: Dict[str, Any] = dict.fromkeys(_native.IO_STAT_FIELDS, 0)
        if not self._finalized:
            self._finalize()
        if self._native_active:
            for s in self._bank_stats():
                io = s.get("io")
                # a detached slot's live tail is gone; its retained final
                # snapshot keeps the totals monotonic
                if io is None:
                    io = self._io_final.get(s["index"])
                if io:
                    for k in _native.IO_STAT_FIELDS:
                        out[k] += io[k]
        out["drain"] = dict(
            self._drain_totals, crossings=self.drain_crossings
        )
        out["gso"] = dict(self._gso_totals)
        out["decode"] = (
            dict(self._decode_pool.stats(),
                 parallel_ticks=self.decode_parallel_ticks)
            if self._decode_pool is not None
            else {"backend": "serial", "workers": 1, "jobs": 0,
                  "batches": 0, "decode_ns": 0, "worker_jobs": {},
                  "parallel_ticks": 0}
        )
        out["capabilities"] = self.io_capabilities()
        return out

    def _io_set_capture(self, index: int, on: bool = True) -> None:
        """Test seam: tee every natively-sent datagram of slot ``index``
        into a drainable buffer (the wire-parity pin's capture side)."""
        if not self._finalized:
            self._finalize()
        if not self._io_attached[index]:
            raise InvalidRequest(f"slot {index} is not on the native io path")
        self._lib.ggrs_net_set_capture(
            self._net_handles[index], 1 if on else 0
        )

    def _io_drain_capture(self, index: int) -> List[Tuple[Any, bytes]]:
        """Drain slot ``index``'s capture tee: ``((ip, port), bytes)`` per
        datagram, in exact send order."""
        if not self._finalized:
            self._finalize()
        if not self._io_attached[index]:
            raise InvalidRequest(f"slot {index} is not on the native io path")
        handle = self._net_handles[index]
        buf = ctypes.create_string_buffer(1 << 16)
        out_len = ctypes.c_size_t(0)
        while True:
            rc = self._lib.ggrs_net_drain_capture(
                handle, buf, len(buf), ctypes.byref(out_len)
            )
            if rc == _native.BANK_ERR_BUFFER_TOO_SMALL:
                buf = ctypes.create_string_buffer(
                    max(out_len.value, 2 * len(buf))
                )
                continue
            if rc != 0:
                raise RuntimeError(f"ggrs_net_drain_capture failed: {rc}")
            break
        b = buf.raw[: out_len.value]
        out: List[Tuple[Any, bytes]] = []
        pos = 0
        unpack_from = struct.unpack_from
        while pos < len(b):
            ip, port, dlen = unpack_from("<IHI", b, pos)
            pos += 10
            addr = (_pysocket.inet_ntoa(ip.to_bytes(4, "little")), port)
            out.append((addr, b[pos : pos + dlen]))
            pos += dlen
        return out

    def inject_socket_errno(self, index: int, err: int,
                            count: int = 1) -> None:
        """Chaos hook: the next ``count`` datagrams slot ``index`` stages
        on the native datapath fail with errno ``err`` before any syscall
        — transient errnos (ENOBUFS, EAGAIN...) count as packet loss, a
        fatal errno faults the slot (``BANK_ERR_IO``) exactly like a
        raising ``sendto`` on the Python path."""
        if not self._finalized:
            self._finalize()
        if not self._io_attached[index]:
            raise InvalidRequest(f"slot {index} is not on the native io path")
        self._lib.ggrs_net_inject_send_errno(
            self._net_handles[index], int(err), int(count)
        )

    # ------------------------------------------------------------------
    # chaos hooks (tests + scripts/chaos.py)
    # ------------------------------------------------------------------

    def inject_datagram(self, index: int, from_addr, data: bytes) -> None:
        """Chaos hook: deliver raw datagram bytes to session ``index`` as if
        they arrived from ``from_addr``, without touching the network (other
        slots' traffic and fault-rng streams are unperturbed).  Native slots
        stage for the next tick's crossing; evicted slots process
        immediately through the session's receive path."""
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            raise InvalidRequest("inject_datagram requires the native bank")
        state = self._slot_state[index]
        if state == SLOT_EVICTED:
            ep = self._evicted[index]._player_reg.remotes.get(from_addr)
            if ep is None:
                raise InvalidRequest(f"no endpoint for address {from_addr!r}")
            ep.handle_datagram(data)
            return
        if state != SLOT_NATIVE:
            # quarantined/dead slots process no traffic; dropping silently
            # would let a chaos run report clean without exercising its fault
            raise InvalidRequest(
                f"slot {index} is {state}: it processes no datagrams"
            )
        m = self._mirrors[index]
        ep_idx = m.addr_to_ep.get(from_addr)
        if ep_idx is None:
            raise InvalidRequest(f"no endpoint for address {from_addr!r}")
        self._inject_dgrams.setdefault(index, []).append((ep_idx, bytes(data)))

    def inject_slot_error(self, index: int, code: Optional[int] = None) -> None:
        """Chaos hook: make session ``index`` fault with ``code`` (default
        ``BANK_ERR_INJECTED``) on the next native tick — the stand-in for a
        real mid-tick native fault, driven through the real ctrl-op path."""
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            raise InvalidRequest("inject_slot_error requires the native bank")
        if self._slot_state[index] != SLOT_NATIVE:
            raise InvalidRequest(
                f"slot {index} is {self._slot_state[index]}: only "
                "bank-resident slots can take a simulated native fault"
            )
        self._inject_err[index] = int(
            code if code is not None else _native.BANK_ERR_INJECTED
        )

    # ------------------------------------------------------------------
    # supervision observables
    # ------------------------------------------------------------------

    def slot_state(self, index: int) -> str:
        """``"native"`` (bank-resident, or the healthy fallback),
        ``"quarantined"``, ``"evicted"``, or ``"dead"``."""
        if not self._finalized:
            self._finalize()
        return self._slot_state[index]

    def fault_log(self, index: int) -> List[SlotFault]:
        if not self._finalized:
            self._finalize()
        return list(self._fault_log[index])

    def drain_state_transitions(self) -> List[Tuple[int, str, str, int]]:
        """Ship-and-clear the supervision transition feed: ``(slot, old,
        new, tick)`` per transition since the last drain (bounded at
        ``max(256, 2 * B)`` while undrained — sized to hold a whole
        shard-wide failure).  Incremental consumers — the fleet shard's
        forensics sweep — react to exactly these instead of polling every
        slot's state every tick."""
        out = self._state_transitions
        self._state_transitions = []
        return out

    # ------------------------------------------------------------------
    # observability: the one-crossing stat harvest (DESIGN.md §12)
    # ------------------------------------------------------------------

    def flight_recorder(self, index: int) -> Optional[FlightRecorder]:
        """The slot's flight recorder (None when metrics are disabled)."""
        if not self._finalized:
            self._finalize()
        return self._recorders[index] if self._recorders else None

    def flight_dump(self, index: int, last: int = 32) -> str:
        """Formatted dump of the slot's newest ``last`` recorded events —
        the post-mortem surface (also logged automatically on quarantine
        and eviction)."""
        rec = self.flight_recorder(index)
        if rec is None:
            return "  (flight recorder disabled)"
        return rec.dump(last)

    def scrape(self) -> List[Dict[str, Any]]:
        """Harvest every slot's protocol/sync counters and refresh the
        scrape gauges.  Native path: ONE ``ggrs_bank_stats`` ctypes
        crossing for the whole bank, cached per pool tick (repeat scrapes
        and ``network_stats`` calls within a tick reuse it) — the tick
        crossing count (``crossings``) is never touched; scrapes count in
        ``stat_crossings``.  Evicted slots report from their live Python
        session; quarantined slots report their frozen bank state.  The
        returned records are re-filled in place on the next scrape (zero
        steady-state allocation) — copy what you need to keep."""
        if not self._finalized:
            self._finalize()
        with trace_span("ggrs.obs.scrape"), self.tracer.span("pool.scrape"):
            if self._native_active:
                stats = self._bank_stats()
            else:
                stats = [
                    self._session_stats(i, s)
                    for i, s in enumerate(self._sessions)
                ]
            self._update_scrape_gauges(stats)
        return stats

    def native_phase_totals(self) -> Optional[Tuple[int, Dict[str, int]]]:
        """``(timed_ticks, {phase: total_ns})`` accumulated by the native
        phase timers since the bank was built — the cumulative view of the
        per-tick timing tail, refreshed by ``scrape()`` (it rides the
        stats crossing).  None until tracing is armed and a scrape ran."""
        return self._phase_totals

    def last_tick_phases(self) -> Optional[Dict[str, int]]:
        """The most recent tick's in-crossing phase ns (the same numbers
        re-emitted as ``bank.*`` trace spans), or None."""
        return self._last_phase_ns

    def _bank_stats(self) -> List[Dict[str, Any]]:
        if (
            self._stats_cache is not None
            and self._stats_cache[0] == self._tick_no
        ):
            return self._stats_cache[1]
        if not hasattr(self._lib, "ggrs_bank_stats"):
            # prebuilt pre-obs library: mirrors only, no native counters
            stats = [self._mirror_stats(i) for i in range(len(self._mirrors))]
        else:
            self.stat_crossings += 1
            self._m_cross_stats.inc()
            if self._scrape_buf is None:
                self._scrape_buf = ctypes.create_string_buffer(
                    max(1 << 16, 256 * sum(
                        1 + len(m.endpoints) for m in self._mirrors
                    ))
                )
            out_len = ctypes.c_size_t(0)
            while True:
                rc = self._lib.ggrs_bank_stats(
                    self._bank, self._scrape_buf, len(self._scrape_buf),
                    ctypes.byref(out_len),
                )
                if rc == _native.BANK_ERR_BUFFER_TOO_SMALL:
                    self._scrape_buf = ctypes.create_string_buffer(
                        max(out_len.value, 2 * len(self._scrape_buf))
                    )
                    continue
                if rc != 0:
                    raise RuntimeError(f"ggrs_bank_stats failed: {rc}")
                break
            stats = self._refresh_bank_records(out_len.value)
            self._apply_io_metrics_live(stats)
        # evicted (and dead-after-eviction) slots: the bank record froze at
        # fault time; the live numbers are the Python session's
        for i, session in self._evicted.items():
            stats[i] = self._session_stats(i, session)
        self._stats_cache = (self._tick_no, stats)
        return stats

    _EP_KEYS = (
        "state", "ping", "send_queue_len", "last_acked_frame",
        "last_recv_frame", "local_frames_behind", "remote_frames_behind",
        "frame_advantage", "packets_sent", "bytes_sent", "stats_start",
    )

    def _refresh_bank_records(self, n: int) -> List[Dict[str, Any]]:
        """Parse one ``ggrs_bank_stats`` dump (layout: session_bank.cpp)
        into the pool's record dicts, IN PLACE.

        Hot for the scrape budget: one ``unpack_from`` per record (header /
        endpoint, straight off the ctypes buffer) and zero steady-state
        allocation — the record dicts are built once and re-filled, so a
        scrape-per-tick driver at B=64 stays inside the <5% tick-p99
        budget instead of feeding the gen-0 GC ~500 dicts per tick.  The
        returned records are live views: valid until the next scrape."""
        if self._bank_records is None:
            self._bank_records = [
                dict(
                    index=i, state="", current_frame=0, last_confirmed=0,
                    ticks=0, rollbacks=0, rollback_frames=0,
                    max_rollback_depth=0, faults=0,
                    endpoints=[
                        dict.fromkeys(self._EP_KEYS, 0) | {
                            "addr": ep.addr,
                            "core": dict.fromkeys(_native.EP_STAT_FIELDS, 0),
                        }
                        for ep in m.endpoints
                    ],
                    next_spectator_frame=0,
                    spectators=[],
                    io=None,
                )
                for i, m in enumerate(self._mirrors)
            ]
        unpack_from = struct.unpack_from
        buf = self._scrape_buf
        end = n
        if self._trace_native:
            # cumulative timing tail (count byte last): u64 timed_ticks,
            # n_ph * u64 totals, u8 n_ph — parsed from the end, like the
            # tick output's tail
            (n_ph,) = unpack_from("<B", buf, n - 1)
            tail = 8 + 8 * n_ph + 1
            vals = unpack_from(f"<{n_ph + 1}Q", buf, n - tail)
            self._phase_totals = (
                vals[0], dict(zip(_phase_names(n_ph), vals[1:]))
            )
            end = n - tail
        pos = 0
        for i, rec in enumerate(self._bank_records):
            (rec["current_frame"], rec["last_confirmed"], rec["ticks"],
             rec["rollbacks"], rec["rollback_frames"],
             rec["max_rollback_depth"], rec["faults"], n_eps) = unpack_from(
                "<qq5QB", buf, pos
            )
            rec["state"] = self._slot_state[i]
            pos += 57
            if n_eps != len(rec["endpoints"]):
                raise RuntimeError("bank stats endpoint count mismatch")
            for es in rec["endpoints"]:
                (es["state"], es["ping"], es["send_queue_len"],
                 es["last_acked_frame"], es["last_recv_frame"],
                 es["local_frames_behind"], es["remote_frames_behind"],
                 es["frame_advantage"], es["packets_sent"],
                 es["bytes_sent"], es["stats_start"], c0, c1, c2, c3, c4,
                 c5, c6) = unpack_from("<B10q7Q", buf, pos)
                pos += 137
                core = es["core"]
                (core["emits"], core["emit_bytes"], core["acks"],
                 core["datagrams"], core["new_frames"], core["drops"],
                 core["fallbacks"]) = (c0, c1, c2, c3, c4, c5, c6)
            if self._has_spec:
                next_spec, n_specs = unpack_from("<qB", buf, pos)
                pos += 9
                rec["next_spectator_frame"] = next_spec
                specs = rec["spectators"]
                if len(specs) != n_specs:  # dynamic attach since last build
                    del specs[:]
                    specs.extend(
                        dict(addr=sp.addr, state=0, last_acked_frame=0,
                             pending_len=0, ping=0, packets_sent=0,
                             bytes_sent=0, stats_start=0)
                        for sp in self._mirrors[i].spectators[:n_specs]
                    )
                for ss in specs:
                    (ss["state"], ss["last_acked_frame"],
                     ss["pending_len"], ss["ping"], ss["packets_sent"],
                     ss["bytes_sent"], ss["stats_start"]) = unpack_from(
                        "<B6q", buf, pos
                    )
                    pos += 49
            if self._has_io_layout:
                # batched-datapath tail (DESIGN.md §15): u8 flag, then 22
                # u64 NetBatch counters when this slot has a socket
                # attached.  Refilled in place, like everything else here.
                (has_io,) = unpack_from("<B", buf, pos)
                pos += 1
                if has_io:
                    words = unpack_from(
                        f"<{_native.IO_STAT_WORDS}Q", buf, pos
                    )
                    pos += 8 * _native.IO_STAT_WORDS
                    nf = len(_native.IO_STAT_FIELDS)
                    nb = len(_native.IO_BATCH_BUCKETS) + 1
                    io = rec["io"]
                    if io is None:
                        io = rec["io"] = dict.fromkeys(
                            _native.IO_STAT_FIELDS, 0
                        ) | {"recv_batches": [0] * nb,
                             "send_batches": [0] * nb}
                    for k, v in zip(_native.IO_STAT_FIELDS, words):
                        io[k] = v
                    io["recv_batches"][:] = words[nf:nf + nb]
                    io["send_batches"][:] = words[nf + nb:]
                else:
                    rec["io"] = None
        if pos != end:
            raise RuntimeError("bank stats buffer layout mismatch")
        # a fresh list (the evicted overrides below must not clobber the
        # master records); the dicts themselves are shared live views
        return list(self._bank_records)

    def _mirror_stats(self, index: int) -> Dict[str, Any]:
        """Minimal record from the Python-side mirrors alone (prebuilt
        pre-obs native library: no counter symbols to read)."""
        m = self._mirrors[index]
        return dict(
            index=index, state=self._slot_state[index],
            current_frame=m.current_frame, last_confirmed=m.last_confirmed,
            ticks=0, rollbacks=0, rollback_frames=0, max_rollback_depth=0,
            faults=len(self._fault_log[index]),
            next_spectator_frame=m.next_spec_frame,
            spectators=[],
            endpoints=[
                dict(addr=ep.addr, state=0 if ep.running else 1, ping=0,
                     send_queue_len=0, last_acked_frame=NULL_FRAME,
                     last_recv_frame=NULL_FRAME, local_frames_behind=0,
                     remote_frames_behind=0, frame_advantage=0,
                     packets_sent=0, bytes_sent=0, stats_start=0,
                     core={k: 0 for k in _native.EP_STAT_FIELDS})
                for ep in m.endpoints
            ],
        )

    _EP_STATE_CODE = {
        "running": 0, "disconnected": 1, "shutdown": 2, "synchronizing": 3,
    }

    def _session_stats(self, index: int, session: Any) -> Dict[str, Any]:
        """The same record shape as ``_parse_bank_stats``, read from a live
        ``P2PSession`` (the fallback path and evicted slots)."""
        endpoints: List[Dict[str, Any]] = []
        for ep in session._remote_endpoints:
            core_obj = ep._core
            last_acked = getattr(core_obj, "last_acked_frame", None)
            endpoints.append(dict(
                addr=ep.peer_addr,
                state=self._EP_STATE_CODE.get(ep._state, 1),
                ping=ep._round_trip_time,
                send_queue_len=core_obj.pending_len(),
                last_acked_frame=(
                    last_acked() if last_acked is not None else NULL_FRAME
                ),
                last_recv_frame=ep.last_recv_frame(),
                local_frames_behind=ep.local_frame_advantage,
                remote_frames_behind=ep.remote_frame_advantage,
                frame_advantage=ep.average_frame_advantage(),
                packets_sent=ep._packets_sent,
                bytes_sent=ep._bytes_sent,
                stats_start=ep._stats_start_time,
                core={k: 0 for k in _native.EP_STAT_FIELDS},
            ))
        return dict(
            index=index, state=self._slot_state[index],
            current_frame=session.current_frame,
            last_confirmed=session._sync_layer.last_confirmed_frame,
            ticks=getattr(session, "_stat_ticks", 0),
            rollbacks=getattr(session, "_stat_rollbacks", 0),
            rollback_frames=getattr(session, "_stat_rollback_frames", 0),
            max_rollback_depth=getattr(session, "_stat_max_rollback", 0),
            faults=len(self._fault_log[index]),
            endpoints=endpoints,
            next_spectator_frame=getattr(
                session, "_next_spectator_frame", 0
            ),
            spectators=[
                dict(addr=addr, state=0 if sp.is_running() else 1,
                     last_acked_frame=getattr(
                         sp._core, "last_acked_frame", lambda: NULL_FRAME
                     )(),
                     pending_len=sp._core.pending_len(),
                     ping=getattr(sp, "_round_trip_time", 0),
                     packets_sent=getattr(sp, "_packets_sent", 0),
                     bytes_sent=getattr(sp, "_bytes_sent", 0),
                     stats_start=getattr(sp, "_stats_start_time", 0))
                for addr, sp in getattr(
                    session._player_reg, "spectators", {}
                ).items()
                if hasattr(sp, "_core")  # journal taps have no wire state
            ],
        )

    def _gauge_setters(self, index: int, n_eps: int):
        """Prebound ``Gauge.set`` methods for one slot — label resolution
        (dict lookups + str conversions) happens once per pool lifetime,
        not once per scrape (the scrape budget at B=64 is dominated by
        exactly this)."""
        cached = self._setter_cache.get(index)
        if cached is not None and len(cached[1]) == n_eps:
            return cached
        slot = str(index)
        slot_set = (
            self._m_slot_frame.labels(slot=slot).set,
            self._m_slot_occupancy.labels(slot=slot).set,
            self._m_slot_rollbacks.labels(slot=slot).set,
            self._m_slot_rollback_depth.labels(slot=slot).set,
        )
        ep_set = []
        for e in range(n_eps):
            ep = str(e)
            ep_set.append((
                self._m_ep_ping.labels(slot=slot, endpoint=ep).set,
                self._m_ep_queue.labels(slot=slot, endpoint=ep).set,
                self._m_ep_kbps.labels(slot=slot, endpoint=ep).set,
                self._m_ep_behind.labels(
                    slot=slot, endpoint=ep, side="local"
                ).set,
                self._m_ep_behind.labels(
                    slot=slot, endpoint=ep, side="remote"
                ).set,
            ))
        cached = (slot_set, ep_set)
        self._setter_cache[index] = cached
        return cached

    def _refresh_predict_metrics(self) -> None:
        """Fold the Python-tier prediction-accuracy counters (input-queue
        mispredict accounting, DESIGN.md §28) and the device plane's
        adopt/decline tallies into the ``ggrs_predict_*`` family, as
        deltas against the previous scrape.  Rides the existing scrape
        cadence: zero extra ctypes crossings, zero extra RPC traffic."""
        mis = plane_mis = depth = 0
        seen_ids = set()
        for session in list(self._sessions) + list(self._evicted.values()):
            if id(session) in seen_ids:
                continue
            seen_ids.add(id(session))
            sl = getattr(session, "_sync_layer", None)
            if sl is None:
                continue
            for q in sl.input_queues:
                mis += q.mispredicts
                plane_mis += q.plane_mispredicts
                depth += q.mispredict_depth_frames
        hits = fallbacks = 0
        if self._prediction_plane is not None:
            st = self._prediction_plane.stats()
            hits = st.get("hits", 0)
            fallbacks = st.get("fallbacks", 0)
        prev = self._predict_seen
        d_plane = max(0, plane_mis - prev[1])
        d_scalar = max(0, (mis - plane_mis) - (prev[0] - prev[1]))
        d_depth = max(0, depth - prev[2])
        d_hits = max(0, hits - prev[3])
        d_fallbacks = max(0, fallbacks - prev[4])
        if d_plane:
            self._m_mis_plane.inc(d_plane)
        if d_scalar:
            self._m_mis_scalar.inc(d_scalar)
        if d_depth:
            self._m_mis_depth.inc(d_depth)
        if d_hits:
            self._m_pred_adopt.inc(d_hits)
        if d_fallbacks:
            self._m_pred_fallback.inc(d_fallbacks)
        self._predict_seen = [mis, plane_mis, depth, hits, fallbacks]

    def _update_scrape_gauges(self, stats: List[Dict[str, Any]]) -> None:
        if not self._obs_on:
            return
        self._refresh_predict_metrics()
        now = self._now_ms()
        for s in stats:
            slot_set, ep_set = self._gauge_setters(
                s["index"], len(s["endpoints"])
            )
            current = s["current_frame"]
            confirmed = s["last_confirmed"]
            slot_set[0](current)
            slot_set[1](
                current - confirmed if confirmed != NULL_FRAME else current
            )
            slot_set[2](s["rollbacks"])
            slot_set[3](s["max_rollback_depth"])
            for es, (set_ping, set_queue, set_kbps, set_local,
                     set_remote) in zip(s["endpoints"], ep_set):
                set_ping(es["ping"])
                set_queue(es["send_queue_len"])
                set_kbps(self._kbps(es, now))
                set_local(es["local_frames_behind"])
                set_remote(es["remote_frames_behind"])
            specs = s.get("spectators")
            if specs:
                # broadcast gauges: how far each viewer's ack trails the
                # broadcast tip (the stream stall detector).  Setters are
                # prebound per (slot, spectator) — zero label resolution
                # or str() allocation on the steady-state scrape.
                tip = s.get("next_spectator_frame", 0) - 1
                idx = s["index"]
                spec_set = self._spec_setter_cache.get(idx)
                if spec_set is None or len(spec_set) < len(specs):
                    slot = str(idx)
                    spec_set = [
                        self._m_spec_lag.labels(
                            slot=slot, spectator=str(e)
                        ).set
                        for e in range(len(specs))
                    ]
                    self._spec_setter_cache[idx] = spec_set
                for e, ss in enumerate(specs):
                    lag = (
                        max(0, tip - ss["last_acked_frame"])
                        if ss["state"] == 0 else 0
                    )
                    spec_set[e](lag)

    def _now_ms(self) -> int:
        clock = self._clock
        if clock is None:
            if not self._builders:
                return 0
            clock = self._builders[0][0]._clock
        return clock()

    def _kbps(self, es: Dict[str, Any], now: Optional[int] = None) -> int:
        """``PeerProtocol.network_stats``'s bandwidth estimate over one
        harvested endpoint record (0 before a second has elapsed)."""
        if now is None:
            now = self._now_ms()
        seconds = (now - es["stats_start"]) // 1000
        if seconds <= 0:
            return 0
        total = es["bytes_sent"] + es["packets_sent"] * UDP_HEADER_SIZE
        return (total // seconds) // 1024

    def network_stats(self, index: int, handle: int) -> NetworkStats:
        """``P2PSession.network_stats`` parity for pooled slots: the same
        ``NetworkStats`` dataclass, for NATIVE, QUARANTINED and EVICTED
        slots alike.  Native/quarantined slots read the one-crossing stat
        harvest (cached per tick); evicted slots delegate to their live
        Python session; a DEAD slot that never evicted raises
        ``StatsUnavailable`` (there is nothing live to measure).  Raises
        ``BadPlayerHandle`` for local/unknown handles and
        ``StatsUnavailable`` before any time has elapsed or when the
        endpoint is not running — exactly the per-session contract."""
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            return self._sessions[index].network_stats(handle)
        if index in self._evicted:
            return self._evicted[index].network_stats(handle)
        if self._slot_state[index] in (SLOT_DEAD, SLOT_MIGRATED):
            raise StatsUnavailable()
        m = self._mirrors[index]
        ep_idx = next(
            (e for e, ep in enumerate(m.endpoints) if handle in ep.handles),
            None,
        )
        if ep_idx is None:
            raise BadPlayerHandle()
        es = self._bank_stats()[index]["endpoints"][ep_idx]
        if es["state"] != 0:
            raise StatsUnavailable()
        if (self._clock() - es["stats_start"]) // 1000 == 0:
            raise StatsUnavailable()
        stats = NetworkStats(
            ping=es["ping"],
            send_queue_len=es["send_queue_len"],
            kbps_sent=self._kbps(es),
            local_frames_behind=es["local_frames_behind"],
            remote_frames_behind=es["remote_frames_behind"],
        )
        sock_stats = getattr(m.socket, "stats", None)
        if sock_stats is not None:
            stats.send_errors = sock_stats.send_errors
        return stats

    # ------------------------------------------------------------------
    # policy helpers (the Python halves of the split)
    # ------------------------------------------------------------------

    def _on_protocol_disconnected(self, m: _SessionMirror, ep_idx: int) -> None:
        """EvDisconnected from an endpoint: mirror
        ``P2PSession._handle_event`` — mark the endpoint's players
        disconnected (via next tick's ctrl op) and surface the user event."""
        ep = m.endpoints[ep_idx]
        for handle in ep.handles:
            m.pending_ctrl.append((1, ep_idx, m.local_last[handle]))
            m.local_disc[handle] = True  # mirror eagerly for the policy reads
        ep.running = False
        m.push_event((_LZ_DISCONNECTED, ep.addr))

    def _run_consensus(self, m: _SessionMirror) -> None:
        """``P2PSession._update_player_disconnects`` over the mirrors; the
        resulting disconnects become next tick's ctrl ops."""
        n = m.num_players
        queue_connected = [True] * n
        queue_min = [2**31 - 1] * n
        for ep in m.endpoints:
            if not ep.running:
                continue
            for h in range(n):
                if ep.peer_disc[h]:
                    queue_connected[h] = False
                if ep.peer_last[h] < queue_min[h]:
                    queue_min[h] = ep.peer_last[h]
        handle_to_ep = {
            h: i for i, ep in enumerate(m.endpoints) for h in ep.handles
        }
        for h in range(n):
            local_connected = not m.local_disc[h]
            local_min = m.local_last[h]
            min_confirmed = queue_min[h]
            if local_connected:
                min_confirmed = min(min_confirmed, local_min)
            if not queue_connected[h] and (
                local_connected or local_min > min_confirmed
            ):
                ep_idx = handle_to_ep.get(h)
                if ep_idx is not None:
                    m.pending_ctrl.append((1, ep_idx, min_confirmed))
                    for eh in m.endpoints[ep_idx].handles:
                        m.local_disc[eh] = True
                    m.endpoints[ep_idx].running = False

    def _store_checksum(self, ep: _EndpointMirror, frame: Frame,
                        checksum: int) -> None:
        """``PeerProtocol._on_checksum_report`` with interval 1 (desync
        detection is off for bank-eligible sessions)."""
        if len(ep.pending_checksums) >= MAX_CHECKSUM_HISTORY_SIZE:
            oldest = frame - (MAX_CHECKSUM_HISTORY_SIZE - 1)
            ep.pending_checksums = {
                f: c for f, c in ep.pending_checksums.items() if f >= oldest
            }
        ep.pending_checksums[frame] = checksum

    # ------------------------------------------------------------------
    # observables (API parity with P2PSession where the pool drivers and
    # tests read it)
    # ------------------------------------------------------------------

    def events(self, index: int) -> List:
        if not self.native_active:  # property finalizes lazily
            return self._sessions[index].events()
        if index in self._evicted:  # evicted (or dead after eviction)
            return self._evicted[index].events()
        m = self._mirrors[index]
        # lazy decode (DESIGN.md §19): the queue holds tag tuples; the
        # public GgrsEvent objects are constructed only here, on drain
        out = _materialize_events(m.event_queue)
        m.event_queue.clear()
        return out

    def current_frame(self, index: int) -> Frame:
        if not self.native_active:
            return self._sessions[index].current_frame
        if index in self._evicted:
            return self._evicted[index].current_frame
        return self._mirrors[index].current_frame

    def last_confirmed_frame(self, index: int) -> Frame:
        if not self.native_active:
            return self._sessions[index]._sync_layer.last_confirmed_frame
        if index in self._evicted:
            return self._evicted[index]._sync_layer.last_confirmed_frame
        return self._mirrors[index].last_confirmed

    def frames_ahead(self, index: int) -> int:
        if not self.native_active:
            return self._sessions[index].frames_ahead()
        if index in self._evicted:
            return self._evicted[index].frames_ahead()
        return self._mirrors[index].frames_ahead

    def session(self, index: int):
        """The underlying P2PSession: always present on the fallback path,
        and present for EVICTED slots on the native path (the bank itself
        has no per-session objects)."""
        if not self.native_active:
            return self._sessions[index]
        if index in self._evicted:
            return self._evicted[index]
        raise InvalidRequest(
            "native bank active: this slot has no per-session object"
        )

    def _check_valid(self) -> None:
        if self._invalid is not None:
            raise RuntimeError(
                f"pool was invalidated by an earlier failed tick "
                f"({self._invalid}); rebuild it"
            )

    def __del__(self) -> None:  # pragma: no cover
        try:
            if self._decode_pool is not None:
                self._decode_pool.close()
                self._decode_pool = None
            if self._bank and self._lib is not None:
                self._lib.ggrs_bank_free(self._bank)
                self._bank = None
            if self._lib is not None:
                for i, handle in enumerate(self._net_handles):
                    if handle:
                        self._net_handles[i] = None
                        self._lib.ggrs_net_free(handle)
        except Exception:
            pass


def adopt_resume_bundle(builder, socket, bundle: Dict[str, Any], *,
                        journal=None, saved_states=None):
    """Resume a migrated/failed-over match on THIS side of the wire: build
    a fresh ``P2PSession`` from an ``export_resume_state`` bundle (or the
    journal-synthesized equivalent the fleet failover path builds) — the
    destination half of live match migration (DESIGN.md §16).

    ``builder`` must describe the SAME match topology as the source slot
    (player count, config, remote/spectator addresses); the adopted wire
    identities (endpoint magics, send/recv windows, connect mirrors) make
    the peers and viewers see a retransmission hiccup, never a new
    endpoint.  ``journal``, when given, is tapped so the resumed session
    keeps journaling its confirmed stream (``JournalTap``).

    Returns ``(session, load_request)``: the caller must lead the
    session's next request list with ``load_request`` so the game restores
    the state saved at the resume frame (the bundle carries that state;
    its cell is pre-filled).

    ``saved_states``: a pre-built ``SavedStates`` ring for callers that
    rebuild the resume state some other way (crash failover loads a
    journal checkpoint and fast-forwards through a request prelude); when
    given, the bundle's ``state_blob`` is ignored and the caller owns
    filling the resume cell."""
    h = bundle["harvest"]
    resume = bundle["resume_frame"]
    if saved_states is None:
        saved = SavedStates(bundle["max_prediction"])
        data, checksum = pickle.loads(bundle["state_blob"])
        saved.get_cell(resume).save(resume, data, checksum)
    else:
        saved = saved_states
    cell = saved.get_cell(resume)
    session = builder.start_p2p_session(socket)
    endpoint_states: Dict[Any, Dict[str, Any]] = {}
    for e, em in enumerate(bundle["endpoints"]):
        he = h["endpoints"][e]
        endpoint_states[em["addr"]] = dict(
            magic=em["magic"],
            running=he["state"] == 0,
            peer_connect_status=list(zip(em["peer_disc"], em["peer_last"])),
            last_recv_frame=he["last_recv"],
            recv_entries=he["recv_entries"],
            last_acked_frame=he["last_acked_frame"],
            send_base=he["send_base"],
            pending=he["pending"],
            pending_checksums=em.get("pending_checksums") or {},
        )
    session.adopt_resume_state(
        frame=resume,
        last_confirmed=resume,
        saved_states=saved,
        connect_status=list(zip(h["local_disc"], h["local_last"])),
        player_inputs=h["player_inputs"],
        endpoint_states=endpoint_states,
        next_recommended_sleep=bundle.get("next_recommended_sleep", 0),
        pending_events=list(bundle.get("pending_events", ())),
        next_spectator_frame=h.get("next_spectator_frame", 0),
    )
    if bundle.get("spectators"):
        from ..broadcast.hub import graft_spectator_endpoints

        spec_states = h.get("spectators") or []
        graft_spectator_endpoints(session, builder, [
            dict(sp, state=spec_states[e] if e < len(spec_states) else None)
            for e, sp in enumerate(bundle["spectators"])
        ])
    if journal is not None:
        from ..broadcast.journal import JournalTap

        session.adopt_spectator_endpoint(
            JournalTap.ADDR, JournalTap(journal, builder._config)
        )
    decode = builder._config.input_decode
    for handle, blob in (bundle.get("staged_inputs") or {}).items():
        session.add_local_input(int(handle), decode(blob))
    # bundle-adopted sessions are pool/fleet-owned by definition: their
    # request lists are consumed tick-synchronously, so they take the
    # pooled-request path too (DESIGN.md §19)
    session.enable_request_pooling()
    return session, LoadGameState(cell=cell, frame=resume)
