"""Host session pool: step B P2P sessions' per-tick protocol + sync
mechanism in ONE ctypes crossing per pool tick.

The round-5 capacity knee was ~90% host bookkeeping, and the per-operation
native cores measured perf-neutral because ~200 ctypes crossings per
session-tick hand back what the C++ saves (docs/ROUND5.md §4).  This module
is the located fix: ``HostSessionPool`` drives every pooled session's tick —
input enqueue, prediction/confirmation watermarks, endpoint timers, ack
trim, outbound InputMessage assembly — through ``native/session_bank.cpp``
off a single packed command buffer per tick.

POLICY STAYS HERE, in Python: GgrsEvent emission, the disconnect consensus
(:meth:`P2PSession._update_player_disconnects` semantics, applied as next
tick's control ops), wait-recommendation pacing, and the construction of the
``GgrsRequest`` lists the game fulfills.  The request grammar and the public
per-session observables (``current_frame``, ``last_confirmed_frame``,
``events``, landed frames) are unchanged from ``sessions/p2p.py``.

FALLBACK: when the native library is unavailable (``GGRS_TPU_NO_NATIVE``,
no toolchain) or any session's shape is outside the bank's mechanism
(sparse saving, lockstep, spectators, desync detection, handshake,
variable-size inputs), the pool transparently drives ordinary per-session
``P2PSession`` objects — the untouched semantic reference.  Parity between
the two paths is pinned by tests/test_session_bank.py: bit-identical wire
bytes, frames, and events under seeded loss/dup/reorder traffic.

Known one-tick-late behaviors on the native path (documented divergence,
exercised only in disconnect scenarios; the fallback is exact): reactions
to ``Disconnected`` protocol events and disconnect-consensus adjustments
are computed from this tick's mirrors and applied as next tick's control
ops.
"""

from __future__ import annotations

import ctypes
import os
import random
import struct
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core.errors import InvalidRequest
from ..core.sync_layer import SavedStates
from ..core.types import (
    AdvanceFrame,
    Disconnected,
    Frame,
    GgrsRequest,
    InputStatus,
    LoadGameState,
    NetworkInterrupted,
    NetworkResumed,
    NULL_FRAME,
    SaveGameState,
    WaitRecommendation,
)
from ..net import _native
from ..net.messages import RawMessage
from ..net.protocol import MAX_CHECKSUM_HISTORY_SIZE
from ..sessions.p2p import (
    MAX_EVENT_QUEUE_SIZE,
    MIN_RECOMMENDATION,
    RECOMMENDATION_INTERVAL,
)

_STATUS = (
    InputStatus.CONFIRMED,
    InputStatus.PREDICTED,
    InputStatus.DISCONNECTED,
)

# bank event kinds (session_bank.cpp EvKind)
_EV_INTERRUPTED = 1
_EV_RESUMED = 2
_EV_DISCONNECTED = 3
_EV_CHECKSUM = 4

# receive staging caps shared with NativeEndpointCore: a session whose
# worst-case input packet could overflow them must stay on the fallback
# (the bank drops cap-exceeding packets instead of re-decoding in Python)
_RECV_CAP_BYTES = 1 << 16
_RECV_CAP_FRAMES = 512
_WORST_CASE_FRAMES = 192  # 128-deep pending window with generous slack


def _uvarint_len(v: int) -> int:
    n = 1
    while v >= 0x80:
        v >>= 7
        n += 1
    return n


def _bank_eligible(builder) -> bool:
    """Can this builder's session run on the native bank mechanism?  The
    checks mirror the bank's scope; anything outside it uses the Python
    sessions (identical semantics, per-session cost)."""
    cfg = builder._config
    from ..core.sync_layer import _native_sync_semantics_ok
    from ..core.types import Spectator

    if not _native_sync_semantics_ok(cfg):
        return False
    if builder._sparse_saving or builder._max_prediction < 1:
        return False  # sparse saving / lockstep: fallback policy paths
    if builder._desync_detection.enabled or builder._sync_handshake:
        return False
    if builder._local_players < 1 or builder._num_players > 64:
        return False
    if any(
        isinstance(t, Spectator) for t in builder._player_reg.handles.values()
    ):
        return False
    # worst-case packet must fit the native staging caps
    size = cfg.native_input_size
    per_frame = builder._num_players * (size + _uvarint_len(size))
    if _WORST_CASE_FRAMES * per_frame > _RECV_CAP_BYTES:
        return False
    if _WORST_CASE_FRAMES > _RECV_CAP_FRAMES:
        return False
    return True


class _EndpointMirror:
    """Python-side view of one bank endpoint: identity plus the state the
    consensus / event policy reads."""

    __slots__ = (
        "addr", "handles", "magic", "running",
        "peer_disc", "peer_last", "pending_checksums",
    )

    def __init__(self, addr, handles: List[int], magic: int, players: int):
        self.addr = addr
        self.handles = handles
        self.magic = magic
        self.running = True
        self.peer_disc = [False] * players
        self.peer_last = [NULL_FRAME] * players
        self.pending_checksums: Dict[Frame, int] = {}


class _SessionMirror:
    """Python-side policy state for one bank session."""

    __slots__ = (
        "config", "socket", "num_players", "max_prediction", "input_size",
        "local_handles", "local_handle_set", "endpoints", "addr_to_ep",
        "saved_states", "current_frame", "last_confirmed", "frames_ahead",
        "local_disc", "local_last", "event_queue", "next_recommended_sleep",
        "staged_inputs", "pending_ctrl",
    )

    def __init__(self, config, socket, num_players, max_prediction,
                 local_handles):
        self.config = config
        self.socket = socket
        self.num_players = num_players
        self.max_prediction = max_prediction
        self.input_size = config.native_input_size
        self.local_handles = local_handles
        self.local_handle_set = set(local_handles)
        self.endpoints: List[_EndpointMirror] = []
        self.addr_to_ep: Dict[Any, int] = {}
        self.saved_states = SavedStates(max_prediction)
        self.current_frame: Frame = 0
        self.last_confirmed: Frame = NULL_FRAME
        self.frames_ahead = 0
        self.local_disc = [False] * num_players
        self.local_last = [NULL_FRAME] * num_players
        self.event_queue: deque = deque()
        self.next_recommended_sleep: Frame = 0
        self.staged_inputs: Dict[int, bytes] = {}
        self.pending_ctrl: List[Tuple[int, int, Frame]] = []

    def push_event(self, event) -> None:
        self.event_queue.append(event)
        while len(self.event_queue) > MAX_EVENT_QUEUE_SIZE:
            self.event_queue.popleft()


class HostSessionPool:
    """B pooled host sessions, one mechanism crossing per tick.

    Usage (single-threaded, like every session object)::

        pool = HostSessionPool()
        for builder, socket in matches:
            pool.add_session(builder, socket)
        ...
        pool.add_local_input(i, handle, value)     # per session, per tick
        request_lists = pool.advance_all()          # ONE native crossing
        events = pool.events(i)

    ``request_lists[i]`` follows the exact ``GgrsRequest`` grammar of
    ``P2PSession.advance_frame``; feed it to any executor, including
    ``parallel.BatchedRequestExecutor`` (see ``parallel.HostedPool``).

    On the native path all sessions' timers run off ONE clock read per tick
    (builder 0's clock): pooled sessions must share a timebase.  Builders
    whose clocks read visibly apart at finalize fall back to per-session
    Python sessions, where each honors its own clock.
    """

    def __init__(self) -> None:
        self._builders: List[Tuple[Any, Any]] = []
        self._finalized = False
        self._native_active = False
        self._bank = None
        self._lib = None
        self._mirrors: List[_SessionMirror] = []
        self._sessions: List[Any] = []  # fallback P2PSessions
        self._clock = None
        self._out_buf: Optional[ctypes.Array] = None
        self._out_len = ctypes.c_size_t(0)
        self._invalid: Optional[str] = None
        self.crossings = 0  # ggrs_bank_tick invocations (the count test)

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    def add_session(self, builder, socket) -> int:
        """Register one session described by a fully-populated
        ``SessionBuilder`` plus its socket.  Returns the session index."""
        if self._finalized:
            raise InvalidRequest("pool already finalized; add sessions first")
        self._builders.append((builder, socket))
        return len(self._builders) - 1

    def _finalize(self) -> None:
        self._finalized = True
        lib = None if os.environ.get("GGRS_TPU_NO_NATIVE") else (
            _native.bank_lib()
        )
        # The bank runs every session's timers off ONE clock read per tick
        # (builder 0's clock) — that is the pool's contract.  Builders whose
        # clocks are visibly on a different timebase (a frozen test clock
        # pooled with a real one reads hours apart) stay on the per-session
        # fallback, where each session honors its own clock.  Distinct
        # callables over the same timebase (per-builder lambdas reading one
        # counter) read within the tolerance and pool fine.
        def same_timebase() -> bool:
            if not self._builders:
                return False
            first = self._builders[0][0]._clock
            t0 = first()
            for b, _ in self._builders:
                if b._clock is first:
                    continue
                if abs(b._clock() - t0) > 100:
                    return False
            return True

        eligible = lib is not None and same_timebase() and all(
            _bank_eligible(b) and hasattr(s, "receive_all_datagrams")
            for b, s in self._builders
        )
        if not eligible:
            for builder, socket in self._builders:
                self._sessions.append(builder.start_p2p_session(socket))
            return

        self._lib = lib
        self._bank = lib.ggrs_bank_new()
        if not self._bank:
            raise MemoryError("ggrs_bank_new failed")
        self._native_active = True
        from ..core.types import Remote

        for builder, socket in self._builders:
            cfg = builder._config
            # builder-level validation parity (start_p2p_session's checks)
            for handle in range(builder._num_players):
                if handle not in builder._player_reg.handles:
                    raise InvalidRequest(
                        "Not enough players have been added. Keep registering "
                        "players up to the defined player number."
                    )
            local_handles = sorted(
                h for h, t in builder._player_reg.handles.items()
                if not isinstance(t, Remote)
            )
            arr = (ctypes.c_int32 * max(1, len(local_handles)))(*local_handles)
            idx = lib.ggrs_bank_add_session(
                self._bank, builder._num_players, cfg.native_input_size,
                builder._max_prediction, builder._fps,
                builder._disconnect_timeout_ms,
                builder._disconnect_notify_start_ms,
                arr, len(local_handles), builder._input_delay,
            )
            if idx < 0:
                raise RuntimeError(f"ggrs_bank_add_session failed: {idx}")
            mirror = _SessionMirror(
                cfg, socket, builder._num_players, builder._max_prediction,
                local_handles,
            )
            # endpoints: same address grouping, iteration order, and magic
            # draws as start_p2p_session -> PeerProtocol.__init__, so the
            # wire bytes (magic included) match the fallback bit-for-bit
            remote_by_addr: Dict[Any, List[int]] = {}
            for handle, ptype in builder._player_reg.handles.items():
                if isinstance(ptype, Remote):
                    remote_by_addr.setdefault(ptype.addr, []).append(handle)
            now = builder._clock()
            for addr, handles in remote_by_addr.items():
                rng = builder._rng if builder._rng is not None else (
                    random.Random()
                )
                magic = 0
                while magic == 0:
                    magic = rng.randrange(0, 1 << 16)
                handles = sorted(handles)
                harr = (ctypes.c_int32 * len(handles))(*handles)
                ep_idx = lib.ggrs_bank_add_endpoint(
                    self._bank, idx, magic, harr, len(handles), now
                )
                if ep_idx < 0:
                    raise RuntimeError(
                        f"ggrs_bank_add_endpoint failed: {ep_idx}"
                    )
                mirror.addr_to_ep[addr] = int(ep_idx)
                mirror.endpoints.append(
                    _EndpointMirror(addr, handles, magic,
                                    builder._num_players)
                )
            self._mirrors.append(mirror)
        self._clock = self._builders[0][0]._clock
        # output buffer sized to the worst realistic tick (rollback resim
        # descriptors + a full outbound volley per endpoint), grown never:
        # a too-small buffer poisons the pool loudly instead
        per_session = 0
        for m in self._mirrors:
            adv_bytes = m.num_players * (1 + m.input_size)
            per_session = max(
                per_session,
                4096
                + (m.max_prediction + 4) * (16 + adv_bytes)
                + len(m.endpoints) * (2048 + 32 * m.num_players),
            )
        self._out_buf = ctypes.create_string_buffer(
            max(1 << 16, per_session * len(self._mirrors))
        )

    # ------------------------------------------------------------------
    # per-tick API
    # ------------------------------------------------------------------

    @property
    def native_active(self) -> bool:
        if not self._finalized:
            self._finalize()
        return self._native_active

    def __len__(self) -> int:
        return len(self._builders)

    def add_local_input(self, index: int, handle: int, value) -> None:
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            self._sessions[index].add_local_input(handle, value)
            return
        m = self._mirrors[index]
        if handle not in m.local_handle_set:
            raise InvalidRequest(
                "The player handle you provided is not referring to a local "
                "player."
            )
        m.staged_inputs[handle] = m.config.input_encode(value)

    def advance_all(self) -> List[List[GgrsRequest]]:
        """Run every session's tick (poll + advance); returns the B request
        lists in session order.  Native path: exactly one ctypes crossing."""
        if not self._finalized:
            self._finalize()
        if not self._native_active:
            return [s.advance_frame() for s in self._sessions]
        self._check_valid()

        pack = struct.pack
        # validate EVERY session's staged inputs before any destructive step
        # (ctrl-op swap, socket drain): raising mid-build would silently lose
        # pending disconnect ops and drained datagrams on a caller retry
        for m in self._mirrors:
            for handle in m.local_handles:
                if handle not in m.staged_inputs:
                    raise InvalidRequest(
                        f"Missing local input for handle {handle} while "
                        "calling advance_frame()."
                    )
        cmd_parts: List[bytes] = []
        for m in self._mirrors:
            cmd_parts.append(b"\x01")
            cmd_parts.extend(m.staged_inputs[h] for h in m.local_handles)
            ctrl = m.pending_ctrl
            m.pending_ctrl = []
            cmd_parts.append(pack("<H", len(ctrl)))
            for op, ep_idx, frame in ctrl:
                cmd_parts.append(pack("<BHq", op, ep_idx, frame))
            datagrams = []
            for from_addr, data in m.socket.receive_all_datagrams():
                ep_idx = m.addr_to_ep.get(from_addr)
                if ep_idx is not None:
                    datagrams.append((ep_idx, data))
            cmd_parts.append(pack("<H", len(datagrams)))
            for ep_idx, data in datagrams:
                cmd_parts.append(pack("<HI", ep_idx, len(data)))
                cmd_parts.append(data)
        cmd = b"".join(cmd_parts)

        self.crossings += 1
        rc = self._lib.ggrs_bank_tick(
            self._bank, self._clock(), cmd, len(cmd),
            self._out_buf, len(self._out_buf), ctypes.byref(self._out_len),
        )
        if rc == _native.BANK_ERR_BUFFER_TOO_SMALL:
            # kErrBufferTooSmall: the tick RAN and its output is
            # retained natively — grow and fetch (the one case that costs a
            # second crossing, e.g. a stalled peer's whole-window volley)
            self._out_buf = ctypes.create_string_buffer(
                max(self._out_len.value, 2 * len(self._out_buf))
            )
            rc = self._lib.ggrs_bank_fetch_out(
                self._bank, self._out_buf, len(self._out_buf),
                ctypes.byref(self._out_len),
            )
        if rc != 0:
            self._invalid = f"ggrs_bank_tick failed: {rc}"
            if rc in (_native.BANK_ERR_SYNC, _native.BANK_ERR_CONFIRM,
                      _native.BANK_ERR_SEQUENCE, _native.BANK_ERR_SYNC_INPUTS,
                      _native.BANK_ERR_LANDED_SPLIT):
                # the Python path fails these as AssertionErrors; match it
                raise AssertionError(self._invalid)
            raise RuntimeError(self._invalid)
        return self._parse_output()

    def _parse_output(self) -> List[List[GgrsRequest]]:
        buf = memoryview(self._out_buf).cast("B")[: self._out_len.value]
        unpack_from = struct.unpack_from
        pos = 0
        request_lists: List[List[GgrsRequest]] = []
        for m in self._mirrors:
            players, isize = m.num_players, m.input_size
            landed, frames_ahead, current, confirmed, consensus, n_ops = (
                unpack_from("<qiqqBH", buf, pos)
            )
            pos += 31
            requests: List[GgrsRequest] = []
            advanced = False
            decode = m.config.input_decode
            for _ in range(n_ops):
                kind = buf[pos]
                pos += 1
                if kind == 2:
                    statuses = bytes(buf[pos : pos + players])
                    pos += players
                    blob = bytes(buf[pos : pos + players * isize])
                    pos += players * isize
                    requests.append(AdvanceFrame(inputs=[
                        (decode(blob[p * isize : (p + 1) * isize]),
                         _STATUS[statuses[p]])
                        for p in range(players)
                    ]))
                    advanced = True
                else:
                    (frame,) = unpack_from("<q", buf, pos)
                    pos += 8
                    cell = m.saved_states.get_cell(frame)
                    if kind == 0:
                        requests.append(SaveGameState(cell=cell, frame=frame))
                        advanced = False
                    else:
                        assert cell.frame == frame, (
                            f"rollback loads frame {frame} but its cell "
                            f"holds {cell.frame} — was the save fulfilled?"
                        )
                        requests.append(LoadGameState(cell=cell, frame=frame))
                        advanced = False
            (n_out,) = unpack_from("<H", buf, pos)
            pos += 2
            socket = m.socket
            for _ in range(n_out):
                ep_idx, dlen = unpack_from("<HI", buf, pos)
                pos += 6
                data = bytes(buf[pos : pos + dlen])
                pos += dlen
                socket.send_to(RawMessage(data), m.endpoints[ep_idx].addr)
            # stage event records; dispatch AFTER the status mirrors below
            # are parsed — _on_protocol_disconnected reads m.local_last, and
            # p2p.py's _handle_event sees the status as updated by this
            # tick's EvInputs, not last tick's
            (n_events,) = unpack_from("<H", buf, pos)
            pos += 2
            staged_events = []
            for _ in range(n_events):
                kind, ep_idx = unpack_from("<BH", buf, pos)
                pos += 3
                if kind == _EV_INTERRUPTED:
                    (remaining,) = unpack_from("<q", buf, pos)
                    pos += 8
                    staged_events.append((kind, ep_idx, remaining))
                elif kind == _EV_CHECKSUM:
                    frame, lo, hi = unpack_from("<qQQ", buf, pos)
                    pos += 24
                    staged_events.append((kind, ep_idx, (frame, lo, hi)))
                else:
                    staged_events.append((kind, ep_idx, None))
            (n_eps,) = unpack_from("<B", buf, pos)
            pos += 1
            for e in range(n_eps):
                ep = m.endpoints[e]
                ep.running = buf[pos] == 0
                pos += 1
                for h in range(players):
                    disc, lf = unpack_from("<Bq", buf, pos)
                    pos += 9
                    ep.peer_disc[h] = bool(disc)
                    ep.peer_last[h] = lf
            for h in range(players):
                disc, lf = unpack_from("<Bq", buf, pos)
                pos += 9
                m.local_disc[h] = bool(disc)
                m.local_last[h] = lf

            # ---- policy (Python): events, wait recommendation, consensus ----
            for kind, ep_idx, payload in staged_events:
                ep = m.endpoints[ep_idx]
                if kind == _EV_INTERRUPTED:
                    m.push_event(NetworkInterrupted(
                        addr=ep.addr, disconnect_timeout=payload
                    ))
                elif kind == _EV_RESUMED:
                    m.push_event(NetworkResumed(addr=ep.addr))
                elif kind == _EV_DISCONNECTED:
                    self._on_protocol_disconnected(m, ep_idx)
                elif kind == _EV_CHECKSUM:
                    frame, lo, hi = payload
                    self._store_checksum(ep, frame, lo | (hi << 64))
            pre_current = current - (1 if advanced else 0)
            m.frames_ahead = frames_ahead
            if (
                pre_current > m.next_recommended_sleep
                and frames_ahead >= MIN_RECOMMENDATION
            ):
                m.next_recommended_sleep = pre_current + RECOMMENDATION_INTERVAL
                m.push_event(WaitRecommendation(skip_frames=frames_ahead))
            m.current_frame = current
            m.last_confirmed = confirmed
            if advanced:
                m.staged_inputs.clear()
            if consensus:
                self._run_consensus(m)
            request_lists.append(requests)
        return request_lists

    # ------------------------------------------------------------------
    # policy helpers (the Python halves of the split)
    # ------------------------------------------------------------------

    def _on_protocol_disconnected(self, m: _SessionMirror, ep_idx: int) -> None:
        """EvDisconnected from an endpoint: mirror
        ``P2PSession._handle_event`` — mark the endpoint's players
        disconnected (via next tick's ctrl op) and surface the user event."""
        ep = m.endpoints[ep_idx]
        for handle in ep.handles:
            m.pending_ctrl.append((1, ep_idx, m.local_last[handle]))
            m.local_disc[handle] = True  # mirror eagerly for the policy reads
        ep.running = False
        m.push_event(Disconnected(addr=ep.addr))

    def _run_consensus(self, m: _SessionMirror) -> None:
        """``P2PSession._update_player_disconnects`` over the mirrors; the
        resulting disconnects become next tick's ctrl ops."""
        n = m.num_players
        queue_connected = [True] * n
        queue_min = [2**31 - 1] * n
        for ep in m.endpoints:
            if not ep.running:
                continue
            for h in range(n):
                if ep.peer_disc[h]:
                    queue_connected[h] = False
                if ep.peer_last[h] < queue_min[h]:
                    queue_min[h] = ep.peer_last[h]
        handle_to_ep = {
            h: i for i, ep in enumerate(m.endpoints) for h in ep.handles
        }
        for h in range(n):
            local_connected = not m.local_disc[h]
            local_min = m.local_last[h]
            min_confirmed = queue_min[h]
            if local_connected:
                min_confirmed = min(min_confirmed, local_min)
            if not queue_connected[h] and (
                local_connected or local_min > min_confirmed
            ):
                ep_idx = handle_to_ep.get(h)
                if ep_idx is not None:
                    m.pending_ctrl.append((1, ep_idx, min_confirmed))
                    for eh in m.endpoints[ep_idx].handles:
                        m.local_disc[eh] = True
                    m.endpoints[ep_idx].running = False

    def _store_checksum(self, ep: _EndpointMirror, frame: Frame,
                        checksum: int) -> None:
        """``PeerProtocol._on_checksum_report`` with interval 1 (desync
        detection is off for bank-eligible sessions)."""
        if len(ep.pending_checksums) >= MAX_CHECKSUM_HISTORY_SIZE:
            oldest = frame - (MAX_CHECKSUM_HISTORY_SIZE - 1)
            ep.pending_checksums = {
                f: c for f, c in ep.pending_checksums.items() if f >= oldest
            }
        ep.pending_checksums[frame] = checksum

    # ------------------------------------------------------------------
    # observables (API parity with P2PSession where the pool drivers and
    # tests read it)
    # ------------------------------------------------------------------

    def events(self, index: int) -> List:
        if not self.native_active:  # property finalizes lazily
            return self._sessions[index].events()
        m = self._mirrors[index]
        out = list(m.event_queue)
        m.event_queue.clear()
        return out

    def current_frame(self, index: int) -> Frame:
        if not self.native_active:
            return self._sessions[index].current_frame
        return self._mirrors[index].current_frame

    def last_confirmed_frame(self, index: int) -> Frame:
        if not self.native_active:
            return self._sessions[index]._sync_layer.last_confirmed_frame
        return self._mirrors[index].last_confirmed

    def frames_ahead(self, index: int) -> int:
        if not self.native_active:
            return self._sessions[index].frames_ahead()
        return self._mirrors[index].frames_ahead

    def session(self, index: int):
        """The underlying P2PSession (fallback mode only — the native bank
        has no per-session objects)."""
        if self.native_active:
            raise InvalidRequest(
                "native bank active: per-session objects do not exist"
            )
        return self._sessions[index]

    def _check_valid(self) -> None:
        if self._invalid is not None:
            raise RuntimeError(
                f"pool was invalidated by an earlier failed tick "
                f"({self._invalid}); rebuild it"
            )

    def __del__(self) -> None:  # pragma: no cover
        try:
            if self._bank and self._lib is not None:
                self._lib.ggrs_bank_free(self._bank)
                self._bank = None
        except Exception:
            pass
