"""Speculative branch execution: vmap over predicted-input futures.

The reference predicts remote inputs with a single strategy (repeat-last by
default) and pays a full rollback+resimulation whenever the prediction was
wrong (/root/reference/src/input_queue.rs:104-167,
/root/reference/src/sessions/p2p_session.rs:658-714).  On TPU, advancing one
small state is MXU-starved anyway — so instead of one predicted future we
advance **K parallel branches** under K different predicted input sequences
with ``vmap`` (one batched program, same wall-clock as one branch), and when
confirmed inputs arrive we *select* the branch whose predictions matched
(a device-side argmax — no replay at all).  Only when no branch guessed right
do we fall back to the fused scan replay.  This is BASELINE config 3's
speculative parallelism; it has no analog in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

AdvanceFn = Callable[[Any, Any], Any]


@dataclass(frozen=True)
class SpeculativeBranches:
    """Compiled speculative-execution programs for a fixed (advance, K).

    Shapes: branch states carry a leading K axis on every leaf; windowed
    inputs are ``[K, W, ...per-frame-input...]`` (per branch, per frame).
    """

    num_branches: int
    init: Callable[[Any], Any]  # state -> K-branch states
    speculate_window: Callable[[Any, Any], Any]  # (state, inputs_KW) -> (branches, per-branch traj checksums)
    resolve: Callable[[Any, Any, Any], Tuple[Any, jax.Array, jax.Array]]
    replay_window: Callable[[Any, Any], Any]  # (state, inputs_W) -> state
    collapse: Callable[[Any, jax.Array], Any]  # (branches, idx) -> state


def build_speculation_programs(
    advance: AdvanceFn, num_branches: int
) -> SpeculativeBranches:
    """Compile the branch programs.

    ``advance`` is the same pure ``(state, inputs) -> state`` the replay path
    uses; speculation composes with it rather than requiring a special game.
    """
    assert num_branches >= 1
    K = num_branches

    def _init(state: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                jnp.asarray(leaf)[None, ...], (K,) + jnp.asarray(leaf).shape
            ).copy(),
            state,
        )

    def _window_one(state: Any, inputs_w: Any) -> Any:
        def body(st: Any, inp: Any) -> Tuple[Any, None]:
            return advance(st, inp), None

        out, _ = jax.lax.scan(body, state, inputs_w)
        return out

    def _speculate_window(state: Any, inputs_kw: Any) -> Any:
        """Advance K branches from one shared base state through a W-frame
        window; returns the K final states (one vmap'd scan — a single XLA
        program, not K programs)."""
        branches = _init(state)
        return jax.vmap(_window_one)(branches, inputs_kw)

    def _resolve(
        branches: Any, inputs_kw: Any, confirmed_w: Any
    ) -> Tuple[Any, jax.Array, jax.Array]:
        """Select the branch whose input window matches the confirmed inputs.

        Returns ``(state, branch_idx, found)``; when ``found`` is False the
        returned state is branch 0 and the caller must replay from the base
        state with the confirmed inputs instead."""
        def leaf_match(pred: jax.Array, conf: jax.Array) -> jax.Array:
            # pred: [K, W, ...], conf: [W, ...] -> [K] all-equal
            eq = pred == conf[None, ...]
            return jnp.all(eq.reshape(K, -1), axis=1)

        matches_per_leaf = jax.tree_util.tree_map(
            leaf_match, inputs_kw, confirmed_w
        )
        match = jax.tree_util.tree_reduce(
            jnp.logical_and, matches_per_leaf, jnp.ones((K,), bool)
        )
        idx = jnp.argmax(match)  # first matching branch
        found = jnp.any(match)
        return _collapse(branches, idx), idx, found

    def _collapse(branches: Any, idx: jax.Array) -> Any:
        return jax.tree_util.tree_map(
            lambda leaf: jax.lax.dynamic_index_in_dim(
                leaf, idx, axis=0, keepdims=False
            ),
            branches,
        )

    return SpeculativeBranches(
        num_branches=K,
        init=jax.jit(_init),
        speculate_window=jax.jit(_speculate_window),
        resolve=jax.jit(_resolve),
        replay_window=jax.jit(_window_one),
        collapse=jax.jit(_collapse),
    )
