"""P2P example: one local player vs remote peers over UDP, state on device.

The reference's ex_game_p2p (/root/reference/examples/ex_game/ex_game_p2p.rs)
runs one window per process; here one process drives ONE session and you
start the peers separately (or use --both to spawn both sides in-process,
handy for a quick look):

  python examples/ex_game_p2p.py --local-port 7777 --players local 127.0.0.1:8888 &
  python examples/ex_game_p2p.py --local-port 8888 --players 127.0.0.1:7777 local

Honors WaitRecommendation by skipping frames (the reference's slow-down),
prints network stats periodically, reports desync/disconnect events.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def parse_addr(s: str):
    host, _, port = s.rpartition(":")
    return (host or "127.0.0.1", int(port))


def run_session(local_port: int, players, spectators, frames: int, render: bool):
    from ex_game import FPS, FrameClock, Game, box_config
    from ggrs_tpu.core import DesyncDetection, Local, Remote, Spectator
    from ggrs_tpu.core.errors import NotSynchronized, PredictionThreshold
    from ggrs_tpu.net import UdpNonBlockingSocket
    from ggrs_tpu.sessions import SessionBuilder

    builder = (
        SessionBuilder(box_config())
        .with_num_players(len(players))
        .with_desync_detection_mode(DesyncDetection.on(60))
        .with_fps(FPS)
        # handshake before streaming: peers may start seconds apart (jax
        # import + warmup), and without it the disconnect timers cannot tell
        # "not started yet" from "gone" (disconnect timers are paused until
        # the handshake completes)
        .with_sync_handshake(True)
        # share-a-machine CI tolerance for mid-run scheduling hiccups
        .with_disconnect_timeout(5_000)
        .with_disconnect_notify_delay(2_000)
    )
    local_handles = []
    for handle, spec in enumerate(players):
        if spec == "local":
            builder = builder.add_player(Local(), handle)
            local_handles.append(handle)
        else:
            builder = builder.add_player(Remote(parse_addr(spec)), handle)
    for i, spec in enumerate(spectators):
        builder = builder.add_player(Spectator(parse_addr(spec)), len(players) + i)

    # build (and jit-warm) the game BEFORE the session: endpoint disconnect
    # timers start at session creation, and warmup takes seconds
    game = Game(len(players), render=render)
    sess = builder.start_p2p_session(UdpNonBlockingSocket.bind_to_port(local_port))
    clock = FrameClock(FPS)

    frame = 0
    while frame < frames:
        sess.poll_remote_clients()
        for ev in sess.events():
            name = type(ev).__name__
            if name == "WaitRecommendation":
                clock.skip(ev.skip_frames)
            print(f"[:{local_port}] event: {ev}")
        for _ in range(clock.ready_frames()):
            for h in local_handles:
                sess.add_local_input(h, game.bot_input(h, frame))
            try:
                requests = sess.advance_frame()
            except NotSynchronized:
                continue  # handshake still completing
            except PredictionThreshold:
                continue  # waiting on remote inputs
            game.handle_requests(requests)
            game.draw()
            frame += 1
            if frame % 300 == 0:
                for h in sess.remote_player_handles():
                    try:
                        print(f"[:{local_port}] stats p{h}: {sess.network_stats(h)}")
                    except Exception:
                        pass
        time.sleep(0.0005)
    # drain: keep pumping retransmissions/acks briefly so peers and
    # spectators that are still behind receive the tail of our inputs
    # (the reference's protocol lingers on shutdown for the same reason,
    # /root/reference/src/network/protocol.rs:311-319)
    deadline = time.perf_counter() + 1.0
    while time.perf_counter() < deadline:
        sess.poll_remote_clients()
        time.sleep(0.005)
    print(f"[:{local_port}] done: {frame} frames")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--local-port", type=int, default=7777)
    ap.add_argument(
        "--players",
        nargs="+",
        default=["local", "127.0.0.1:8888"],
        help="per-handle: 'local' or host:port of the remote peer",
    )
    ap.add_argument("--spectators", nargs="*", default=[])
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--render", action="store_true")
    ap.add_argument("--both", action="store_true", help="run both peers in-process")
    args = ap.parse_args()

    if args.both:
        import threading

        a = threading.Thread(
            target=run_session,
            args=(7777, ["local", "127.0.0.1:8888"], [], args.frames, args.render),
        )
        b = threading.Thread(
            target=run_session,
            args=(8888, ["127.0.0.1:7777", "local"], [], args.frames, False),
        )
        a.start(), b.start()
        a.join(), b.join()
        return

    run_session(args.local_port, args.players, args.spectators, args.frames, args.render)


if __name__ == "__main__":
    main()
