"""SyncTest example: forced rollback + checksum verification every frame.

Host-session flavor of the reference's ex_game_synctest
(/root/reference/examples/ex_game/ex_game_synctest.rs): builds a
SyncTestSession, feeds bot inputs for all players, executes requests on
device.  Use --device-session to run the same thing through the fused
DeviceSyncTestSession instead (states never leave HBM).

  python examples/ex_game_synctest.py --num-players 2 --check-distance 7 --frames 600
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-players", type=int, default=2)
    ap.add_argument("--check-distance", type=int, default=7)
    ap.add_argument("--input-delay", type=int, default=0)
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--render", action="store_true")
    ap.add_argument("--device-session", action="store_true")
    args = ap.parse_args()

    from ex_game import FPS, Game, box_config
    from ggrs_tpu.sessions import SessionBuilder

    game = Game(args.num_players, render=args.render)

    if args.device_session:
        import jax.numpy as jnp
        from ggrs_tpu.sessions import DeviceSyncTestSession

        sess = DeviceSyncTestSession(
            game.box.advance,
            game.box.init_state(),
            jnp.zeros((args.num_players,), jnp.uint8),
            check_distance=max(args.check_distance, 1),
        )
        inputs = np.asarray(
            [
                [game.bot_input(p, f) for p in range(args.num_players)]
                for f in range(args.frames)
            ],
            np.uint8,
        )
        sess.run_ticks(inputs)
        print(f"device synctest: {args.frames} frames, no desyncs")
        return

    builder = (
        SessionBuilder(box_config())
        .with_num_players(args.num_players)
        .with_check_distance(args.check_distance)
        .with_input_delay(args.input_delay)
        .with_fps(FPS)
    )
    sess = builder.start_synctest_session()

    for frame in range(args.frames):
        for p in range(args.num_players):
            sess.add_local_input(p, game.bot_input(p, frame))
        game.handle_requests(sess.advance_frame())
        game.draw()
    print(f"synctest: {args.frames} frames, no desyncs (state on device)")


if __name__ == "__main__":
    main()
