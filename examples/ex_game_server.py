"""Massed-hosting example: N live P2P BoxGame matches on one chip.

The reference binds one session to one process; a matchmaking service
hosting hundreds of games runs hundreds of processes.  Here ONE process
drives N matches (2 peers each, in-memory transport — the shape of a game
server simulating authoritatively for its clients) and fulfills all 2N
sessions' per-tick request lists with a single batched device dispatch
(``parallel.BatchedRequestExecutor``).  Per-session rollback depths differ
every tick; the pool normalizes them into one predicated program.

  python examples/ex_game_server.py --matches 16 --frames 300
"""

from __future__ import annotations

import argparse
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--matches", type=int, default=8)
    ap.add_argument("--frames", type=int, default=240)
    ap.add_argument("--max-prediction", type=int, default=8)
    args = ap.parse_args()

    import numpy as np

    from ex_game import box_config
    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.games import BoxGame
    from ggrs_tpu.net import InMemoryNetwork
    from ggrs_tpu.parallel import BatchedRequestExecutor
    from ggrs_tpu.sessions import SessionBuilder

    game = BoxGame(2)
    n_sessions = 2 * args.matches

    # compile the pool BEFORE any session exists (see ops executor warmup)
    pool = BatchedRequestExecutor(
        game.advance,
        game.init_state(),
        lambda pairs: np.asarray([p[0] for p in pairs], np.uint8),
        batch_size=n_sessions,
        ring_length=args.max_prediction + 2,
        max_burst=args.max_prediction + 1,
    )
    pool.warmup(np.zeros((2,), np.uint8))

    net = InMemoryNetwork()
    sessions, schedules = [], []
    for m in range(args.matches):
        names = (f"A{m}", f"B{m}")
        for me in (0, 1):
            b = (
                SessionBuilder(box_config())
                .with_rng(random.Random(1000 + 3 * m + me))
                .with_max_prediction_window(args.max_prediction)
                .add_player(Local(), me)
                .add_player(Remote(names[1 - me]), 1 - me)
            )
            sessions.append(b.start_p2p_session(net.socket(names[me])))
            schedules.append(
                lambda i, m=m, me=me: ((i + 2 * m + me) // (2 + m % 3)) % 16
            )

    # inputs hold constant over the final frames so repeat-last predictions
    # become correct and every peer's live state converges to the true
    # simulation (predicted tails otherwise legitimately differ at the
    # moment we stop and compare)
    drain_from = max(0, args.frames - 3 * args.max_prediction)

    t0 = time.perf_counter()
    for i in range(args.frames):
        for s in sessions:
            s.poll_remote_clients()
        reqs = []
        for h, (s, sched) in enumerate(zip(sessions, schedules)):
            s.add_local_input(h % 2, sched(min(i, drain_from)))
            reqs.append(s.advance_frame())
        pool.run(reqs)  # ONE dispatch for all matches
    pool.block_until_ready()
    dt = time.perf_counter() - t0

    # verify every match's two peers agree bit-exactly
    desyncs = 0
    for m in range(args.matches):
        a, b = pool.live_state(2 * m), pool.live_state(2 * m + 1)
        for k in a:
            if not np.array_equal(np.asarray(a[k]), np.asarray(b[k])):
                desyncs += 1
                break
    rate = n_sessions * args.frames / dt
    print(
        f"hosted {args.matches} matches ({n_sessions} sessions) for "
        f"{args.frames} frames: {rate:,.0f} session-ticks/sec, "
        f"{desyncs} desynced matches"
    )
    print("SERVER-EXAMPLE-OK" if desyncs == 0 else "SERVER-EXAMPLE-DESYNC")
    return 0 if desyncs == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
