"""Shared example-game harness: BoxGame fulfilled on device, driven from a
fixed-timestep loop.

Mirrors the reference's example scaffolding (state/checksum handling, request
dispatch, desync-on-demand — /root/reference/examples/ex_game/ex_game.rs) with
a terminal renderer instead of a window: each ship is a letter on an 80x24
grid.  Keyboard input is replaced by a deterministic per-player bot (seeded),
so the examples run headless; pass --render to watch.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional

import numpy as np

import jax

# Honor an explicit JAX_PLATFORMS env var even where the container's
# interpreter startup pre-registers a tunneled accelerator and overrides the
# normal env handling (same situation tests/conftest.py documents): apply it
# through the config directly, which wins as long as no backend has
# initialized yet — true at example startup.
if os.environ.get("JAX_PLATFORMS"):
    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except RuntimeError:  # pragma: no cover - backend already up; keep as-is
        pass

import jax.numpy as jnp

from ggrs_tpu.games import BoxGame, boxgame_config
from ggrs_tpu.games.boxgame import WINDOW_H, WINDOW_W, _FP  # fixed-point consts
from ggrs_tpu.ops import DeviceRequestExecutor

FPS = 60
# prediction window shared by the example sessions and the jit warmup —
# sessions built by the drivers leave the builder default (8) untouched
MAX_PREDICTION = 8

box_config = boxgame_config


class Game:
    """Owns the device executor and renders / reports state."""

    def __init__(
        self,
        num_players: int,
        render: bool = False,
        rollbacks: bool = True,
        max_prediction: int = MAX_PREDICTION,
    ) -> None:
        self.box = BoxGame(num_players)
        self.num_players = num_players
        self.render = render
        self.executor = DeviceRequestExecutor(
            self.box.advance,
            self.box.init_state(),
            lambda pairs: jnp.asarray([p[0] for p in pairs], jnp.uint8),
        )
        # compile ALL programs the session can dispatch before its loop
        # starts: a mid-session compile pause stalls the poll/ack pump long
        # enough to trip peers' disconnect timers.  Spectators never roll
        # back (rollbacks=False skips the burst-depth compiles).  The deepest
        # burst is max_prediction resim pairs + the trailing live advance.
        self.executor.warmup(
            jnp.zeros((num_players,), jnp.uint8),
            burst_depths=range(2, max_prediction + 2) if rollbacks else (),
        )
        self.frames_run = 0

    def handle_requests(self, requests: List) -> None:
        self.executor.run(requests)
        self.frames_run += 1

    def bot_input(self, handle: int, frame: int) -> int:
        """Deterministic per-player 'AI': thrust always, turn in a pattern."""
        phase = (frame // 30 + handle * 7) % 4
        return 0b0001 | (0b0100 if phase in (1, 3) else 0b1000 if phase == 2 else 0)

    def draw(self) -> None:
        if not self.render:
            return
        state = self.executor.state
        pos = np.asarray(state["pos"]) / _FP if state["pos"].dtype == np.int32 else np.asarray(state["pos"])
        cols, rows = 78, 22
        grid = [[" "] * cols for _ in range(rows)]
        for p in range(self.num_players):
            x = int(pos[p, 0] / (WINDOW_W / _FP) * cols) % cols
            y = int(pos[p, 1] / (WINDOW_H / _FP) * rows) % rows
            grid[y][x] = chr(ord("A") + p)
        sys.stdout.write("\x1b[H\x1b[2J")
        for row in grid:
            sys.stdout.write("".join(row) + "\n")
        sys.stdout.write(f"frame {self.frames_run}\n")
        sys.stdout.flush()


class FrameClock:
    """Fixed-timestep accumulator with skip support (the reference's loop,
    /root/reference/examples/ex_game/ex_game_p2p.rs:110-136)."""

    def __init__(self, fps: int = FPS) -> None:
        self.dt = 1.0 / fps
        self.acc = 0.0
        self.last = time.perf_counter()
        self.skip_until = 0.0

    def ready_frames(self, max_frames: int = 5) -> int:
        now = time.perf_counter()
        self.acc += now - self.last
        self.last = now
        # drop backlog beyond one burst: after a long pause (e.g. a jit
        # compile) a game resumes at real-time cadence rather than fast-
        # forwarding hundreds of frames — which would outrun remote peers'
        # input rings (a spectator follows at most 60 frames behind)
        self.acc = min(self.acc, max_frames * self.dt)
        n = 0
        while self.acc >= self.dt and n < max_frames:
            self.acc -= self.dt
            if now >= self.skip_until:
                n += 1
        return n

    def skip(self, frames: int) -> None:
        """Honor a WaitRecommendation by sitting out ``frames`` frames."""
        self.skip_until = time.perf_counter() + frames * self.dt
