"""Spectator example: follow a host's confirmed inputs, never rolling back.

Counterpart of the reference's ex_game_spectator
(/root/reference/examples/ex_game/ex_game_spectator.rs).  The host must list
this process as a spectator:

  python examples/ex_game_p2p.py --local-port 7777 --players local 127.0.0.1:8888 \
      --spectators 127.0.0.1:9999
  python examples/ex_game_spectator.py --local-port 9999 --host 127.0.0.1:7777

This client is host-implementation agnostic: the host above is a single
``P2PSession``, but a pool-scale host works identically — attach a
``ggrs_tpu.broadcast.SpectatorHub`` to a ``HostSessionPool`` and the
native bank fans the same wire-identical confirmed-input stream to this
process from inside its one-crossing-per-tick loop (DESIGN.md §13;
README "Spectating & replays").  Matches journaled there replay offline
through ``ggrs_tpu.sessions.ReplaySession`` with the exact request
stream this client fulfills live.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--local-port", type=int, default=9999)
    ap.add_argument("--host", default="127.0.0.1:7777")
    ap.add_argument("--num-players", type=int, default=2)
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--render", action="store_true")
    args = ap.parse_args()

    from ex_game import FPS, FrameClock, Game, box_config
    from ggrs_tpu.core import Disconnected
    from ggrs_tpu.core.errors import (
        NotSynchronized,
        PredictionThreshold,
        SpectatorTooFarBehind,
    )
    from ggrs_tpu.net import UdpNonBlockingSocket
    from ggrs_tpu.sessions import SessionBuilder

    host, _, port = args.host.rpartition(":")
    # build (and jit-warm) the game BEFORE the session: the disconnect timer
    # runs from session creation, and warmup takes seconds.  Spectators never
    # roll back, so skip the burst-program compiles entirely.
    game = Game(args.num_players, render=args.render, rollbacks=False)
    sess = (
        SessionBuilder(box_config())
        .with_num_players(args.num_players)
        .with_fps(FPS)
        # handshake before following: the disconnect timers pause until the
        # host actually appears (it may spend tens of seconds importing jax
        # and pre-compiling before sending frame 0), then catch a real exit
        .with_sync_handshake(True)
        .with_disconnect_timeout(5_000)
        .with_disconnect_notify_delay(2_000)
        # recover quickly when the host briefly runs ahead of real time
        .with_max_frames_behind(15)
        .with_catchup_speed(4)
        .start_spectator_session(
            (host or "127.0.0.1", int(port)),
            UdpNonBlockingSocket.bind_to_port(args.local_port),
        )
    )
    clock = FrameClock(FPS)
    # ready line: scripts (and the smoke test) wait for this before starting
    # the host, so the no-handshake stream never races our socket bind
    print(f"[spectator] listening on :{args.local_port}", flush=True)

    frame = 0
    while frame < args.frames:
        sess.poll_remote_clients()
        for ev in sess.events():
            print(f"[spectator] event: {ev}")
            if isinstance(ev, Disconnected):
                # the host is gone — no more confirmed inputs will ever come
                print(f"[spectator] host disconnected at frame {frame}; exiting")
                return
        for _ in range(clock.ready_frames()):
            try:
                game.handle_requests(sess.advance_frame())
                frame = sess.current_frame
                game.draw()
            except NotSynchronized:
                pass  # handshake still completing
            except PredictionThreshold:
                pass  # host inputs not here yet
            except SpectatorTooFarBehind:
                print("[spectator] lapped by host; exiting")
                return
        time.sleep(0.0005)
    print(f"[spectator] done: {frame} frames")


if __name__ == "__main__":
    main()
