"""Benchmark: resimulated frames/sec at 8-frame rollback (BASELINE config 2).

Measures the flagship path — BoxGame under ``DeviceSyncTestSession`` with
check_distance=8, the fused load→(advance, save)^8 replay as one XLA program —
against a host-side baseline that executes the same session semantics the way
the reference does: one Python-level request at a time over NumPy state
(save = copy + checksum, advance = vectorized NumPy step).  The reference
itself publishes no numbers (BASELINE.md), so ``vs_baseline`` is the ratio of
the device path to that host request-loop on this machine.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import time
import zlib

import numpy as np

import jax
import jax.numpy as jnp

from ggrs_tpu.games import BoxGame
from ggrs_tpu.sessions import DeviceSyncTestSession

CHECK_DISTANCE = 8
PLAYERS = 2


def _inputs(n: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 16, size=(n, PLAYERS)).astype(np.uint8)


def bench_device(total_ticks: int, chunk: int) -> float:
    """Resim frames/sec through the fused device session.

    Inputs are pre-staged to device and the desync check deferred to the end:
    the timed loop contains zero host↔device transfers (each costs a full
    round-trip on a tunneled TPU), exactly how a throughput consumer would
    drive the session."""
    game = BoxGame(PLAYERS)
    sess = DeviceSyncTestSession(
        game.advance,
        game.init_state(),
        jnp.zeros((PLAYERS,), jnp.uint8),
        check_distance=CHECK_DISTANCE,
        max_prediction=CHECK_DISTANCE,
    )
    # No device->host read may happen before or inside the timed loop: on a
    # tunneled TPU the first D2H permanently degrades dispatch throughput by
    # ~1000x (measured), so desync verification runs once, after timing.
    warm = _inputs(chunk, seed=100)
    sess.run_ticks(warm, check=False)  # warmup ticks + compiles both programs
    sess.run_ticks(warm, check=False)  # steady-state program now cached
    sess.block_until_ready()

    chunks = [
        jnp.asarray(_inputs(chunk, seed=i)) for i in range(total_ticks // chunk)
    ]
    jax.block_until_ready(chunks)

    t0 = time.perf_counter()
    for staged in chunks:
        sess.run_ticks(staged, check=False)
    sess.block_until_ready()
    dt = time.perf_counter() - t0
    sess.verify()  # zero desyncs required for the number to count
    return len(chunks) * chunk * CHECK_DISTANCE / dt


def bench_host_baseline(ticks: int) -> float:
    """The same synctest semantics executed the reference's way: a Python
    request loop, one save/load/advance at a time, NumPy state."""
    game = BoxGame(PLAYERS)
    state = game.init_state_np()
    saved = {}  # frame -> (state copy, checksum)
    history = {}
    inputs_by_frame = {}
    d = CHECK_DISTANCE
    ins = _inputs(ticks, seed=7)

    def checksum(s):
        return zlib.crc32(s["pos"].tobytes() + s["vel"].tobytes() + s["rot"].tobytes())

    t0 = time.perf_counter()
    resim_frames = 0
    for frame in range(ticks):
        inputs_by_frame[frame] = ins[frame]
        if frame > d:
            # verify window, then forced rollback: load + d×(save, advance)
            for f in range(frame - d, frame):
                if f in history and f in saved and saved[f][1] != history[f]:
                    raise AssertionError("desync in baseline")
            state = {k: v.copy() for k, v in saved[frame - d][0].items()}
            for f in range(frame - d, frame):
                if f > frame - d:
                    saved[f] = ({k: v.copy() for k, v in state.items()}, checksum(state))
                state = game.advance_np(state, inputs_by_frame[f])
                resim_frames += 1
        cs = checksum(state)
        saved[frame] = ({k: v.copy() for k, v in state.items()}, cs)
        history.setdefault(frame, cs)
        state = game.advance_np(state, ins[frame])
        # drop data outside the ring, like the real session
        saved.pop(frame - d - 1, None)
        inputs_by_frame.pop(frame - d - 1, None)
    dt = time.perf_counter() - t0
    return max(resim_frames, 1) / dt


def main() -> None:
    backend = jax.default_backend()
    # enough work to dwarf dispatch overhead; chunked so inputs stream H2D
    total_ticks, chunk = (16384, 1024) if backend == "tpu" else (4096, 512)
    device_fps = bench_device(total_ticks, chunk)
    host_fps = bench_host_baseline(600)
    print(
        json.dumps(
            {
                "metric": f"boxgame_synctest_resim_frames_per_sec_cd{CHECK_DISTANCE}",
                "value": round(device_fps, 1),
                "unit": "resim_frames/sec",
                "vs_baseline": round(device_fps / host_fps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
