"""Benchmarks: one JSON line per BASELINE config, flagship last.

Configs (BASELINE.md "targets to measure"):
  1. BoxGame host SyncTest, cd=2     — the CPU request-loop reference point
  2. BoxGame device SyncTest, cd=8   — the flagship fused-replay path
  3. BoxGame P2P 4p, 8-branch speculation — speculative rollback vs replay
  4. EcsWorld device SyncTest, cd=16 — entity-world, long rollback window
  5. 256 batched ChipVM sessions     — massed session parallelism on 1 chip

Each line is ``{"metric", "value", "unit", "vs_baseline"}``.  The reference
publishes no numbers (BASELINE.md), so every ``vs_baseline`` is the ratio of
the measured path to the equivalent host/NumPy request loop on this machine
(config 3: ratio to the same P2P loop with speculation disabled).  The
flagship config-2 line prints LAST.

PROCESS ISOLATION: with no argument, this script re-execs itself once per
config (``python bench.py <config>``) and forwards each child's JSON line.
A fresh process per config gives each measurement a fresh tunnel client, so
no config inherits another's accumulated client state or drift.

HONEST TIMING (round 4 correction): the tunneled client acks
``block_until_ready`` WITHOUT completion until the process's first
device->host read; rounds 1-3 interpreted that first read as "permanent
~50x dispatch degradation" and avoided it — which made every device-path
number an ENQUEUE rate, not a compute rate (one r3 figure implied 3.2x the
chip's HBM peak; a probe implied 190x peak FLOPs).  Every timed config now
calls ``enter_honest_timing_mode()`` after warmup, so block_until_ready is
a real completion fence and all numbers are compute-grounded.  Expect
BENCH_r04 values far below r01-r03 on device configs: the old numbers were
fiction; these are real.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time
import zlib
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ggrs_tpu.games import BoxGame, ChipVM, EcsWorld, boxgame_config
from ggrs_tpu.sessions import DeviceSyncTestSession

CHECK_DISTANCE = 8
PLAYERS = 2
REPEATS = 3  # timed passes per config; best-of counters tunnel drift

# config name -> (function name, per-child wall-clock budget in seconds[,
# extra environment for the child]).  PRINT order (the driver reads the
# final line as the headline, so the flagship prints last); EXECUTION order
# puts the flagship first so slow configs can't starve the headline of wall
# clock — see orchestrate().
#
# The DEFAULT invocation runs only the COMPACT subset below (VERDICT r5
# item 1: round 5's 15-config suite, worst-case budgets ~5.5 h, no longer
# fit the driver's capture window and BENCH_r05 recorded rc:124 with an
# empty tail).  GGRS_BENCH_FULL=1 restores the full suite.
CONFIGS = {
    "host_cd2": ("run_host_cd2", 600),
    "host_datapath": ("run_host_datapath", 600),
    "spec_p2p": ("run_spec_p2p", 1500),
    # same speculation measurement on the CPU backend: approximates a
    # direct-attached accelerator's µs dispatch, the regime DESIGN §5/§10
    # predicts shrinks the speculation window-carry penalty
    # NOTE: JAX_PLATFORMS alone is clobbered by the container's
    # sitecustomize; main() honors GGRS_BENCH_PLATFORM via jax.config
    "spec_p2p_cpu": (
        "run_spec_p2p", 900,
        {"GGRS_BENCH_PLATFORM": "cpu",
         "GGRS_BENCH_METRIC_PREFIX": "cpubackend_"},
    ),
    # budgets sized for degraded tunnel weather: both finished in 2-4 min
    # on a quiet link but blew a 1200s budget during a 5-10x slowdown
    "ecs": ("run_ecs", 1800),
    "chipvm256": ("run_chipvm256", 1800),
    "pallas_checksum": ("run_pallas_checksum", 1200),
    "spec_width": ("run_spec_width", 1200),
    "batch_sweep": ("run_batch_sweep", 1800),
    # the sweep's biggest B validated on the virtual 8-device CPU mesh
    "batch_sweep_mesh": (
        "run_batch_sweep", 900,
        {"GGRS_BENCH_PLATFORM": "cpu",
         "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    ),
    "pool_hosting": ("run_pool_hosting", 1500),
    "pool_capacity": ("run_pool_capacity", 1800),
    "soak": ("run_soak", 1500),
    "pool_capacity_cpu": (
        "run_pool_capacity", 1200,
        {"GGRS_BENCH_PLATFORM": "cpu",
         "GGRS_BENCH_METRIC_PREFIX": "cpubackend_"},
    ),
    # the native session bank (one C++ crossing per pool tick for ALL
    # sessions' protocol+sync mechanism): 4-peer tick vs the 0.25 ms target
    # and the pooled capacity ramp, on the CPU-backend proxy (the
    # direct-attached host-bound regime the capacity headline lives in)
    "host_bank": (
        "run_host_bank", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    # the supervised bank running DEGRADED: 1/8 of slots quarantined and
    # evicted to per-session Python sessions (the fault-isolation layer's
    # steady state after real faults) vs the all-native pool
    "host_bank_degraded": (
        "run_host_bank_degraded", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    # broadcast fan-out (DESIGN.md §13): one bank-hosted match fanning its
    # confirmed-input stream to {8, 64} real spectator sessions — p99 pool
    # tick and wire bytes per viewer, on the CPU-backend host proxy
    "broadcast_fanout": (
        "run_broadcast_fanout", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    # the kernel-batched socket datapath (DESIGN.md §15): B=64 matches
    # over real loopback UDP with per-match viewer fan-out — socket
    # syscalls per pool tick and host-loop p99, native_io on vs off
    "host_bank_io": (
        "run_host_bank_io", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    # the vectorized policy plane (DESIGN.md §19): capacity sweep
    # B=64/128/256/512 matches with knee detection, fast-path coverage,
    # vectorized-vs-legacy decode p99, per-phase attribution, and the
    # serving GC posture (freeze after warmup) priced explicitly
    "host_bank_capacity": (
        "run_host_bank_capacity", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    # datapath gen 2 (DESIGN.md §23): the one-crossing inbound drain and
    # the shared dispatch socket — B=512/1024 inbound A/B (batched and
    # dispatch vs the per-slot reference drain), inbound syscalls per
    # pool tick and host-loop p99
    "inbound_gen2": (
        "run_inbound_gen2", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    # parallel slow-slot decode + GRO inbound (DESIGN.md §24): the
    # inbound_gen2 population with the decode backend and GRO toggled
    # independently — B=256/512/1024 host p99 per posture, syscalls
    # gro-on vs gro-off, decode-plane engagement counters
    "decode_parallel": (
        "run_decode_parallel", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    # the input plane (DESIGN.md §27): B=256 pooled matches with fixed
    # 4-byte uint inputs vs variable-size command records in the varrec
    # envelope — host tick p99 and wire bytes/tick, payload-vs-envelope
    # accounting, native engagement named per leg
    "input_plane": (
        "run_input_plane", 900,
        {"GGRS_BENCH_PLATFORM": "cpu"},
    ),
    "flagship": ("run_flagship", 900),
}

# The default subset: sized so the driver's capture window always sees the
# flagship line even in degraded-tunnel weather.  BENCH_r05 recorded
# rc=124 with an EMPTY tail against the round-5 suite, and the round-6
# six-config compact subset still summed to a 7200 s worst case — far
# past any driver window — so the default is now three configs
# (worst-case budgets 1500 s) under a hard total deadline
# (GGRS_BENCH_TOTAL_BUDGET, default 420 s) that clamps every child's
# budget to the time actually remaining.  Configs that don't fit are
# SKIPPED LOUDLY (stderr) rather than silently starving the headline, and
# every child's metric lines stream to stdout the moment the child prints
# them, so even a driver that kills the orchestrator mid-run has captured
# everything measured so far.  GGRS_BENCH_FULL=1 restores the full suite
# (no default deadline).
COMPACT_CONFIGS = (
    "host_cd2",
    "host_bank",
    "flagship",
)

# Compact-run deadline: leave generous headroom inside the shortest
# plausible driver capture window (the tier-1 harness uses ~870 s).
DEFAULT_TOTAL_BUDGET_S = 420


def _inputs(n: int, players: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 16, size=(n, players)).astype(np.uint8)


# children run with a metric prefix when one measurement is repeated under a
# different backend (e.g. "cpubackend_" for the CPU-dispatch speculation run)
_METRIC_PREFIX = os.environ.get("GGRS_BENCH_METRIC_PREFIX", "")


def emit(metric: str, value: float, unit: str, vs_baseline: float,
         obs: Optional[dict] = None) -> None:
    record = {
        "metric": _METRIC_PREFIX + metric,
        # small values (roofline fractions, ratios) need the digits
        "value": round(value, 1) if abs(value) >= 10 else round(value, 5),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 2),
    }
    if obs is not None:
        # obs metrics snapshot (ggrs_tpu.obs.json_snapshot shape) — rides
        # into bench_out/latest.json with the metric it annotates
        record["obs"] = obs
    print(json.dumps(record), flush=True)


def _obs_counters_snapshot(registry) -> dict:
    """The registry's counter/histogram families as a compact snapshot —
    per-slot/per-endpoint scrape gauges are dropped (at B=64 matches they
    are ~1k samples of point-in-time noise; the counters are the record)."""
    from ggrs_tpu.obs import json_snapshot

    return {
        name: fam
        for name, fam in json_snapshot(registry).items()
        if not name.startswith(("ggrs_slot_", "ggrs_endpoint_"))
    }


# ---------------------------------------------------------------------------
# device synctest harness (configs 2 and 4)
# ---------------------------------------------------------------------------


def bench_device_synctest(
    advance, init_state, input_template, input_fn, d: int, total_ticks: int, chunk: int
) -> float:
    """Resim frames/sec through the fused device session.

    Inputs are pre-staged to device and the desync check deferred to the end:
    the timed loop contains zero host↔device data transfers (each costs a
    full round-trip on a tunneled TPU), exactly how a throughput consumer
    would drive the session.  Completion IS awaited each pass — see
    enter_honest_timing_mode()."""
    sess = DeviceSyncTestSession(
        advance, init_state, input_template, check_distance=d, max_prediction=d
    )
    warm = input_fn(chunk, seed=100)
    sess.run_ticks(warm, check=False)  # warmup ticks + compiles both programs
    sess.run_ticks(warm, check=False)  # steady-state program now cached
    sess.block_until_ready()
    enter_honest_timing_mode()  # block_until_ready must be a REAL fence

    chunks = [
        jnp.asarray(input_fn(chunk, seed=i)) for i in range(total_ticks // chunk)
    ]
    jax.block_until_ready(chunks)

    # the tunneled chip's effective throughput drifts ~3x on a scale of tens
    # of seconds (shared link): take the best of REPEATS passes — the one
    # least polluted by external contention
    best = 0.0
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        for staged in chunks:
            sess.run_ticks(staged, check=False)
        sess.block_until_ready()
        dt = time.perf_counter() - t0
        best = max(best, len(chunks) * chunk * d / dt)
    # zero desyncs required for the number to count; the caller runs verify()
    # (a D2H read) only after ALL device-timed configs have finished
    return best, sess.verify


# ---------------------------------------------------------------------------
# host request-loop harness (configs 1 and the vs_baseline denominators)
# ---------------------------------------------------------------------------


def bench_host_synctest(game, players: int, d: int, ticks: int, seed: int = 7) -> float:
    """Synctest semantics executed the reference's way: a Python request
    loop, one save/load/advance at a time, NumPy state."""
    state = game.init_state_np()
    saved = {}  # frame -> (state copy, checksum)
    history = {}
    inputs_by_frame = {}
    ins = _inputs(ticks, players, seed)

    def checksum(s):
        return zlib.crc32(b"".join(np.ascontiguousarray(v).tobytes() for v in s.values()))

    def copy(s):
        return {k: np.copy(v) for k, v in s.items()}

    t0 = time.perf_counter()
    resim_frames = 0
    for frame in range(ticks):
        inputs_by_frame[frame] = ins[frame]
        if frame > d:
            # verify window, then forced rollback: load + d×(save, advance)
            for f in range(frame - d, frame):
                if f in history and f in saved and saved[f][1] != history[f]:
                    raise AssertionError("desync in baseline")
            state = copy(saved[frame - d][0])
            for f in range(frame - d, frame):
                if f > frame - d:
                    saved[f] = (copy(state), checksum(state))
                state = game.advance_np(state, inputs_by_frame[f])
                resim_frames += 1
        cs = checksum(state)
        saved[frame] = (copy(state), cs)
        history.setdefault(frame, cs)
        state = game.advance_np(state, ins[frame])
        # drop data outside the ring, like the real session
        saved.pop(frame - d - 1, None)
        inputs_by_frame.pop(frame - d - 1, None)
    dt = time.perf_counter() - t0
    return max(resim_frames, 1) / dt


# ---------------------------------------------------------------------------
# config 3: speculative P2P (4 players, 8 branches)
# ---------------------------------------------------------------------------


def _speculative_p2p_setup(speculate: bool, game=None, programs=None) -> tuple:
    """Four P2P peers over the in-memory net, each fulfilling requests with a
    device executor; peer 0 optionally speculates with 8 branches.  Returns
    (tick_fn, executors).  Pass the same ``game`` + shared ``ExecutorPrograms``
    to both variants so all eight executors compile the burst/advance programs
    once — on a remote-compile tunnel each duplicate compile costs ~1s wall
    clock."""
    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.net import InMemoryNetwork
    from ggrs_tpu.ops import DeviceRequestExecutor, ExecutorPrograms
    from ggrs_tpu.parallel import SpeculativeRollback
    from ggrs_tpu.sessions import SessionBuilder

    if game is None:
        game = BoxGame(4)
    peers = ["P0", "P1", "P2", "P3"]
    max_prediction = 8  # BASELINE config 3: 8-frame prediction window
    if programs is None:
        programs = ExecutorPrograms(game.advance, with_checksums=False)

    def sched(player, i):
        return ((i + player) // 3) % 16  # transitions force regular rollbacks

    # NumPy end to end on the host side: inputs_to_array and branch_inputs
    # never touch the device, so hypothesis construction costs no dispatches
    # (H2D happens once per fused call inside the executor/speculation)
    def to_arr(pairs):
        return np.asarray([p[0] for p in pairs], np.uint8)

    def branch_inputs(k, frame, arr):
        out = np.array(arr, np.uint8, copy=True)
        if k < 7:
            out[1:] = np.uint8(k)
        else:
            out[1:] = [sched(p, frame) for p in (1, 2, 3)]
        return out

    hyp_base = np.zeros((8, 4), np.uint8)
    hyp_base[:7, 1:] = np.arange(7, dtype=np.uint8)[:, None]

    def branch_inputs_all(frame, arr):
        # vectorized: all 8 hypotheses in one [K, players] array build
        out = hyp_base.copy()
        out[:, 0] = arr[0]
        out[7, 1:] = [sched(p, frame) for p in (1, 2, 3)]
        return out

    net = InMemoryNetwork()
    sessions, executors = [], []
    for me in range(4):
        b = (
            SessionBuilder(boxgame_config())
            .with_num_players(4)
            .with_max_prediction_window(max_prediction)
            .with_clock(lambda: 0)
            .with_rng(random.Random(91 + me))
        )
        for p in range(4):
            b = b.add_player(Local() if p == me else Remote(peers[p]), p)
        sessions.append(b.start_p2p_session(net.socket(peers[me])))
        spec = (
            SpeculativeRollback(
                game.advance, 8, branch_inputs, max_window=8,
                branch_inputs_all=branch_inputs_all,
            )
            if (speculate and me == 0)
            else None
        )
        ex = DeviceRequestExecutor(
            game.advance, game.init_state(), to_arr,
            with_checksums=False, speculation=spec, programs=programs,
        )
        # pre-compile everything (advance, bursts, speculation programs):
        # no jit compile may land inside the timed loop; the deepest burst
        # is max_prediction resim pairs + the trailing live advance
        ex.warmup(
            np.zeros((4,), np.uint8),
            burst_depths=range(2, max_prediction + 2),
        )
        executors.append(ex)

    from ggrs_tpu.core.types import LoadGameState

    def tick(i):
        """One tick of all four peers; True when peer 0's request list
        carried a rollback (a Load) — the ticks whose latency the
        speculation design claims to improve."""
        rolled = False
        for s in sessions:
            s.poll_remote_clients()
        for p, (s, ex) in enumerate(zip(sessions, executors)):
            s.add_local_input(p, sched(p, i))
            reqs = s.advance_frame()
            if p == 0 and any(isinstance(r, LoadGameState) for r in reqs):
                rolled = True
            ex.run(reqs)
        return rolled

    return tick, executors


def bench_speculative_p2p(seg_ticks: int = 100, segments: int = 4) -> tuple:
    """Time the speculative and plain variants in ALTERNATING segments so the
    tunneled chip's minute-scale throughput drift hits both equally, and take
    each variant's best segment.  Returns (spec_rate, plain_rate,
    fetch_stats, latencies); ``fetch_stats()`` reads the device hit counter
    (a D2H transfer), deferred until after the timed segments purely to keep
    data transfers out of the loops."""
    from ggrs_tpu.ops import ExecutorPrograms

    game = BoxGame(4)
    shared = ExecutorPrograms(game.advance, with_checksums=False)
    variants = {
        name: _speculative_p2p_setup(
            speculate=(name == "spec"), game=game, programs=shared
        )
        for name in ("spec", "plain")
    }
    counters = {name: 0 for name in variants}
    rates = {name: [] for name in variants}

    def run(name, n):
        tick, executors = variants[name]
        start = counters[name]
        for i in range(start, start + n):
            tick(i)
        jax.block_until_ready([ex.state for ex in executors])
        counters[name] = start + n

    for name in variants:
        run(name, 24)  # warm caches (compiles were handled by warmup())
    enter_honest_timing_mode()

    for _ in range(segments):
        for name in variants:
            t0 = time.perf_counter()
            run(name, seg_ticks)
            rates[name].append(seg_ticks / (time.perf_counter() - t0))

    # ---- latency phase (VERDICT r3 item 1): per-tick wall time with the
    # state actually materialized each tick (block_until_ready), so a
    # rollback's stall is measured to COMPLETION, not to enqueue.  Alternate
    # segments again so drift hits both variants equally.
    latencies = {n: {"tick": [], "roll": []} for n in variants}

    def run_latency(name, n):
        tick, executors = variants[name]
        ex0 = executors[0]
        start = counters[name]
        for i in range(start, start + n):
            t0 = time.perf_counter()
            rolled = tick(i)
            jax.block_until_ready(ex0.state)
            dt = time.perf_counter() - t0
            latencies[name]["tick"].append(dt)
            if rolled:
                latencies[name]["roll"].append(dt)
        counters[name] = start + n

    # a p99 needs samples: the top percentile of N ticks is ~N/100 events,
    # so 300 ticks gave a 3-sample p99 that flipped run to run.  On the CPU
    # backend (~1 ms ticks) 2400 ticks are cheap; on the tunnel (~90 ms
    # fenced ticks) stay small and treat the tunnel's tail as RTT-dominated.
    seg, rounds = (600, 4) if jax.default_backend() == "cpu" else (150, 2)
    for name in variants:
        run_latency(name, 16)  # settle into the per-tick-blocking regime
        latencies[name] = {"tick": [], "roll": []}
    for _ in range(rounds):
        for name in variants:
            run_latency(name, seg)

    ex0 = variants["spec"][1][0]

    def fetch_stats():
        return ex0.spec_hits + ex0.spec_misses, ex0.spec_hits

    return max(rates["spec"]), max(rates["plain"]), fetch_stats, latencies


# ---------------------------------------------------------------------------
# config 5: massed batched sessions
# ---------------------------------------------------------------------------


def bench_batched_chipvm(
    batch: int,
    total_ticks: int,
    chunk: int,
    d: int,
    mesh_devices: int = 1,
    repeats: int = REPEATS,
) -> Tuple[float, Any, float, float]:
    """(agg resim f/s, verify fn, compile+warmup sec, carry MiB) across
    ``batch`` independent ChipVM synctest sessions (shard_map over a
    ``mesh_devices``-device mesh — the same program the 8-chip dry-run
    validates).  ``repeats=0`` skips the timed passes entirely
    (correctness-only dryruns) and reports rate 0."""
    from ggrs_tpu.parallel import BatchedSessions, make_mesh

    vm = ChipVM(2)
    t_compile0 = time.perf_counter()
    batched = BatchedSessions(
        vm.advance,
        vm.init_state(),
        jnp.zeros((2,), jnp.uint8),
        batch_size=batch,
        mesh=make_mesh(mesh_devices),
        check_distance=d,
        max_prediction=d,
    )
    def chunk_inputs(seed):
        return jnp.asarray(
            np.random.default_rng(seed).integers(
                0, 256, size=(batch, chunk, 2)
            ).astype(np.uint8)
        )

    batched.run_ticks(chunk_inputs(100), check=False)  # warmup ticks + compiles
    batched.run_ticks(chunk_inputs(101), check=False)  # full-chunk steady program
    batched.block_until_ready()
    compile_sec = time.perf_counter() - t_compile0
    carry_mb = _tree_nbytes(batched._carry) / 2**20
    enter_honest_timing_mode()

    staged = [chunk_inputs(i) for i in range(total_ticks // chunk)]
    jax.block_until_ready(staged)

    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for c in staged:
            batched.run_ticks(c, check=False)  # fully async: no D2H inside
        batched.block_until_ready()
        dt = time.perf_counter() - t0
        best = max(best, batch * len(staged) * chunk * d / dt)

    def verify():
        assert batched.verify()["mismatches"] == 0

    return best, verify, compile_sec, carry_mb


# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# per-config entry points (each runs in its own process; see module docstring)
# ---------------------------------------------------------------------------


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def enter_honest_timing_mode() -> None:
    """One sacrificial device->host read, required before ANY timed loop.

    Measured on the tunneled TPU (round 4): until a process performs its
    first D2H read, the client acks ``jax.block_until_ready`` WITHOUT
    waiting for completion — 8 chained 4096x4096 matmuls "complete" in
    0.3 ms pre-read vs 7.1 s with a real fence (an implied 37,653 TFLOP/s,
    ~190x the chip's peak).  After the first read, block_until_ready is a
    true completion fence (block-vs-D2H-fence ratios ~= 1.0).

    Earlier rounds read this as "the first D2H permanently degrades
    dispatch ~50x" and carefully avoided reads near timed loops — which
    meant every device-path number in BENCH_r01..r03 timed ENQUEUE, not
    compute.  The "degraded" regime is simply the honest one: dispatches on
    this tunnel cost real milliseconds.  Call this after warmup in every
    bench child; on direct-attached backends (cpu, non-tunneled TPU) it is
    a harmless scalar fetch."""
    jax.device_get(jnp.zeros((), jnp.int32) + 1)


# Public spec-sheet peaks per device kind (HBM GB/s, VMEM MiB).  Used to
# ground measured numbers against the silicon (VERDICT r3 item 2): a GB/s
# reading above HBM peak means the working set lived in VMEM, not HBM.
_DEVICE_PEAKS = {
    "TPU v5 lite": {"hbm_gbs": 819.0, "vmem_mib": 128},   # v5e
    "TPU v4": {"hbm_gbs": 1228.0, "vmem_mib": 128},
    "TPU v5p": {"hbm_gbs": 2765.0, "vmem_mib": 128},
    "TPU v6 lite": {"hbm_gbs": 1640.0, "vmem_mib": 128},  # v6e/Trillium
}


def _device_info():
    """(device_kind, peaks_or_None) for jax.devices()[0]."""
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "unknown")
    return kind, _DEVICE_PEAKS.get(kind)


def _tree_nbytes(tree) -> int:
    return sum(
        np.asarray(l).nbytes for l in jax.tree_util.tree_leaves(tree)
    )


def emit_hbm_grounding(prefix: str, traffic_bytes_per_sec: float) -> None:
    """Ground a throughput number against the chip: modeled REQUIRED HBM
    traffic (ring writes + input reads; an upper bound — working sets
    smaller than VMEM may never touch HBM at all) as a fraction of the
    device's spec-sheet peak.  A fraction far below 1 states honestly that
    the config is dispatch/compute-bound on this silicon, not
    bandwidth-bound."""
    kind, peaks = _device_info()
    if peaks is None:
        return
    pct = 100.0 * traffic_bytes_per_sec / 1e9 / peaks["hbm_gbs"]
    emit(
        f"{prefix}_modeled_hbm_traffic_pct_of_peak", pct,
        f"% of {peaks['hbm_gbs']:.0f}GB/s HBM peak ({kind}); modeled "
        f"required traffic, upper bound", 0.0,
    )


def run_host_cd2() -> None:
    """Config 1: the reference-shaped CPU request loop — the 1× denominator."""
    host_cd2 = bench_host_synctest(BoxGame(PLAYERS), PLAYERS, d=2, ticks=600)
    emit("boxgame_synctest_host_resim_frames_per_sec_cd2", host_cd2,
         "resim_frames/sec", 1.0)


def _four_peer_population():
    """THE single definition of the 4-peer host-tick scenario (names, rng
    seeds; inputs come from ``_four_peer_input``): yields
    ``(builder, socket)`` per peer.  ``host_datapath`` and ``host_bank``
    both consume it, so their numbers stay comparable."""
    import random as _random

    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.net import InMemoryNetwork
    from ggrs_tpu.sessions import SessionBuilder

    P = 4
    net = InMemoryNetwork()
    names = [f"N{h}" for h in range(P)]
    for h in range(P):
        b = (
            SessionBuilder(boxgame_config())
            .with_num_players(P)
            .with_clock(lambda: 0)
            .with_rng(_random.Random(40 + h))
        )
        for o in range(P):
            b = b.add_player(Local() if o == h else Remote(names[o]), o)
        yield b, net.socket(names[h])


def _four_peer_input(i: int, h: int) -> int:
    return (i * 7 + h) % 16


def run_host_datapath() -> None:
    """Host-tick microbench (VERDICT r3 item 3): four live P2P peers over
    the in-memory net with trivial (host, no-device) request fulfillment —
    pure session + endpoint-datapath cost, the number that bounds massed
    hosting.  ``vs_baseline`` is round 3's recorded 1.17 ms/tick over the
    measured value (>1 = faster than round 3's host path)."""
    R3_US_PER_TICK = 1170.0  # docs/DESIGN.md §10, BENCH_r03 era measurement

    sessions = [
        b.start_p2p_session(sock) for b, sock in _four_peer_population()
    ]
    state = [0] * len(sessions)

    def drive(ticks, base):
        for i in range(base, base + ticks):
            for s in sessions:
                s.poll_remote_clients()
            for h, s in enumerate(sessions):
                s.add_local_input(h, _four_peer_input(i, h))
                for r in s.advance_frame():
                    k = type(r).__name__
                    if k == "SaveGameState":
                        r.cell.save(r.frame, state[h], None)
                    elif k == "LoadGameState":
                        state[h] = r.cell.data()

    drive(200, 0)  # warm
    n, base = 2000, 200
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        drive(n, base)
        best = min(best, (time.perf_counter() - t0) / n * 1e6)
        base += n
    emit("p2p4_host_datapath_us_per_tick", best, "us/tick (4 sessions)",
         R3_US_PER_TICK / best if best else 0.0)


def run_spec_p2p() -> None:
    """Config 3: speculative P2P vs the same loop with speculation off —
    throughput AND per-tick latency distributions (the axis the speculation
    design actually targets: branch-select vs an 8-deep serial resim chain
    on rollback ticks).  The whole live path performs zero D2H, so both
    variants run at full dispatch rate; the stats fetch (a D2H read)
    happens after all timing."""
    spec_rate, plain_rate, fetch_spec_stats, lat = bench_speculative_p2p()

    # latency lines first (the throughput line stays the config headline).
    # For spec lines vs_baseline is plain/spec (>1 = speculation is FASTER
    # on that percentile); plain lines carry 1.0.
    pcts = {"p50": 50, "p99": 99}
    kinds = [("rollback_stall", "roll")]
    if any(len(lat[n]["roll"]) < len(lat[n]["tick"]) for n in lat):
        # only when some ticks did NOT roll back is the all-ticks
        # distribution a distinct measurement
        kinds.append(("tick_latency", "tick"))
    for kind, key in kinds:
        vals = {n: np.asarray(lat[n][key]) * 1e6 for n in lat}  # µs
        if any(v.size == 0 for v in vals.values()):
            continue
        stats = {
            n: {
                **{p: float(np.percentile(v, q)) for p, q in pcts.items()},
                "max": float(v.max()),
            }
            for n, v in vals.items()
        }
        for p in list(pcts) + ["max"]:
            emit(f"p2p4_plain_{kind}_us_{p}", stats["plain"][p],
                 "us/tick" if key == "tick" else "us/rollback-tick", 1.0)
            emit(f"p2p4_spec_{kind}_us_{p}", stats["spec"][p],
                 "us/tick" if key == "tick" else "us/rollback-tick",
                 stats["plain"][p] / stats["spec"][p]
                 if stats["spec"][p] else 0.0)

    rollbacks, hits = fetch_spec_stats()
    emit("p2p4_speculative_8branch_ticks_per_sec", spec_rate,
         f"ticks/sec (hit {hits}/{rollbacks} rollbacks)"
         if rollbacks else "ticks/sec",
         spec_rate / plain_rate if plain_rate else 0.0)


def run_ecs() -> None:
    """Config 4: EcsWorld, 4 players, 16-frame rollback window."""
    ecs = EcsWorld(4, entities_per_player=32)
    ticks4, chunk4 = (4096, 512) if _on_tpu() else (768, 256)
    ecs_fps, verify4 = bench_device_synctest(
        ecs.advance, ecs.init_state(), jnp.zeros((4,), jnp.uint8),
        lambda n, seed: _inputs(n, 4, seed), 16, ticks4, chunk4,
    )
    verify4()  # D2H desync gate — after timing
    ecs_host = bench_host_synctest(ecs, 4, d=16, ticks=300)
    emit("ecs_synctest_resim_frames_per_sec_cd16", ecs_fps,
         "resim_frames/sec", ecs_fps / ecs_host)
    state_b = _tree_nbytes(ecs.init_state())
    emit_hbm_grounding("ecs_synctest", (ecs_fps / 16) * (2 * state_b + 16 + 4))


def run_chipvm256() -> None:
    """Config 5: 256 concurrent ChipVM sessions batched on one chip."""
    ticks5, chunk5 = (1024, 256) if _on_tpu() else (128, 64)
    vm_rate, verify5, _, _ = bench_batched_chipvm(256, ticks5, chunk5, d=8)
    verify5()  # D2H desync gate — after timing
    vm_host = bench_host_synctest(ChipVM(2), 2, d=8, ticks=300)
    emit("chipvm_256sessions_resim_frames_per_sec", vm_rate,
         "resim_frames/sec", vm_rate / vm_host)
    state_b = _tree_nbytes(ChipVM(2).init_state())
    emit_hbm_grounding("chipvm_256sessions", (vm_rate / 8) * (2 * state_b + 16 + 2))


def run_batch_sweep() -> None:
    """VERDICT r4 item 3: sweep the batch axis to its knee.

    B = 256 / 1024 / 4096 / 16384 ChipVM sessions on one chip, per-B
    aggregate resim f/s + compile time + carry HBM footprint.  Tick counts
    halve as B quadruples (bounding per-B wall time to ~2× the previous
    step even at perfect scaling); the knee is read off the REPORTED
    per-session rates, which divide by measured time and are plan-shape
    independent.  On the CPU backend (the batch_sweep_mesh child) the
    sweep validates the biggest B on the 8-device virtual mesh instead of
    timing."""
    on_tpu = _on_tpu()
    mesh_devices = 1
    if not on_tpu:
        # dryrun variant: biggest B over the virtual 8-device mesh,
        # correctness only (CPU timing of 16k sessions is meaningless).
        import jax as _jax
        mesh_devices = min(8, len(_jax.devices()))
        if mesh_devices < 8:
            # without the virtual mesh this would duplicate
            # batch_sweep_mesh's job at mesh size 1 — nothing new measured
            print("# skip: batch sweep needs the TPU or the 8-device "
                  "virtual mesh (XLA_FLAGS=--xla_force_host_platform_"
                  "device_count=8)")
            return
        B = 16384
        _, verify, _, carry_mb = bench_batched_chipvm(
            B, total_ticks=8, chunk=4, d=8,
            mesh_devices=mesh_devices, repeats=0,
        )
        verify()
        emit(
            f"chipvm_sweep_b{B}_virtual_mesh{mesh_devices}_ok", 1.0,
            f"16384 sessions over {mesh_devices} virtual devices, zero "
            f"mismatches ({carry_mb:.0f} MiB carry)",
            1.0,
        )
        return

    plan = [(256, 1024, 256), (1024, 512, 128), (4096, 256, 64), (16384, 128, 32)]
    per_session_256 = None
    best_agg = 0.0
    for B, ticks, chunk in plan:
        rate, verify, compile_sec, carry_mb = bench_batched_chipvm(
            B, ticks, chunk, d=8, mesh_devices=mesh_devices
        )
        verify()
        best_agg = max(best_agg, rate)
        per_session = rate / B
        if per_session_256 is None:
            per_session_256 = per_session
        emit(
            f"chipvm_sweep_b{B}_resim_frames_per_sec", rate,
            f"agg resim f/s ({per_session:.0f}/session, compile "
            f"{compile_sec:.1f}s, carry {carry_mb:.1f} MiB)",
            per_session / per_session_256,
        )
    # a 60 Hz session at d=8 consumes 480 resim f/s; the saturated aggregate
    # bounds how many device-resident synctest-style sessions one chip's
    # COMPUTE sustains (the pool_hosting config bounds the host side)
    emit(
        "chipvm_sweep_60hz_device_session_ceiling", best_agg / (60 * 8),
        "sessions/chip (saturated agg / 480 resim f/s)", 1.0,
    )


def run_pallas_checksum() -> None:
    """Supplemental: the pallas single-pass digest vs the XLA lane formulas
    on a 256 MiB state leaf — the per-save hot op at large-state scale.
    ``vs_baseline`` is pallas GB/s over XLA GB/s (>1 = the kernel wins).

    The leaf is sized ABOVE the chip's ~128 MiB VMEM so the measurement
    actually streams from HBM: round 3 used a 64 MiB leaf and recorded
    2627 GB/s — over 3x the v5e's 819 GB/s HBM peak — because the whole
    working set stayed VMEM-resident across the timed passes.  A
    pct-of-HBM-peak line grounds the reading against the spec sheet."""
    from ggrs_tpu.ops import pallas_checksum as pc
    from ggrs_tpu.ops.checksum import _leaf_digest

    if not (pc.HAVE_PALLAS and _on_tpu()):
        print("# skip: pallas_checksum needs TPU + pallas", flush=True)
        return

    words = jnp.asarray(
        np.random.default_rng(3).integers(
            0, 2**32, size=(64 * 1024 * 1024,), dtype=np.uint32
        )
    )
    nbytes = words.size * 4

    pallas_fn = jax.jit(pc.leaf_digest_pallas)
    # pin the baseline to the pure-XLA lanes even if the caller exported
    # GGRS_TPU_PALLAS_CHECKSUM=on (else this benchmark compares pallas to
    # itself and the lane-equality assert below is vacuous)
    pc.use_pallas_checksums(False)
    xla_fn = jax.jit(_leaf_digest)

    a, b = pallas_fn(words), xla_fn(words)
    jax.block_until_ready((a, b))
    enter_honest_timing_mode()

    def rate(fn) -> float:
        # 60 passes per fenced segment so the tunnel's fixed fence cost
        # (~80 ms) amortizes below the streaming time
        best = 0.0
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            out = [fn(words) for _ in range(60)]
            jax.block_until_ready(out)
            best = max(best, 60 * nbytes / (time.perf_counter() - t0))
        return best

    pallas_gbs = rate(pallas_fn) / 1e9
    xla_gbs = rate(xla_fn) / 1e9
    assert np.array_equal(np.asarray(a), np.asarray(b)), "lane mismatch"
    emit("pallas_checksum_digest_gb_per_sec", pallas_gbs, "GB/s (256MiB leaf)",
         pallas_gbs / xla_gbs if xla_gbs else 0.0)
    kind, peaks = _device_info()
    if peaks is not None:
        best_gbs = max(pallas_gbs, xla_gbs)
        emit("checksum_digest_pct_of_hbm_peak",
             100.0 * best_gbs / peaks["hbm_gbs"],
             f"% of {peaks['hbm_gbs']:.0f}GB/s HBM peak ({kind}); leaf "
             f"streams from HBM (256MiB > {peaks['vmem_mib']}MiB VMEM)",
             0.0)


def _match_population(n_matches: int):
    """THE single definition of the hosting benches' match population:
    yields ``(builder, socket, schedule)`` per session — names, rng seeds,
    and input schedules that every hosting variant (per-session, pooled,
    host-bank) must share so their numbers stay comparable."""
    import random

    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.net import InMemoryNetwork
    from ggrs_tpu.sessions import SessionBuilder

    net = InMemoryNetwork()
    for m in range(n_matches):
        names = (f"A{m}", f"B{m}")
        for me in (0, 1):
            b = (
                SessionBuilder(boxgame_config())
                .with_clock(lambda: 0)
                .with_rng(random.Random(3 + 5 * m + me))
                .add_player(Local(), me)
                .add_player(Remote(names[1 - me]), 1 - me)
            )
            yield (
                b,
                net.socket(names[me]),
                lambda i, m=m, me=me: ((i + 2 * m + me) // (2 + m % 3)) % 16,
            )


def _build_matches(n_matches: int):
    """The per-session form of ``_match_population``: started P2PSessions."""
    sessions, schedules = [], []
    for b, sock, sched in _match_population(n_matches):
        sessions.append(b.start_p2p_session(sock))
        schedules.append(sched)
    return sessions, schedules


def _pooled_matches_setup(n_matches: int):
    """n_matches 2-peer BoxGame matches over one in-memory net with ONE
    BatchedRequestExecutor fulfilling all 2·n sessions.  Returns
    (sessions, schedules, pool)."""
    from ggrs_tpu.parallel import BatchedRequestExecutor

    game = BoxGame(2)

    def to_arr(pairs):
        return np.asarray([p[0] for p in pairs], np.uint8)

    sessions, schedules = _build_matches(n_matches)
    pool = BatchedRequestExecutor(
        game.advance, game.init_state(), to_arr,
        batch_size=len(sessions), ring_length=10, max_burst=9,
        with_checksums=False,
    )
    pool.warmup(np.zeros((2,), np.uint8))
    return sessions, schedules, pool


def _hosting_setup(n_matches: int, pooled: bool):
    """n_matches 2-peer BoxGame matches over one in-memory net; fulfillment
    is either ONE BatchedRequestExecutor for all 2·n sessions (pooled) or a
    per-session DeviceRequestExecutor pool sharing compiled programs.
    Returns (tick_fn, finalize_fn)."""
    from ggrs_tpu.ops import DeviceRequestExecutor, ExecutorPrograms

    game = BoxGame(2)

    def to_arr(pairs):
        return np.asarray([p[0] for p in pairs], np.uint8)

    if pooled:
        sessions, schedules, pool = _pooled_matches_setup(n_matches)

        def tick(i):
            for s in sessions:
                s.poll_remote_clients()
            reqs = []
            for h, (s, sched) in enumerate(zip(sessions, schedules)):
                s.add_local_input(h % 2, sched(i))
                reqs.append(s.advance_frame())
            pool.run(reqs)

        return tick, pool.block_until_ready

    sessions, schedules = _build_matches(n_matches)
    B = len(sessions)

    programs = ExecutorPrograms(game.advance, with_checksums=False)
    executors = [
        DeviceRequestExecutor(
            game.advance, game.init_state(), to_arr,
            with_checksums=False, programs=programs,
        )
        for _ in range(B)
    ]
    executors[0].warmup(np.zeros((2,), np.uint8), burst_depths=range(2, 10))

    def tick(i):
        for s in sessions:
            s.poll_remote_clients()
        for h, (s, sched, ex) in enumerate(zip(sessions, schedules, executors)):
            s.add_local_input(h % 2, sched(i))
            ex.run(s.advance_frame())

    def finalize():
        jax.block_until_ready([ex.state for ex in executors])

    return tick, finalize


def p2p_soak(frames: int, periodic=None) -> dict:
    """THE long-horizon two-peer harness, shared verbatim by the bench soak
    line and tests/test_soak.py so both certify the same behavior: 2 peers
    over the seeded fault net, desync detection on, rolling bit-exact
    comparison of every settled frame (a frame's first save may be
    speculative — the LAST save wins, compared once both peers are
    max_prediction+1 past it, then forgotten so memory stays bounded).

    ``periodic(sessions, digests)`` runs every 10k frames for extra
    invariants (the test asserts queue bounds there).  Returns
    ``{"fps", "compared", "desyncs", "rss_drift_mb"}`` after asserting
    convergence itself."""
    import resource

    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.core.types import DesyncDetection
    from ggrs_tpu.net import InMemoryNetwork
    from ggrs_tpu.sessions import SessionBuilder

    game = BoxGame(2)
    net = InMemoryNetwork(seed=1234, loss=0.08, duplicate=0.04, reorder=0.04)
    clock_now = [0]
    sessions = []
    for me in (0, 1):
        b = (
            SessionBuilder(boxgame_config())
            .with_desync_detection_mode(DesyncDetection.on(interval=100))
            .with_clock(lambda: clock_now[0])
            .with_rng(random.Random(77 + me))
            .add_player(Local(), me)
            .add_player(Remote(("peer", 1 - me)), 1 - me)
        )
        sessions.append(b.start_p2p_session(net.socket(("peer", me))))

    # settled = both peers advanced past the frame by the whole prediction
    # window, so no speculative save can still be pending for it
    horizon_slack = sessions[0]._max_prediction + 1
    states = [game.init_state_np(), game.init_state_np()]
    digests: list = [{}, {}]
    compared = [0]

    def digest(st) -> int:
        return zlib.crc32(
            b"".join(np.ascontiguousarray(v).tobytes() for v in st.values())
        )

    def compare_settled() -> None:
        horizon = min(s.current_frame for s in sessions) - horizon_slack
        for f in [f for f in digests[0] if f <= horizon]:
            if f in digests[1]:
                assert digests[0][f] == digests[1][f], (
                    f"state divergence at frame {f}"
                )
                del digests[1][f]
                compared[0] += 1
            del digests[0][f]

    def rss_mb() -> float:
        # CURRENT resident set, not ru_maxrss: the rusage value is a
        # process-lifetime high-water mark, so a pytest run whose earlier
        # device tests peaked higher would make the drift identically 0.0
        # and the leak certification vacuous
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1]) / 1024.0
        except OSError:
            pass
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

    desyncs = 0
    rss_half = 0.0
    t0 = time.perf_counter()
    for i in range(frames):
        clock_now[0] += 16
        for me, s in enumerate(sessions):
            s.add_local_input(me, (i * 7 + me * 3) % 16)
            for r in s.advance_frame():
                k = type(r).__name__
                if k == "SaveGameState":
                    snap = {k2: v.copy() for k2, v in states[me].items()}
                    d = digest(snap)
                    r.cell.save(r.frame, snap, d)
                    digests[me][r.frame] = d  # last save wins
                elif k == "LoadGameState":
                    states[me] = {
                        k2: v.copy() for k2, v in r.cell.data().items()
                    }
                elif k == "AdvanceFrame":
                    inp = np.asarray([v for v, _ in r.inputs], np.uint8)
                    states[me] = game.advance_np(states[me], inp)
            desyncs += sum(
                1 for e in s.events()
                if type(e).__name__ == "DesyncDetected"
            )
        if i % 500 == 0:
            compare_settled()
        if i == frames // 2:
            rss_half = rss_mb()
        if periodic is not None and i % 10_000 == 0:
            periodic(sessions, digests)
    compare_settled()
    dt = time.perf_counter() - t0
    assert desyncs == 0, f"{desyncs} desync events over the soak"
    assert compared[0] > frames // 2, f"only {compared[0]} frames compared"
    assert all(s.current_frame >= frames - 64 for s in sessions), (
        "a peer stalled short of the horizon"
    )
    return {
        "fps": frames / dt,
        "compared": compared[0],
        "desyncs": desyncs,
        "rss_drift_mb": rss_mb() - rss_half,
    }


def pool_soak(ticks: int, n_matches: int = 4) -> dict:
    """Long-horizon pooled-hosting harness shared by bench and test: one
    BatchedRequestExecutor fulfilling 2·n_matches sessions for ``ticks``
    ticks (periodic fences), asserting every session reaches the horizon.
    Returns ``{"session_ticks_per_sec", "sessions", "ring_wraps"}``."""
    sessions, schedules, pool = _pooled_matches_setup(n_matches)
    n_sessions = len(sessions)
    t0 = time.perf_counter()
    for i in range(ticks):
        reqs = []
        for h, (s, sched) in enumerate(zip(sessions, schedules)):
            s.add_local_input(h % 2, sched(i))
            reqs.append(s.advance_frame())
        pool.run(reqs)
        if i % 2_000 == 0:
            pool.block_until_ready()
    pool.block_until_ready()
    dt = time.perf_counter() - t0
    assert all(s.current_frame >= ticks - 64 for s in sessions), (
        "a pooled session stalled short of the horizon"
    )
    for m in range(n_matches):
        fa = sessions[2 * m].current_frame
        fb = sessions[2 * m + 1].current_frame
        assert abs(fa - fb) <= sessions[0]._max_prediction
    return {
        "session_ticks_per_sec": n_sessions * ticks / dt,
        "sessions": n_sessions,
        "ring_wraps": ticks // 128,
    }


def run_soak() -> None:
    """Soak line (VERDICT r4 item 6): the long-horizon run as a recorded
    metric, certifying the bookkeeping doesn't leak or drift at horizons
    the reference never tests.  The harnesses are shared with
    tests/test_soak.py (p2p_soak / pool_soak above)."""
    FRAMES = 100_000
    stats = p2p_soak(FRAMES)
    emit(
        "soak_p2p_100k_frames_per_sec", stats["fps"],
        f"frames/sec sustained over 1e5 faulted frames ({stats['compared']} "
        f"settled frames bit-identical, 0 desyncs, RSS drift "
        f"{stats['rss_drift_mb']:.1f} MiB)",
        1.0,
    )
    # 1e5 pooled ticks off the tunnel; 2e4 through it (each tunneled pool
    # tick costs ~10 ms of enqueue+host, so 1e5 would blow the config
    # budget — the wraparound horizons are crossed ~156x even at 2e4)
    ticks = 20_000 if _on_tpu() else 100_000
    pstats = pool_soak(ticks)
    emit(
        "soak_pool_session_ticks_per_sec", pstats["session_ticks_per_sec"],
        f"session_ticks/sec sustained over {ticks} pooled ticks "
        f"({pstats['sessions']} sessions, ~{pstats['ring_wraps']} "
        f"input-ring wraps/queue, all sessions at full horizon)",
        1.0,
    )


def run_pool_capacity() -> None:
    """THE capacity headline (VERDICT r4 item 1): how many live 60 Hz
    matches does one chip host?

    Ramps the pooled-hosting match count B; at each B, T ticks run with a
    per-tick completion fence (a real 60 Hz server must finish each tick's
    work inside its frame) and the per-tick wall-time distribution is
    recorded.  The capacity is the largest ramp step whose p99 tick time
    fits the 16.7 ms frame budget; at every step the tick is decomposed
    into host bookkeeping (sessions, input queues, request assembly) vs
    device fulfillment+fence, naming the limiting regime.  Runs on the
    tunneled TPU (fence ≈ tunnel RTT: a LOWER bound on direct-attached
    capacity) and, as the pool_capacity_cpu child, on the CPU backend (µs
    dispatch: the direct-attached host-bound proxy)."""
    frame_budget_ms = 1000.0 / 60.0
    T = 400
    depth = 8  # pipelined mode: fence the tick from `depth` ago — results
    #            become observable <= depth frames late (the rollback window;
    #            simulation itself stays device-resident and real-time)
    ramp = [16, 32, 64, 128, 256, 512]
    max_ok = {"strict": 0, "pipelined": 0}
    knee_stats = {}
    tick_counter = [0]
    for B in ramp:
        sessions, schedules, pool = _pooled_matches_setup(B)
        tick_counter[0] = 0
        fence_queue: list = []

        def tick(mode):
            i = tick_counter[0]
            tick_counter[0] = i + 1
            t0 = time.perf_counter()
            for s in sessions:
                s.poll_remote_clients()
            reqs = []
            for h, (s, sched) in enumerate(zip(sessions, schedules)):
                s.add_local_input(h % 2, sched(i))
                reqs.append(s.advance_frame())
            t1 = time.perf_counter()
            pool.run(reqs)
            if mode == "strict":
                pool.block_until_ready()
            else:
                # fence marker: a fresh scalar DERIVED from this tick's
                # carry.  Blocking on the carry leaf itself would block on
                # a buffer the NEXT tick donates back to the runtime
                # (session_pool jits with donate_argnums on TPU) — a
                # deleted-array error waiting to happen.  The derived sum
                # is donated nowhere, and fencing it fences the tick that
                # produced its operand.
                marker = jnp.sum(
                    jax.tree_util.tree_leaves(pool.live_states)[0]
                )
                fence_queue.append(marker)
                if len(fence_queue) > depth:
                    jax.block_until_ready(fence_queue.pop(0))
            t2 = time.perf_counter()
            return (t1 - t0) * 1e3, (t2 - t1) * 1e3

        for _ in range(16):
            tick("strict")
        enter_honest_timing_mode()
        for mode in ("strict", "pipelined"):
            if mode in knee_stats:
                continue  # past its knee at a smaller B: a noisy pass at a
                #           larger B must not overwrite max_ok upward
            # best-of-REPEATS distributions: a single 400-tick pass on the
            # shared box swings p99 by ±40% with ambient load; the pass
            # least polluted by contention is the honest capacity estimate
            # (same policy as every other timed config here)
            best = None
            for _ in range(REPEATS):
                host_ms = np.empty(T)
                dev_ms = np.empty(T)
                for i in range(T):
                    host_ms[i], dev_ms[i] = tick(mode)
                pool.block_until_ready()  # drain between passes
                fence_queue.clear()
                total = host_ms + dev_ms
                p50 = float(np.percentile(total, 50))
                p99 = float(np.percentile(total, 99))
                host_frac = float(np.median(host_ms / total))
                if best is None or p99 < best[1]:
                    best = (p50, p99, host_frac)
            p50, p99, host_frac = best
            tag = "" if mode == "strict" else f"_pipelined{depth}"
            emit(
                f"pool_capacity_b{B}{tag}_tick_ms_p99", p99,
                f"ms/tick p99, best of {REPEATS}x{T}-tick passes, {mode} "
                f"fence (p50 {p50:.2f} ms, host fraction {host_frac:.2f})",
                frame_budget_ms / p99,
            )
            if p99 <= frame_budget_ms:
                max_ok[mode] = B
            else:
                knee_stats[mode] = (B, host_frac)
        del sessions, schedules, pool
        if all(m in knee_stats for m in ("strict", "pipelined")):
            break

    for mode in ("strict", "pipelined"):
        regime = ""
        if mode in knee_stats:
            b_knee, host_frac = knee_stats[mode]
            regime = (
                f"; knee at B={b_knee}, limiting regime "
                f"{'host bookkeeping' if host_frac > 0.5 else 'device fulfillment+fence'}"
                f" ({host_frac:.0%} host)"
            )
        tag = "" if mode == "strict" else f"_pipelined{depth}"
        emit(
            f"pool_max_60hz_matches_per_chip{tag}", float(max_ok[mode]),
            f"matches (2 sessions each) with p99 tick <= 16.7 ms, {mode} "
            f"fence{regime}",
            1.0,
        )


def run_spec_width() -> None:
    """The K-branch width ratio DESIGN §5 called unverifiable — measured.

    The question: does advancing K vmapped branch hypotheses alongside the
    live state cost ~the wall time of one advance (spare parallel width, the
    TPU's proposition) or ~K× (serialized)?  Per-tick host dispatches can't
    answer it through the tunnel (per-dispatch overhead ≫ device work), so
    this scans T ticks of the branch-upkeep program — live advance + vmapped
    K-branch advance + the window-ring write, the device body of
    ``SpeculativeRollback.advance_and_extend`` — in ONE program per dispatch,
    fenced once, against the identical scan of the plain advance.
    ``spec_width_ratio_kK`` = t(K)/t(plain) per tick: 1.0 = branches ride
    free, K = fully serialized."""
    game = BoxGame(PLAYERS)
    T = 4096 if _on_tpu() else 1024     # ticks per dispatch
    dispatches, window = 4, 64
    inps = jnp.asarray(_inputs(T, PLAYERS, seed=17))
    st0 = jax.tree_util.tree_map(
        lambda l: jnp.array(l, copy=True), game.init_state()
    )

    def plain_scan(st, xs):
        return jax.lax.scan(lambda s, x: (game.advance(s, x), None), st, xs)[0]

    def make_width_scan(K: int):
        # K hypotheses: local player's real input, remote held at candidate k
        cands = jnp.arange(K, dtype=jnp.uint8)

        def body(carry, xs):
            live, branches, ring = carry
            inp, i = xs
            live = game.advance(live, inp)
            inp_k = jnp.stack(
                [jnp.broadcast_to(inp[0], (K,)), cands], axis=1
            ).astype(jnp.uint8)
            branches = jax.vmap(game.advance)(branches, inp_k)
            slot = jax.lax.rem(i, jnp.int32(window))
            ring = jax.tree_util.tree_map(
                lambda buf, leaf: jax.lax.dynamic_update_index_in_dim(
                    buf, leaf, slot, axis=0
                ),
                ring,
                branches,
            )
            return (live, branches, ring), None

        def run(st, xs):
            branches0 = jax.tree_util.tree_map(
                lambda l: jnp.broadcast_to(l[None], (K,) + l.shape).copy(), st
            )
            ring0 = jax.tree_util.tree_map(
                lambda l: jnp.zeros((window,) + l.shape, l.dtype), branches0
            )
            out, _ = jax.lax.scan(body, (st, branches0, ring0), xs)
            # return the FULL carry: returning only the live state lets
            # XLA's while-loop simplifier dead-code-eliminate the branch
            # advances and ring writes entirely (verified via HLO cost
            # analysis: 0 dynamic-update-slices and ~2.5x fewer flops with
            # a live-only return), which would time plain against plain
            return out

        return run

    ticks_i = jnp.arange(T, dtype=jnp.int32)
    plain_j = jax.jit(plain_scan)
    jax.block_until_ready(plain_j(st0, inps))
    enter_honest_timing_mode()

    def timed(fn, xs) -> float:
        best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            out = None
            for _ in range(dispatches):
                out = fn(st0, xs)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        return best / (dispatches * T)  # seconds per tick

    t_plain = timed(plain_j, inps)
    emit("spec_width_plain_us_per_tick", t_plain * 1e6, "us/tick", 1.0)
    for K in (1, 2, 4, 8):
        wj = jax.jit(make_width_scan(K))
        jax.block_until_ready(wj(st0, (inps, ticks_i)))
        t_k = timed(wj, (inps, ticks_i))
        emit(
            f"spec_width_ratio_k{K}", t_k / t_plain,
            f"x plain advance per tick ({t_k*1e6:.2f} us/tick; 1.0 = "
            f"branches ride free, {K}.0 = serialized)",
            t_plain / t_k,
        )


def run_pool_hosting() -> None:
    """Supplemental: massed hosting — 32 live P2P matches (64 sessions) on
    one chip, every tick's 64 heterogeneous request lists fulfilled as ONE
    batched dispatch (parallel.BatchedRequestExecutor) vs one device
    executor per session.  Metric is aggregate session-ticks/sec;
    ``vs_baseline`` is pooled over per-session (>1 = batching wins)."""
    n_matches, seg, segments = 32, 60, 3
    variants = {
        name: _hosting_setup(n_matches, pooled=(name == "pooled"))
        for name in ("pooled", "individual")
    }
    counters = {name: 0 for name in variants}
    rates = {name: [] for name in variants}

    def run(name, n):
        tick, finalize = variants[name]
        start = counters[name]
        for i in range(start, start + n):
            tick(i)
        finalize()
        counters[name] = start + n

    for name in variants:
        run(name, 16)  # warm
    enter_honest_timing_mode()
    # alternate segments so tunnel drift hits both variants equally
    for _ in range(segments):
        for name in variants:
            t0 = time.perf_counter()
            run(name, seg)
            rates[name].append(
                2 * n_matches * seg / (time.perf_counter() - t0)
            )

    pooled, individual = max(rates["pooled"]), max(rates["individual"])
    emit("p2p_pool_hosting_64sessions_session_ticks_per_sec", pooled,
         "session_ticks/sec (one dispatch per tick)",
         pooled / individual if individual else 0.0)


def bench_bare_scan_floor(game, total_ticks: int, chunk: int) -> float:
    """The control VERDICT r4 demanded: a bare ``jit(lax.scan(advance))`` —
    no ring, no digest, no history — run over the same advance-step count as
    the flagship's replay and credited at the same d-resim-frames-per-tick
    rate.  This measures the serial-scan physics floor; the flagship/floor
    ratio is the replay program's true overhead.  (Round-5 measurement:
    ~2.5 µs per advance step ⇒ ~350k resim-credit f/s — the round-4 claim
    that ~11 µs/frame "is the physics" attributed digest+ring overhead to
    the scan step and was wrong; see scripts/floor_probe.py.)"""
    d = CHECK_DISTANCE
    steps = (d + 1) * chunk  # same advance count per dispatch as the replay

    def body(st, inp):
        return game.advance(st, inp), None

    bare = jax.jit(lambda st, i: jax.lax.scan(body, st, i)[0])
    st0 = jax.tree_util.tree_map(
        lambda l: jnp.array(l, copy=True), game.init_state()
    )
    inps = jnp.asarray(_inputs(steps, PLAYERS, seed=41))
    jax.block_until_ready(bare(st0, inps))
    dispatches = max(1, total_ticks // chunk)
    best = 0.0
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = None
        for _ in range(dispatches):
            out = bare(st0, inps)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        best = max(best, dispatches * chunk * d / dt)
    return best


def run_flagship() -> None:
    """Config 2 (flagship): BoxGame device synctest at cd=8, plus the
    bare-scan floor control that grounds the overhead accounting."""
    game = BoxGame(PLAYERS)
    total_ticks, chunk = (16384, 1024) if _on_tpu() else (4096, 512)
    device_fps, verify2 = bench_device_synctest(
        game.advance, game.init_state(), jnp.zeros((PLAYERS,), jnp.uint8),
        lambda n, seed: _inputs(n, PLAYERS, seed),
        CHECK_DISTANCE, total_ticks, chunk,
    )
    verify2()  # D2H desync gate — after timing
    floor_fps = bench_bare_scan_floor(game, total_ticks // 2, chunk)
    host_fps = bench_host_synctest(game, PLAYERS, d=CHECK_DISTANCE, ticks=600)
    state_b = _tree_nbytes(game.init_state())
    emit_hbm_grounding(
        "boxgame_synctest",
        (device_fps / CHECK_DISTANCE) * (2 * state_b + 16 + PLAYERS),
    )
    emit(
        "bare_scan_floor_frames_per_sec", floor_fps,
        "resim-credit frames/sec (bare lax.scan(advance), no replay extras)",
        floor_fps / host_fps,
    )
    emit(
        f"boxgame_synctest_resim_frames_per_sec_cd{CHECK_DISTANCE}",
        device_fps, "resim_frames/sec", device_fps / host_fps,
    )


def _bank_matches_setup(n_matches: int, metrics=None, tracer=None):
    """The host-bank form of ``_match_population``: the SAME builders /
    sockets / schedules driven through ``parallel.HostSessionPool`` instead
    of per-session P2PSessions, fulfilled by the same
    ``BatchedRequestExecutor``.  ``metrics``: optional isolated
    ``ggrs_tpu.obs.Registry`` for the obs-budget measurements; ``tracer``:
    optional ``ggrs_tpu.obs.Tracer`` for the trace-overhead pricing."""
    from ggrs_tpu.parallel import BatchedRequestExecutor, HostSessionPool

    game = BoxGame(2)

    def to_arr(pairs):
        return np.asarray([p[0] for p in pairs], np.uint8)

    kwargs = {}
    if metrics is not None:
        kwargs["metrics"] = metrics
    if tracer is not None:
        kwargs["tracer"] = tracer
    host = HostSessionPool(**kwargs)
    schedules = []
    for b, sock, sched in _match_population(n_matches):
        host.add_session(b, sock)
        schedules.append(sched)
    pool = BatchedRequestExecutor(
        game.advance, game.init_state(), to_arr,
        batch_size=len(host), ring_length=10, max_burst=9,
        with_checksums=False,
        # descriptor plane (DESIGN.md §21): bulk twin of to_arr — the
        # encoded blobs' first byte IS the value for small uint inputs,
        # so quiet slots convert in one NumPy slice
        raw_inputs_to_array=lambda blobs, statuses: blobs[:, :, 0],
    )
    pool.warmup(np.zeros((2,), np.uint8))
    return host, schedules, pool


def _bank_tick_fn(host, schedules, pool, scrape_each_tick=False,
                  staged=False, split=None):
    """One strict-fence pool tick (host crossing + device fulfillment),
    returning (host_ms, device_ms) — the shared harness of the host_bank
    capacity ramp and the degraded config.  ``scrape_each_tick`` adds the
    obs stat harvest (one extra ctypes crossing) inside the host window —
    the scrape-budget measurement of DESIGN.md §12.  ``staged`` routes
    the local inputs through the batched ``stage_inputs`` crossing
    (descriptor plane, §21) instead of B ``add_local_input`` calls;
    ``split``, when a list, collects per-tick ``(staging_ms,
    advance_ms)`` host sub-phases (the §21 staging/decode attribution)."""
    n = len(host)
    counter = [0]
    stage = getattr(host, "stage_inputs", None) if staged else None

    def tick():
        i = counter[0]
        counter[0] = i + 1
        t0 = time.perf_counter()
        if stage is not None:
            stage([(h, h % 2, schedules[h](i)) for h in range(n)])
        else:
            for h in range(n):
                host.add_local_input(h, h % 2, schedules[h](i))
        ts = time.perf_counter() if split is not None else 0.0
        reqs = host.advance_all()
        if scrape_each_tick:
            host.scrape()
        t1 = time.perf_counter()
        if split is not None:
            split.append(((ts - t0) * 1e3, (t1 - ts) * 1e3))
        pool.run(reqs)
        pool.block_until_ready()
        t2 = time.perf_counter()
        return (t1 - t0) * 1e3, (t2 - t1) * 1e3

    return tick


def _best_tick_percentiles(tick, T):
    """(p50_ms, p99_ms, host_fraction) over T ticks, best-of-REPEATS by
    p99, honest fence entered first."""
    enter_honest_timing_mode()
    best = None
    for _ in range(REPEATS):
        host_ms = np.empty(T)
        dev_ms = np.empty(T)
        for i in range(T):
            host_ms[i], dev_ms[i] = tick()
        total = host_ms + dev_ms
        p50 = float(np.percentile(total, 50))
        p99 = float(np.percentile(total, 99))
        host_frac = float(np.median(host_ms / total))
        if best is None or p99 < best[1]:
            best = (p50, p99, host_frac)
    return best


def run_host_bank() -> None:
    """The tentpole metric (VERDICT r5 item 2): the native session bank —
    every pooled session's protocol+sync mechanism in ONE C++ crossing per
    pool tick.

    Two measurements, both on the CPU-backend proxy (µs dispatch — the
    host-bound regime the capacity headline lives in):

    1. The 4-peer host tick vs the twice-missed ≤0.25 ms round-4 target
       (``vs_baseline`` = 250 µs / measured; >1 = target met), with the
       per-session Python path's tick in the unit string for attribution.
    2. The pooled-capacity ramp: largest match count whose p99 strict-fence
       tick fits the 16.7 ms frame budget, host fraction named per step.
    """
    from ggrs_tpu.parallel import HostSessionPool

    # ---- 1. the 4-peer tick (host_datapath's EXACT scenario, via
    # _four_peer_population, bank-driven vs per-session) ----
    def four_peer_tick_us(use_bank: bool) -> float:
        builders = list(_four_peer_population())
        P = len(builders)
        state = [0] * P
        if use_bank:
            host = HostSessionPool()
            for b, s in builders:
                host.add_session(b, s)
            if not host.native_active:
                # never present the Python fallback as the native-bank
                # headline (e.g. GGRS_TPU_NO_NATIVE set): the caller skips
                return None

            def drive(ticks, base):
                for i in range(base, base + ticks):
                    for h in range(P):
                        host.add_local_input(h, h, _four_peer_input(i, h))
                    for h, reqs in enumerate(host.advance_all()):
                        for r in reqs:
                            k = type(r).__name__
                            if k == "SaveGameState":
                                r.cell.save(r.frame, state[h], None)
                            elif k == "LoadGameState":
                                state[h] = r.cell.data()
        else:
            sessions = [b.start_p2p_session(s) for b, s in builders]

            def drive(ticks, base):
                for i in range(base, base + ticks):
                    for s in sessions:
                        s.poll_remote_clients()
                    for h, s in enumerate(sessions):
                        s.add_local_input(h, _four_peer_input(i, h))
                        for r in s.advance_frame():
                            k = type(r).__name__
                            if k == "SaveGameState":
                                r.cell.save(r.frame, state[h], None)
                            elif k == "LoadGameState":
                                state[h] = r.cell.data()

        drive(200, 0)
        n, base = 2000, 200
        best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            drive(n, base)
            best = min(best, (time.perf_counter() - t0) / n * 1e6)
            base += n
        return best

    from ggrs_tpu.net import _native

    # env check FIRST: bank_lib() would g++-build the library the user
    # explicitly disabled, only to skip
    if os.environ.get("GGRS_TPU_NO_NATIVE") or _native.bank_lib() is None:
        print("# skip: host_bank needs the native toolchain", flush=True)
        return

    bank_us = four_peer_tick_us(use_bank=True)
    if bank_us is None:  # the pool silently fell back: not a native number
        print("# skip: host_bank pool did not engage the native bank",
              flush=True)
        return
    py_us = four_peer_tick_us(use_bank=False)
    emit(
        "host_bank_p2p4_tick_us", bank_us,
        f"us/tick (target 250; per-session python path {py_us:.0f} us, "
        f"{py_us / bank_us:.1f}x)",
        250.0 / bank_us if bank_us else 0.0,
    )

    # ---- 1b. the obs scrape budget (DESIGN.md §12): p99 with a metrics
    # scrape every tick vs without, at the B=64 capacity point; the scrape
    # run's counter snapshot is embedded in the bench record ----
    from ggrs_tpu.obs import Registry

    def scrape_leg(scrape: bool):
        reg = Registry()
        host, schedules, pool = _bank_matches_setup(64, metrics=reg)
        if not host.native_active:
            return None
        tick = _bank_tick_fn(host, schedules, pool,
                             scrape_each_tick=scrape)
        for _ in range(16):
            tick()
        p = _best_tick_percentiles(tick, 200)
        snap = _obs_counters_snapshot(reg)
        crossings = (host.crossings, host.stat_crossings)
        del host, schedules, pool
        return p, snap, crossings

    plain = scrape_leg(False)
    scraped = scrape_leg(True)
    if plain is not None and scraped is not None:
        p99_plain, p99_scraped = plain[0][1], scraped[0][1]
        overhead_pct = (
            (p99_scraped - p99_plain) / p99_plain * 100.0 if p99_plain else 0.0
        )
        ticks, stat_crossings = scraped[2]
        emit(
            "host_bank_obs_scrape_overhead_pct", overhead_pct,
            f"p99 delta with a per-tick metrics scrape, B=64 matches, strict "
            f"fence (scraped {p99_scraped:.2f} ms vs plain {p99_plain:.2f} "
            f"ms; {stat_crossings} stat crossings over {ticks} ticks = one "
            f"per scrape; target <5%)",
            5.0 / overhead_pct if overhead_pct > 0 else 99.0,
            obs=scraped[1],
        )

    # ---- 1c. the trace budget (DESIGN.md §14): p99 with a live Tracer
    # (python tick/crossing/slot spans + the native in-crossing phase
    # timers, armed) vs the shared NULL_TRACER, at the B=64 capacity
    # point — priced exactly like the scrape overhead above ----
    from ggrs_tpu.obs import Tracer

    def trace_leg(trace: bool):
        reg = Registry()
        tracer = Tracer(capacity=1 << 14) if trace else None
        host, schedules, pool = _bank_matches_setup(
            64, metrics=reg, tracer=tracer
        )
        if not host.native_active:
            return None
        armed = host._trace_native
        tick = _bank_tick_fn(host, schedules, pool)
        for _ in range(16):
            tick()
        p = _best_tick_percentiles(tick, 200)
        del host, schedules, pool
        return p, armed

    t_plain = trace_leg(False)
    t_traced = trace_leg(True)
    if t_plain is not None and t_traced is not None:
        p99_plain, p99_traced = t_plain[0][1], t_traced[0][1]
        overhead_pct = (
            (p99_traced - p99_plain) / p99_plain * 100.0 if p99_plain else 0.0
        )
        emit(
            "host_bank_trace_overhead_pct", overhead_pct,
            f"p99 delta with tracing on (python spans + native phase timers "
            f"{'armed' if t_traced[1] else 'UNAVAILABLE'}), B=64 matches, "
            f"strict fence (traced {p99_traced:.2f} ms vs plain "
            f"{p99_plain:.2f} ms; zero extra crossings; target <5%)",
            5.0 / overhead_pct if overhead_pct > 0 else 99.0,
        )

    # ---- 2. capacity ramp with one-crossing host + one-dispatch device ----
    frame_budget_ms = 1000.0 / 60.0
    T = 300
    max_ok = 0
    knee = None
    for B in (64, 128, 256, 512):
        host, schedules, pool = _bank_matches_setup(B)
        tick = _bank_tick_fn(host, schedules, pool)
        for _ in range(16):
            tick()
        p50, p99, host_frac = _best_tick_percentiles(tick, T)
        emit(
            f"host_bank_capacity_b{B}_tick_ms_p99", p99,
            f"ms/tick p99, strict fence, one host crossing + one dispatch "
            f"(p50 {p50:.2f} ms, host fraction {host_frac:.2f}, native "
            f"{'on' if host.native_active else 'OFF'})",
            frame_budget_ms / p99,
        )
        if p99 <= frame_budget_ms:
            max_ok = B
        else:
            knee = (B, host_frac)
        del host, schedules, pool
        if knee is not None:
            break
    regime = ""
    if knee is not None:
        b_knee, host_frac = knee
        regime = (
            f"; knee at B={b_knee}, limiting regime "
            f"{'host bookkeeping' if host_frac > 0.5 else 'device fulfillment+fence'}"
            f" ({host_frac:.0%} host)"
        )
    emit(
        "host_bank_max_60hz_matches_per_chip", float(max_ok),
        f"matches (2 sessions each) with p99 tick <= 16.7 ms, strict fence, "
        f"native session bank{regime}",
        1.0,
    )


def run_host_bank_degraded() -> None:
    """Pool throughput with 1/8 of slots quarantined+evicted (the
    supervision layer's steady state after real faults): the evicted slots
    tick per-session Python P2PSessions inside the same advance_all while
    the survivors keep the one-crossing native path.  Reported against the
    same pool fully native (``vs_baseline`` = healthy p99 / degraded p99;
    1.0 = eviction is free, lower = the Python slots' cost)."""
    from ggrs_tpu.net import _native

    if os.environ.get("GGRS_TPU_NO_NATIVE") or _native.bank_lib() is None:
        print("# skip: host_bank_degraded needs the native toolchain",
              flush=True)
        return

    B = 64  # matches (2 sessions each)
    T = 300

    def measure(degrade: bool):
        from ggrs_tpu.obs import Registry

        reg = Registry()
        host, schedules, pool = _bank_matches_setup(B, metrics=reg)
        n = len(host)
        if not host.native_active:
            return None
        tick = _bank_tick_fn(host, schedules, pool)
        for _ in range(16):
            tick()
        if degrade:
            for idx in range(0, n, 8):  # every 8th slot: 1/8 of the pool
                host.inject_slot_error(idx)
            for _ in range(16):  # let quarantine + eviction settle
                tick()
            evicted = sum(
                1 for i in range(n) if host.slot_state(i) == "evicted"
            )
            if evicted == 0:
                return None
        best = _best_tick_percentiles(tick, T)
        snap = _obs_counters_snapshot(reg)
        del host, schedules, pool
        return best, snap

    healthy = measure(degrade=False)
    degraded = measure(degrade=True)
    if healthy is None or degraded is None:
        print("# skip: host_bank_degraded pool did not engage/degrade",
              flush=True)
        return
    (d50, d99, dfrac), dsnap = degraded
    emit(
        f"host_bank_degraded_b{B}_tick_ms_p99", d99,
        f"ms/tick p99, strict fence, 1/8 slots evicted to Python "
        f"(p50 {d50:.2f} ms, host fraction {dfrac:.2f}; "
        f"all-native p99 {healthy[0][1]:.2f} ms)",
        healthy[0][1] / d99 if d99 else 0.0,
        obs=dsnap,  # the degraded run's fault/eviction/crossing counters
    )


def run_host_bank_capacity() -> None:
    """ISSUE 12 acceptance sweep (DESIGN.md §21): the capacity ramp on
    the descriptor plane — B in 64/128/256/512/1024 MATCHES (2 sessions
    each) with batched input staging + lazy request plans, strict-fence
    host+device tick, knee detection, fast-path coverage, a
    staging+decode A/B at the BENCH_r07 knee (B=512, legacy per-call
    staging + reference parse vs the descriptor plane; target >= 2x),
    and per-phase attribution — including the §21 `staging` phase — from
    the PR 5 in-crossing timers.

    GC posture: the headline p99 is measured with the collector FROZEN
    after warmup (``gc.collect()`` + ``gc.freeze()`` — the standard
    long-lived-serving configuration; at B>=256 the default collector's
    full-heap passes dominate p99).  The default-GC p99 is emitted
    alongside so the delta stays visible rather than hidden."""
    import gc

    from ggrs_tpu.net import _native

    if os.environ.get("GGRS_TPU_NO_NATIVE") or _native.bank_lib() is None:
        print("# skip: host_bank_capacity needs the native toolchain",
              flush=True)
        return

    frame_budget_ms = 1000.0 / 60.0
    T = 150

    def percentiles(tick, ticks):
        """Like _best_tick_percentiles but also reports the HOST-side p99
        (input staging + crossing + decode, device excluded) — the
        acceptance metric of ROADMAP item 3 is a host number."""
        enter_honest_timing_mode()
        best = None
        for _ in range(REPEATS):
            host_ms = np.empty(ticks)
            dev_ms = np.empty(ticks)
            for i in range(ticks):
                host_ms[i], dev_ms[i] = tick()
            total = host_ms + dev_ms
            p50 = float(np.percentile(total, 50))
            p99 = float(np.percentile(total, 99))
            host_frac = float(np.median(host_ms / total))
            host_p99 = float(np.percentile(host_ms, 99))
            if best is None or p99 < best[1]:
                best = (p50, p99, host_frac, host_p99)
        return best

    # ---- descriptor-plane A/B at the BENCH_r07 knee (B=512): staging +
    # decode host time, reference posture (per-call add_local_input +
    # the GGRS_TPU_NO_FASTPATH per-slot reference parse — NOT r07's §19
    # vectorized decode, which the plan path replaced and which cannot
    # be A/B'd in-tree; the r07 comparison is the recorded 23.7 ms
    # B=512 host p99 vs this sweep's number) vs the descriptor plane
    # (stage_inputs + RequestPlan) — the §21 acceptance ratio ----
    def staging_decode(B, descriptor):
        prev = os.environ.pop("GGRS_TPU_NO_FASTPATH", None)
        if not descriptor:
            os.environ["GGRS_TPU_NO_FASTPATH"] = "1"
        try:
            host, schedules, pool = _bank_matches_setup(B)
            if not host.native_active:
                return None
            split = []
            tick = _bank_tick_fn(host, schedules, pool,
                                 staged=descriptor, split=split)
            for _ in range(16):
                tick()
            enter_honest_timing_mode()
            best = None
            gc.collect()
            gc.freeze()  # the serving posture, like the sweep below: the
            # A/B prices the CODE paths, not default-GC full-heap spikes
            try:
                dev = []
                for _ in range(REPEATS):
                    del split[:]
                    del dev[:]
                    for _ in range(min(T, 100)):
                        dev.append(tick()[1])
                    arr = np.asarray(split)
                    sd50 = float(np.percentile(arr.sum(axis=1), 50))
                    if best is None or sd50 < best[0]:
                        best = (
                            sd50,
                            float(np.percentile(arr.sum(axis=1), 99)),
                            float(np.percentile(arr[:, 0], 50)),
                            float(np.percentile(arr[:, 1], 50)),
                            float(np.percentile(dev, 50)),
                        )
            finally:
                gc.unfreeze()
                gc.collect()
            cov = host.fast_slot_ticks
            del host, schedules, pool
            return best + (cov,)
        finally:
            os.environ.pop("GGRS_TPU_NO_FASTPATH", None)
            if prev is not None:
                os.environ["GGRS_TPU_NO_FASTPATH"] = prev

    legacy = staging_decode(512, descriptor=False)
    desc = staging_decode(512, descriptor=True)
    if legacy is None or desc is None:
        print("# skip: host_bank_capacity pool did not engage the native "
              "bank", flush=True)
        return
    emit(
        "host_bank_capacity_b512_staging_decode_ms_p50", desc[0],
        f"ms/tick staging+advance_all HOST p50 at B=512 on the "
        f"descriptor plane, GC frozen, best of {REPEATS} "
        f"(staging {desc[2]:.2f} + advance_all {desc[3]:.2f}, p99 "
        f"{desc[1]:.2f}, device window p50 {desc[4]:.2f}; SAME-DAY "
        f"reference leg = per-call staging + NO_FASTPATH per-slot "
        f"parse, NOT r07's since-replaced vectorized decode: "
        f"{legacy[0]:.2f} = {legacy[2]:.2f} + {legacy[3]:.2f}, p99 "
        f"{legacy[1]:.2f}, device p50 {legacy[4]:.2f}; "
        f"{desc[5]} fast-path slot ticks vs {legacy[5]}; the r07 "
        f"cross-reference is its recorded 23.7 ms B=512 host p99 vs "
        f"this sweep's b512_host_ms_p99)",
        legacy[0] / desc[0] if desc[0] else 0.0,
    )

    # ---- the sweep: default-GC and frozen-GC p99 per B, knee detect,
    # batched staging (the production driver posture, §21) ----
    max_ok = 0
    knee = None
    for B in (64, 128, 256, 512, 1024):
        host, schedules, pool = _bank_matches_setup(B)
        if not host.native_active:
            print("# skip: pool fell back at B=%d" % B, flush=True)
            return
        tick = _bank_tick_fn(host, schedules, pool, staged=True)
        for _ in range(16):
            tick()
        p50_d, p99_d, _, hp99_d = percentiles(tick, min(T, 100))
        gc.collect()
        gc.freeze()
        try:
            # (h_p99, not host_p99: that name is the A/B helper above)
            p50, p99, host_frac, h_p99 = percentiles(tick, T)
        finally:
            gc.unfreeze()
            gc.collect()
        fast_cov = host.fast_slot_ticks / max(
            1, host.crossings * len(host)
        )
        emit(
            f"host_bank_capacity_b{B}_host_ms_p99", h_p99,
            f"ms/tick HOST p99 (staging + one crossing + decode; the "
            f"ROADMAP item 3 acceptance metric; default-GC host p99 "
            f"{hp99_d:.2f} ms; fast-path coverage {fast_cov:.0%})",
            frame_budget_ms / h_p99 if h_p99 else 0.0,
        )
        emit(
            f"host_bank_capacity_b{B}_tick_ms_p99", p99,
            f"ms/tick p99, strict fence host+device, GC frozen after "
            f"warmup (default-GC p99 {p99_d:.2f} ms, p50 {p50_d:.2f}; "
            f"frozen p50 {p50:.2f}; host fraction {host_frac:.2f})",
            frame_budget_ms / p99,
        )
        if h_p99 <= frame_budget_ms and knee is None:
            # largest PASSING PREFIX: a noisy post-knee rung that squeaks
            # under budget must not overwrite the capacity headline
            max_ok = B
        elif h_p99 > frame_budget_ms and knee is None:
            knee = (B, host_frac)
        del host, schedules, pool
        # no early break: the B=1024 rung is part of the ISSUE 12
        # acceptance record even when the knee lands below it

    # ---- per-phase attribution at B=512 (PR 5 in-crossing timers plus
    # the §21 `staging` phase: stage_inputs time accrued outside the tick
    # window rides the same trace tail; the traced pool uses the legacy
    # parse by design, the native phase split is decode-independent) ----
    from ggrs_tpu.obs import Tracer

    host, schedules, pool = _bank_matches_setup(
        512, tracer=Tracer(capacity=1 << 14)
    )
    if host.native_active and host._trace_native:
        tick = _bank_tick_fn(host, schedules, pool, staged=True)
        for _ in range(60):
            tick()
        host.scrape()
        totals = host.native_phase_totals()
        if totals:
            ticks, phases = totals
            per_tick = {
                k: v / max(1, ticks) / 1000.0 for k, v in phases.items()
            }
            top = sorted(per_tick.items(), key=lambda kv: -kv[1])
            emit(
                "host_bank_capacity_b512_crossing_phase_us", sum(
                    per_tick.values()
                ),
                "us/tick in-crossing + staging total at B=512 matches ("
                + " ".join(f"{k}={v:.0f}" for k, v in top)
                + ")",
                1.0,
            )
    del host, schedules, pool

    regime = ""
    if knee is not None:
        b_knee, host_frac = knee
        regime = (
            f"; knee at B={b_knee}, "
            f"{'host' if host_frac > 0.5 else 'device+fence'} bound "
            f"({host_frac:.0%} host)"
        )
    emit(
        "host_bank_capacity_max_60hz_matches_per_chip", float(max_ok),
        f"matches (2 sessions each) with HOST p99 tick <= 16.7 ms, "
        f"descriptor plane (batched staging + lazy request plans), GC "
        f"frozen after warmup{regime}",
        max_ok / 512.0 if max_ok else 0.0,  # vs the BENCH_r07 knee
    )


class _AckingViewer:
    """Minimal spectator endpoint for the io bench: drains its UDP
    socket, tracks the newest InputMessage start frame, and acks once per
    tick — enough inbound/outbound viewer traffic to make the host's
    per-datagram syscall bill honest without ticking 512 full
    ``SpectatorSession`` objects."""

    def __init__(self, host_addr):
        from ggrs_tpu.net.sockets import UdpNonBlockingSocket

        self.sock = UdpNonBlockingSocket(0)
        self.addr = ("127.0.0.1", self.sock.local_port())
        self.host = host_addr
        self.last = -1

    def tick(self) -> None:
        from ggrs_tpu.net.messages import InputAck, InputMessage, Message

        saw = False
        for _, msg in self.sock.receive_all_messages():
            if isinstance(msg.body, InputMessage):
                if msg.body.start_frame > self.last:
                    self.last = msg.body.start_frame
                saw = True
        if saw:
            self.sock.send_to(
                Message(0x5150, InputAck(self.last)), self.host
            )


def run_host_bank_io() -> None:
    """The kernel-batched socket datapath (DESIGN.md §15): B=64 matches
    over REAL loopback UDP, each host slot with one external peer and
    ``IO_VIEWERS`` fan-out viewers — the topology whose packet path is
    hundreds of sendto/recvfrom syscalls per pool tick on the Python
    shuttle.  Two legs, identical traffic: ``native_io=True`` (one
    recvmmsg + one sendmmsg per slot per tick via ggrs_bank_pump) vs the
    per-datagram shuttle.  Reported: host socket syscalls per pool tick
    (target ≥10× fewer; ``vs_baseline`` = ratio/10, ≥1 = met) and the
    host-loop p99 (``vs_baseline`` = shuttle p99 / batched p99, ≥1 = no
    worse)."""
    import random as _random

    from ggrs_tpu.broadcast import SpectatorHub
    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.core.config import Config
    from ggrs_tpu.net import _native
    from ggrs_tpu.net.sockets import UdpNonBlockingSocket
    from ggrs_tpu.obs import Registry
    from ggrs_tpu.parallel import HostSessionPool
    from ggrs_tpu.sessions import SessionBuilder

    if os.environ.get("GGRS_TPU_NO_NATIVE") or _native.bank_lib() is None:
        print("# skip: host_bank_io needs the native toolchain", flush=True)
        return
    io_available = _native.net_lib() is not None

    B = 64
    IO_VIEWERS = 8
    WARMUP, T = 16, 120
    cfg = Config.for_uint(16)

    def leg(native_io: bool, trace: bool = False, warmup: int = WARMUP,
            t: int = T):
        from ggrs_tpu.obs import Tracer

        clock = [0]
        pool = HostSessionPool(
            native_io=native_io, metrics=Registry(),
            tracer=Tracer(capacity=1 << 12) if trace else None,
        )
        hub = SpectatorHub(pool, rng=_random.Random(99))
        peers = []
        host_socks = []
        viewer_groups = []
        for m in range(B):
            host_sock = UdpNonBlockingSocket(0)
            peer_sock = UdpNonBlockingSocket(0)
            host_addr = ("127.0.0.1", host_sock.local_port())
            pool.add_session(
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(_random.Random(3 + 5 * m))
                .add_player(Local(), 0)
                .add_player(
                    Remote(("127.0.0.1", peer_sock.local_port())), 1
                ),
                host_sock,
            )
            peers.append(
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(_random.Random(4 + 5 * m))
                .add_player(Local(), 1)
                .add_player(Remote(host_addr), 0)
                .start_p2p_session(peer_sock)
            )
            host_socks.append(host_sock)
            viewer_groups.append(
                [_AckingViewer(host_addr) for _ in range(IO_VIEWERS)]
            )
        for m, group in enumerate(viewer_groups):
            for v in group:
                hub.attach(m, v.addr)
        if not pool.native_active:
            return None
        if native_io and not pool.native_io_active:
            return None

        def fulfill(reqs):
            for r in reqs:
                if type(r).__name__ == "SaveGameState":
                    r.cell.save(r.frame, None, None)

        host_ms = np.empty(t)

        def tick(i, record=None):
            clock[0] += 16
            for m, peer in enumerate(peers):
                peer.add_local_input(1, (i + m) % 16)
                fulfill(peer.advance_frame())
            for group in viewer_groups:
                for v in group:
                    v.tick()
            t0 = time.perf_counter()
            for m in range(B):
                pool.add_local_input(m, 0, (i + m) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            if record is not None:
                host_ms[record] = (time.perf_counter() - t0) * 1e3

        enter_honest_timing_mode()
        for i in range(warmup):
            tick(i)
        io0 = pool.io_stats()
        py0 = sum(s.io_syscalls for s in host_socks)
        for i in range(t):
            tick(warmup + i, record=i)
        io1 = pool.io_stats()
        py1 = sum(s.io_syscalls for s in host_socks)
        native_calls = (
            io1["recv_calls"] + io1["send_calls"]
            - io0["recv_calls"] - io0["send_calls"]
        )
        datagrams = (
            io1["recv_datagrams"] + io1["send_datagrams"]
            - io0["recv_datagrams"] - io0["send_datagrams"]
        )
        syscalls_per_tick = (native_calls + (py1 - py0)) / t
        p99 = float(np.percentile(host_ms, 99))
        p50 = float(np.percentile(host_ms, 50))
        frames = [pool.current_frame(m) for m in range(B)]
        phases = None
        if trace:
            totals = pool.native_phase_totals()
            if totals is not None:
                timed, ph = totals
                phases = {
                    k: ph.get(k, 0) / max(timed, 1) / 1e3  # us/tick
                    for k in ("inbound", "outbound", "fanout")
                }
        result = dict(
            syscalls=syscalls_per_tick,
            dgrams_per_tick=datagrams / t,
            p99=p99, p50=p50,
            min_frame=min(frames),
            phases=phases,
        )
        # release the leg's ~640 fds NOW: the pool<->hub cycle keeps the
        # socket objects alive until a full GC pass, and four legs of
        # unclosed fds would trip a default 1024-fd ulimit mid-bench
        del pool, hub
        for sock in host_socks:
            sock.close()
        for peer in peers:
            peer._socket.close()
        for group in viewer_groups:
            for v in group:
                v.sock.close()
        return result

    shuttle = leg(False)
    if shuttle is None:
        print("# skip: host_bank_io pool did not engage the native bank",
              flush=True)
        return
    batched = leg(True) if io_available else None
    if batched is None:
        print("# skip: host_bank_io batched leg unavailable "
              "(no recvmmsg/sendmmsg)", flush=True)
        return
    assert batched["min_frame"] > T - 32, "a batched match stalled"
    ratio = (
        shuttle["syscalls"] / batched["syscalls"]
        if batched["syscalls"] else 0.0
    )
    emit(
        "host_bank_io_syscalls_per_tick", batched["syscalls"],
        f"host socket syscalls per pool tick, B={B} matches x "
        f"{IO_VIEWERS} viewers, native_io on (shuttle "
        f"{shuttle['syscalls']:.0f}/tick; {ratio:.1f}x fewer; "
        f"~{batched['dgrams_per_tick']:.0f} datagrams/tick batched; "
        f"target >=10x)",
        ratio / 10.0,
    )
    emit(
        f"host_bank_io_b{B}_tick_ms_p99", batched["p99"],
        f"ms/tick p99, host loop only, native_io on (p50 "
        f"{batched['p50']:.2f} ms; shuttle p99 {shuttle['p99']:.2f} ms "
        f"p50 {shuttle['p50']:.2f} ms; >=1.0 = no worse than shuttle)",
        shuttle["p99"] / batched["p99"] if batched["p99"] else 0.0,
    )
    # the PR 5 in-crossing phase timers price the move honestly: on the
    # batched leg, inbound/outbound now INCLUDE the kernel I/O that used
    # to live in Python outside the crossing (short traced legs; the p99
    # above stays untraced)
    ph_shuttle = leg(False, trace=True, warmup=8, t=60)
    ph_batched = leg(True, trace=True, warmup=8, t=60)
    if (ph_shuttle and ph_batched and ph_shuttle["phases"]
            and ph_batched["phases"]):
        ps, pb = ph_shuttle["phases"], ph_batched["phases"]
        total_b = sum(pb.values())
        emit(
            "host_bank_io_phase_us_per_tick", total_b,
            "us/tick in-crossing inbound+outbound+fanout with native_io on "
            f"(inbound {pb['inbound']:.0f} outbound {pb['outbound']:.0f} "
            f"fanout {pb['fanout']:.0f}; shuttle crossing-only "
            f"{ps['inbound']:.0f}/{ps['outbound']:.0f}/{ps['fanout']:.0f} "
            "us — the batched phases now CONTAIN the kernel I/O the "
            "shuttle paid per-datagram in Python outside the crossing)",
            1.0,
        )


def run_inbound_gen2() -> None:
    """Datapath gen 2 inbound A/B (DESIGN.md §23): B matches over real
    loopback UDP, one external peer each, NO viewer fan-out — the
    inbound path isolated.  Three legs with identical seeded traffic:

    * ``reference`` — per-slot sockets with the batched drain disabled
      (``GGRS_TPU_NO_RECV_TABLE``): the pre-gen-2 per-slot recvmmsg pump.
    * ``batched``   — per-slot sockets drained by ``ggrs_net_recv_table``
      (one crossing, still one fd per slot).
    * ``dispatch``  — every slot a view on ONE DispatchHub port
      (+1 SO_REUSEPORT sibling), native route-table demux: the fd floor
      and the syscall floor drop together.

    Reported at B=512 (headline; B=1024 reference-vs-dispatch rides
    along): inbound syscalls per pool tick in dispatch mode
    (``vs_baseline`` = reference/dispatch ratio over the 4x target) and
    the dispatch host-loop p99 vs the 16.7 ms frame budget."""
    import gc
    import random as _random

    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.core.config import Config
    from ggrs_tpu.net import _native
    from ggrs_tpu.net.sockets import DispatchHub, UdpNonBlockingSocket
    from ggrs_tpu.parallel import HostSessionPool
    from ggrs_tpu.sessions import SessionBuilder

    if os.environ.get("GGRS_TPU_NO_NATIVE") or _native.bank_lib() is None:
        print("# skip: inbound_gen2 needs the native toolchain", flush=True)
        return
    lib = _native.net_lib()
    if lib is None or not hasattr(lib, "ggrs_net_recv_table"):
        print("# skip: inbound_gen2 needs ggrs_net_recv_table", flush=True)
        return

    WARMUP = 12

    def leg(mode: str, b: int, t: int):
        env_key = "GGRS_TPU_NO_RECV_TABLE"
        saved = os.environ.get(env_key)
        if mode == "reference":
            os.environ[env_key] = "1"
        try:
            cfg = Config.for_uint(16)
            clock = [0]
            pool = HostSessionPool()
            hub = DispatchHub(siblings=1) if mode == "dispatch" else None
            peers, host_socks = [], []
            for m in range(b):
                host_sock = hub.view() if hub else UdpNonBlockingSocket(0)
                host_port = host_sock.local_port()
                peer_sock = UdpNonBlockingSocket(0)
                pool.add_session(
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(_random.Random(3 + 5 * m))
                    .add_player(Local(), 0)
                    .add_player(
                        Remote(("127.0.0.1", peer_sock.local_port())), 1
                    ),
                    host_sock,
                )
                peers.append(
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(_random.Random(4 + 5 * m))
                    .add_player(Local(), 1)
                    .add_player(Remote(("127.0.0.1", host_port)), 0)
                    .start_p2p_session(peer_sock)
                )
                host_socks.append(host_sock)
            if not pool.native_active:
                return None

            def fulfill(reqs):
                for r in reqs:
                    if type(r).__name__ == "SaveGameState":
                        r.cell.save(r.frame, None, None)

            host_ms = np.empty(t)

            def tick(i, record=None):
                clock[0] += 16
                for m, peer in enumerate(peers):
                    peer.add_local_input(1, (i + m) % 16)
                    fulfill(peer.advance_frame())
                # the host window matches _bank_tick_fn: staging (the §21
                # batched crossing) + the crossing (inbound drain +
                # mechanism + outbound flush) + plan decode; request
                # fulfillment is the device side and stays outside, as in
                # the capacity ramp
                t0 = time.perf_counter()
                pool.stage_inputs(
                    [(m, 0, (i + m) % 16) for m in range(b)]
                )
                plan = pool.advance_all()
                if record is not None:
                    host_ms[record] = (time.perf_counter() - t0) * 1e3
                for reqs in plan:
                    fulfill(reqs)

            def inbound_syscalls():
                io = pool.io_stats()
                py = (
                    hub.io_syscalls if hub
                    else sum(s.io_syscalls for s in host_socks)
                )
                return io["recv_calls"] + io["drain"]["recv_calls"] + py

            enter_honest_timing_mode()
            for i in range(WARMUP):
                tick(i)
            s0 = inbound_syscalls()
            # the serving posture (as in run_host_bank_capacity): the A/B
            # prices the datapaths, not default-GC full-heap spikes over
            # 2B live session graphs; best-of-REPEATS p99 counters
            # scheduler drift like _best_tick_percentiles
            gc.collect()
            gc.freeze()
            best = None
            try:
                for rep in range(REPEATS):
                    for i in range(t):
                        tick(WARMUP + rep * t + i, record=i)
                    p99 = float(np.percentile(host_ms, 99))
                    if best is None or p99 < best[0]:
                        best = (p99, float(np.percentile(host_ms, 50)))
            finally:
                gc.unfreeze()
                gc.collect()
            s1 = inbound_syscalls()
            frames = [pool.current_frame(m) for m in range(b)]
            drain = pool.io_stats()["drain"]
            result = dict(
                syscalls=(s1 - s0) / (t * REPEATS),
                p99=best[0],
                p50=best[1],
                min_frame=min(frames),
                fds=len(hub.filenos()) if hub else b,
                crossings=pool.crossings,
                drain_crossings=pool.drain_crossings,
                unroutable=drain["unroutable"],
            )
            del pool
            for sock in host_socks:
                sock.close()
            if hub is not None:
                hub.close()
            for peer in peers:
                peer._socket.close()
            return result
        finally:
            if saved is None:
                os.environ.pop(env_key, None)
            else:
                os.environ[env_key] = saved

    B, T = 512, 80
    legs = {}
    for mode in ("reference", "batched", "dispatch"):
        legs[mode] = leg(mode, B, T)
        if legs[mode] is None:
            print(f"# skip: inbound_gen2 {mode} leg did not engage the "
                  "native datapath", flush=True)
            return
        assert legs[mode]["min_frame"] > T - 32, f"a {mode} match stalled"
    ref, bat, dis = legs["reference"], legs["batched"], legs["dispatch"]
    assert dis["unroutable"] == 0, "dispatch demux dropped routed traffic"
    # the reference leg never touches the recv table; the batched legs
    # drain once per tick plus a bounded regrow re-invocation per
    # backpressure stop while the record table warms up to B (the exact
    # one-drain-per-tick pin lives in tests/test_net_gen2.py)
    assert ref["drain_crossings"] == 0
    assert dis["drain_crossings"] >= WARMUP + T
    assert bat["drain_crossings"] >= WARMUP + T
    ratio = ref["syscalls"] / dis["syscalls"] if dis["syscalls"] else 0.0
    emit(
        f"inbound_gen2_b{B}_syscalls_per_tick", dis["syscalls"],
        f"inbound syscalls per pool tick, B={B}, dispatch mode "
        f"({dis['fds']} fds; reference {ref['syscalls']:.0f}/tick on "
        f"{ref['fds']} fds, batched {bat['syscalls']:.0f}/tick; "
        f"{ratio:.1f}x fewer vs reference; target >=4x)",
        ratio / 4.0,
    )
    emit(
        f"inbound_gen2_b{B}_tick_ms_p99", dis["p99"],
        f"ms/tick p99, host loop only, dispatch mode (p50 "
        f"{dis['p50']:.2f} ms; batched p99 {bat['p99']:.2f} ms; "
        f"reference p99 {ref['p99']:.2f} ms; >=1.0 = inside the "
        "16.7 ms frame budget)",
        16.7 / dis["p99"] if dis["p99"] else 0.0,
    )
    # B=1024: does the dispatch win survive a doubling past the capacity
    # knee?  Reference-vs-dispatch only (shorter; the headline stays 512)
    B2, T2 = 1024, 48
    ref2 = leg("reference", B2, T2)
    dis2 = leg("dispatch", B2, T2)
    if ref2 and dis2:
        r2 = ref2["syscalls"] / dis2["syscalls"] if dis2["syscalls"] else 0.0
        emit(
            f"inbound_gen2_b{B2}_syscalls_per_tick", dis2["syscalls"],
            f"inbound syscalls per pool tick, B={B2}, dispatch mode "
            f"(reference {ref2['syscalls']:.0f}/tick; {r2:.1f}x fewer; "
            f"dispatch p99 {dis2['p99']:.2f} ms vs reference "
            f"{ref2['p99']:.2f} ms)",
            r2 / 4.0,
        )


def run_decode_parallel() -> None:
    """Parallel slow-slot decode + GRO inbound A/B (DESIGN.md §24): the
    inbound_gen2 population — B matches over real loopback UDP, one
    external rollback-every-tick peer each, dispatch mode — with the two
    §24 axes toggled independently:

    * decode ``serial``  — the kill-switch posture (the reference
      ``_parse_slot`` path, bit-identical baseline), vs ``thread`` — the
      DecodePool fan-out (on a GIL build this prices the machinery
      honestly; the wall win needs free-threading or sub-interpreters).
    * GRO off (``GGRS_TPU_NO_GRO``) vs on — coalesced inbound trains
      split natively by ``ggrs_net_recv_table``; the syscall floor drops
      when the kernel actually coalesces.

    Reported: host-loop p99 per leg at B=512 (vs the 16.7 ms budget) and
    B=1024 (vs BENCH_r09's 32.0 ms dispatch baseline, target >=1.5x),
    inbound syscalls per tick GRO-on vs GRO-off, and the decode plane's
    engagement counters (fanned ticks, slow slots/tick, workers)."""
    import gc
    import random as _random

    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.core.config import Config
    from ggrs_tpu.net import _native
    from ggrs_tpu.net.sockets import DispatchHub, UdpNonBlockingSocket
    from ggrs_tpu.parallel import HostSessionPool
    from ggrs_tpu.sessions import SessionBuilder

    if os.environ.get("GGRS_TPU_NO_NATIVE") or _native.bank_lib() is None:
        print("# skip: decode_parallel needs the native toolchain",
              flush=True)
        return
    lib = _native.net_lib()
    if lib is None or not hasattr(lib, "ggrs_net_recv_table"):
        print("# skip: decode_parallel needs ggrs_net_recv_table",
              flush=True)
        return

    WARMUP = 12
    _ENV = ("GGRS_TPU_NO_PARALLEL_DECODE", "GGRS_TPU_DECODE_BACKEND",
            "GGRS_TPU_NO_GRO")

    def leg(decode: str, gro: bool, b: int, t: int):
        env = {}
        if decode == "serial":
            env["GGRS_TPU_NO_PARALLEL_DECODE"] = "1"
        else:
            env["GGRS_TPU_DECODE_BACKEND"] = decode
        if not gro:
            env["GGRS_TPU_NO_GRO"] = "1"
        saved = {k: os.environ.pop(k, None) for k in _ENV}
        os.environ.update(env)
        try:
            cfg = Config.for_uint(16)
            clock = [0]
            pool = HostSessionPool()
            hub = DispatchHub(siblings=1)
            peers = []
            for m in range(b):
                host_sock = hub.view()
                host_port = host_sock.local_port()
                peer_sock = UdpNonBlockingSocket(0)
                pool.add_session(
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(_random.Random(3 + 5 * m))
                    .add_player(Local(), 0)
                    .add_player(
                        Remote(("127.0.0.1", peer_sock.local_port())), 1
                    ),
                    host_sock,
                )
                peers.append(
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(_random.Random(4 + 5 * m))
                    .add_player(Local(), 1)
                    .add_player(Remote(("127.0.0.1", host_port)), 0)
                    .start_p2p_session(peer_sock)
                )
            if not pool.native_active:
                return None

            def fulfill(reqs):
                for r in reqs:
                    if type(r).__name__ == "SaveGameState":
                        r.cell.save(r.frame, None, None)

            host_ms = np.empty(t)

            def tick(i, record=None):
                clock[0] += 16
                for m, peer in enumerate(peers):
                    peer.add_local_input(1, (i + m) % 16)
                    fulfill(peer.advance_frame())
                t0 = time.perf_counter()
                pool.stage_inputs(
                    [(m, 0, (i + m) % 16) for m in range(b)]
                )
                plan = pool.advance_all()
                if record is not None:
                    host_ms[record] = (time.perf_counter() - t0) * 1e3
                for reqs in plan:
                    fulfill(reqs)

            def inbound_syscalls():
                io = pool.io_stats()
                return (io["recv_calls"] + io["drain"]["recv_calls"]
                        + hub.io_syscalls)

            enter_honest_timing_mode()
            for i in range(WARMUP):
                tick(i)
            s0 = inbound_syscalls()
            gc.collect()
            gc.freeze()
            best = None
            try:
                for rep in range(REPEATS):
                    for i in range(t):
                        tick(WARMUP + rep * t + i, record=i)
                    p99 = float(np.percentile(host_ms, 99))
                    if best is None or p99 < best[0]:
                        best = (p99, float(np.percentile(host_ms, 50)))
            finally:
                gc.unfreeze()
                gc.collect()
            s1 = inbound_syscalls()
            frames = [pool.current_frame(m) for m in range(b)]
            io = pool.io_stats()
            result = dict(
                p99=best[0],
                p50=best[1],
                syscalls=(s1 - s0) / (t * REPEATS),
                min_frame=min(frames),
                decode=io["decode"],
                gro_active=io["capabilities"]["gro_active"],
                gro_datagrams=io["drain"]["gro_datagrams"],
                gro_segments=io["drain"]["gro_segments"],
            )
            del pool
            hub.close()
            for peer in peers:
                peer._socket.close()
            return result
        finally:
            for k, v in saved.items():
                os.environ.pop(k, None)
                if v is not None:
                    os.environ[k] = v

    for b, t, baseline in ((256, 96, None), (512, 80, 16.7),
                           (1024, 48, 32.04)):
        legs = {
            "serial_nogro": leg("serial", False, b, t),
            "serial_gro": leg("serial", True, b, t),
            "thread_gro": leg("thread", True, b, t),
        }
        if any(v is None for v in legs.values()):
            print(f"# skip: decode_parallel B={b} leg did not engage",
                  flush=True)
            return
        for name, r in legs.items():
            assert r["min_frame"] > t - 32, f"a {name} B={b} match stalled"
        par = legs["thread_gro"]
        ser = legs["serial_gro"]
        off = legs["serial_nogro"]
        dec = par["decode"]
        assert dec["parallel_ticks"] > 0, "decode plane never fanned out"
        assert ser["decode"]["parallel_ticks"] == 0, "kill switch leaked"
        slots_tick = dec["jobs"] / max(1, dec["parallel_ticks"])
        gro_note = (
            f"{off['syscalls']:.0f} syscalls/tick gro-off vs "
            f"{ser['syscalls']:.0f} gro-on"
            + (f", {ser['gro_segments']}/{ser['gro_datagrams']} "
               f"segs/trains coalesced" if ser["gro_datagrams"] else
               ", kernel coalesced nothing on this run")
        )
        # headline per B: the best serving posture measured, with every
        # leg in the note — vs the 16.7 ms frame budget at B<=512 and vs
        # the r09 dispatch baseline (target >=1.5x better) at B=1024
        best_p99 = min(r["p99"] for r in legs.values())
        vs = ((baseline / 1.5) / best_p99 if b == 1024
              else (baseline or 16.7) / best_p99)
        emit(
            f"decode_parallel_b{b}_tick_ms_p99", best_p99,
            f"ms/tick p99, host loop, B={b} dispatch, best posture "
            f"(serial+gro {ser['p99']:.2f}, serial+nogro "
            f"{off['p99']:.2f}, thread+gro {par['p99']:.2f} ms; thread "
            f"leg fanned {dec['parallel_ticks']} ticks, "
            f"{slots_tick:.0f} slow slots/tick over {dec['workers']} "
            f"workers; {gro_note})",
            vs,
        )


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


def run_broadcast_fanout() -> None:
    """Broadcast fan-out capacity (DESIGN.md §13): one bank-hosted 2-peer
    match whose confirmed-input stream fans natively to N real
    ``SpectatorSession`` viewers, N in {8, 64}.  Reports the host's pool
    tick p99 (vs the 0-viewer pool as baseline — the fan-out must ride the
    existing crossing, so the ratio is the whole story) and wire bytes per
    viewer per tick."""
    from ggrs_tpu.net import _native

    if _native.broadcast_lib() is None:
        print("# skip: broadcast_fanout needs the native toolchain",
              flush=True)
        return

    import random as _random

    from ggrs_tpu.broadcast import SpectatorHub
    from ggrs_tpu.core import Local, Remote
    from ggrs_tpu.core.config import Config
    from ggrs_tpu.core.errors import NotSynchronized, PredictionThreshold
    from ggrs_tpu.core.types import Spectator
    from ggrs_tpu.net import InMemoryNetwork
    from ggrs_tpu.obs import Registry
    from ggrs_tpu.parallel.host_bank import HostSessionPool
    from ggrs_tpu.sessions import SessionBuilder

    TICKS = 400
    cfg = Config.for_uint(16)

    def measure(n_viewers: int):
        clock = [0]
        net = InMemoryNetwork()
        hb = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(_random.Random(1))
            .add_player(Local(), 0)
            .add_player(Remote("P"), 1)
        )
        for k in range(n_viewers):
            hb = hb.add_player(Spectator(f"V{k}"), 2 + k)
        peer = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(_random.Random(2))
            .add_player(Local(), 1)
            .add_player(Remote("H"), 0)
        ).start_p2p_session(net.socket("P"))
        viewers = [
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(_random.Random(10 + k))
            .start_spectator_session("H", net.socket(f"V{k}"))
            for k in range(n_viewers)
        ]
        registry = Registry()
        pool = HostSessionPool(metrics=registry)
        if n_viewers:
            SpectatorHub(pool, rng=_random.Random(3))
        pool.add_session(hb, net.socket("H"))
        assert pool.native_active

        def fulfill(reqs):
            for r in reqs:
                if type(r).__name__ == "SaveGameState":
                    r.cell.save(r.frame, None, None)

        samples = []
        for i in range(TICKS):
            clock[0] += 16
            peer.add_local_input(1, (i * 3) % 16)
            fulfill(peer.advance_frame())
            t0 = time.perf_counter()
            pool.add_local_input(0, 0, (i * 7) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            samples.append(time.perf_counter() - t0)
            for viewer in viewers:
                try:
                    viewer.advance_frame()
                except (NotSynchronized, PredictionThreshold):
                    pass
        p99 = float(np.percentile(np.asarray(samples) * 1e3, 99))
        fan_bytes = registry.value(
            "ggrs_fanout_bytes_total", slot="0"
        ) or 0.0
        per_viewer_tick = (
            fan_bytes / n_viewers / TICKS if n_viewers else 0.0
        )
        return p99, per_viewer_tick

    base_p99, _ = measure(0)
    for n in (8, 64):
        p99, bpv = measure(n)
        emit(f"broadcast_fanout{n}_tick_p99_ms", p99, "ms",
             p99 / base_p99 if base_p99 else 0.0)
        emit(f"broadcast_fanout{n}_bytes_per_viewer_tick", bpv,
             "bytes/viewer/tick", 1.0)


def _parse_child_lines(stdout: str) -> Tuple[list, bool]:
    """Extract the child's valid JSON metric lines (parsed) and whether a
    '# skip' marker appeared (a designed no-metric outcome)."""
    parsed = []
    skipped = False
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("# skip"):
            skipped = True  # a designed skip (e.g. pallas off-TPU)
        elif line.startswith("{"):
            try:
                parsed.append(json.loads(line))
            except ValueError:
                continue
    return parsed, skipped


def _forward_child_lines(name: str, parsed: list, skipped: bool) -> bool:
    """Print the child's already-parsed JSON metric lines; True if any were
    emitted (a '# skip' marker counts as an intentional no-metric outcome)."""
    for obj in parsed:
        print(json.dumps(obj), flush=True)
    if skipped and not parsed:
        sys.stderr.write(f"bench config {name!r} skipped by design\n")
    return bool(parsed) or skipped


def run_input_plane() -> None:
    """The input plane (DESIGN.md §27): B=256 pooled matches with fixed
    4-byte uint inputs vs variable-size RTS command records in the varrec
    envelope — host-loop tick p99 and wire bytes per tick.

    Both peers of every match live in ONE HostSessionPool (2B sessions)
    over one in-memory network whose delivery hook counts every payload
    byte; fulfillment is frame-as-state, so the number prices the host
    input/wire plane, not device fulfillment.  The varrec leg checks the
    §27 claim that variable-size records stay native-bank eligible (the
    unit string names native on/off per leg), and the byte accounting
    splits live payload bytes from envelope capacity — the headroom a
    length-aware wire codec could reclaim."""
    import random

    from ggrs_tpu.core import Config, Local, Remote
    from ggrs_tpu.games import RtsCmd, encode_commands
    from ggrs_tpu.net import InMemoryNetwork
    from ggrs_tpu.parallel import HostSessionPool
    from ggrs_tpu.sessions import SessionBuilder

    B = 256
    T = 300
    CYCLE = 64  # precomputed schedule window; rng stays out of the timing
    frame_budget_ms = 1000.0 / 60.0
    rts = RtsCmd(num_players=2, num_units=4, max_cmds=4)

    def _cmds(rng) -> tuple:
        cmds = []
        for _ in range(rng.randrange(0, 4)):
            kind = rng.randrange(3)
            if kind == 0:
                cmds.append(("move", rng.randrange(4),
                             rng.randrange(-2, 3), rng.randrange(-2, 3)))
            elif kind == 1:
                cmds.append(("gather", rng.randrange(4)))
            else:
                cmds.append(("build", rng.randrange(16), rng.randrange(16)))
        return tuple(cmds)

    def leg(kind: str):
        wire = [0]
        net = InMemoryNetwork()
        orig_send = net._send

        def counted(src, dst, payload):
            wire[0] += len(payload)
            orig_send(src, dst, payload)

        net._send = counted
        host = HostSessionPool()
        for m in range(B):
            names = (f"A{m}", f"B{m}")
            for me in (0, 1):
                cfg = Config.for_uint(32) if kind == "fixed4" else rts.config()
                b = (
                    SessionBuilder(cfg)
                    .with_clock(lambda: 0)
                    .with_rng(random.Random(3 + 5 * m + me))
                    .add_player(Local(), me)
                    .add_player(Remote(names[1 - me]), 1 - me)
                )
                host.add_session(b, net.socket(names[me]))
        n = len(host)
        state = [0] * n

        # per-session CYCLE-long schedules, plus the live payload bytes each
        # tick of the cycle contributes (pre-envelope — what the game sent)
        if kind == "fixed4":
            sched = [
                [((i + h) * 2654435761) & 0xFFFFFFFF for i in range(CYCLE)]
                for h in range(n)
            ]
            pay_per_tick = 4.0 * n
        else:
            sched = [
                [_cmds(random.Random(17 + h * 613 + i)) for i in range(CYCLE)]
                for h in range(n)
            ]
            pay_per_tick = (
                sum(
                    len(encode_commands(c)) for row in sched for c in row
                ) / CYCLE
            )

        def tick(i: int) -> float:
            j = i % CYCLE
            t0 = time.perf_counter()
            for h in range(n):
                host.add_local_input(h, h & 1, sched[h][j])
            for h, reqs in enumerate(host.advance_all()):
                for r in reqs:
                    k = type(r).__name__
                    if k == "SaveGameState":
                        r.cell.save(r.frame, state[h], None)
                    elif k == "LoadGameState":
                        state[h] = r.cell.data()
            return (time.perf_counter() - t0) * 1e3

        for i in range(16):  # pipeline fill
            tick(i)
        enter_honest_timing_mode()
        best = None
        base = 16
        for _ in range(REPEATS):
            wire[0] = 0
            ms = np.empty(T)
            for i in range(T):
                ms[i] = tick(base + i)
            base += T
            p50 = float(np.percentile(ms, 50))
            p99 = float(np.percentile(ms, 99))
            if best is None or p99 < best[0]:
                best = (p99, p50, wire[0] / T)
        return best, pay_per_tick, host.native_active

    (fp99, fp50, fwire), fpay, f_native = leg("fixed4")
    (vp99, vp50, vwire), vpay, v_native = leg("varrec")
    env = rts.config().native_input_size  # [u16 len][payload][pad]

    emit(
        "input_plane_fixed4_b256_tick_ms_p99", fp99,
        f"ms/tick p99, host loop, B={B} matches ({2 * B} pooled sessions), "
        f"4-byte uint inputs, native {'on' if f_native else 'OFF'} "
        f"(p50 {fp50:.2f} ms)",
        frame_budget_ms / fp99 if fp99 else 0.0,
    )
    emit(
        "input_plane_varrec_b256_tick_ms_p99", vp99,
        f"ms/tick p99, host loop, B={B} matches, RTS command records in the "
        f"{env}-byte varrec envelope, native {'on' if v_native else 'OFF'} "
        f"(p50 {vp50:.2f} ms; fixed-4 leg {fp99:.2f} ms, "
        f"{vp99 / fp99 if fp99 else 0.0:.2f}x)",
        frame_budget_ms / vp99 if vp99 else 0.0,
    )
    emit(
        "input_plane_varrec_wire_bytes_per_tick", vwire,
        f"bytes/tick on the wire, B={B} ({vwire / B:.0f} B/match/tick; live "
        f"payload {vpay:.0f} B/tick = {vpay / vwire if vwire else 0.0:.1%} "
        f"of wire — the rest is the fixed {env}-byte envelope + protocol "
        f"framing; fixed-4 leg {fwire:.0f} B/tick)",
        fwire / vwire if vwire else 0.0,
    )


def orchestrate() -> None:
    """Run each selected config in its own subprocess.  The flagship child
    runs FIRST and its metric lines are printed THE MOMENT it completes
    (VERDICT r5 item 1: a driver capture window must never close on an
    empty stream), then re-printed at the end so the final line stays the
    headline.  The default selection is the COMPACT subset; GGRS_BENCH_FULL=1
    restores the full suite.
    A child that dies or times out costs its own line only.  Exits nonzero
    if NO config produced a metric (total failure must not read as a clean
    run to a driver that records the exit status)."""
    here = os.path.abspath(__file__)
    if os.environ.get("GGRS_BENCH_FULL"):
        names = list(CONFIGS)
        total_budget = float(
            os.environ.get("GGRS_BENCH_TOTAL_BUDGET") or "inf"
        )
    else:
        names = [n for n in CONFIGS if n in COMPACT_CONFIGS]
        total_budget = float(
            os.environ.get("GGRS_BENCH_TOTAL_BUDGET")
            or DEFAULT_TOTAL_BUDGET_S
        )
    only = os.environ.get("GGRS_BENCH_ONLY")
    if only:  # comma-separated subset, e.g. GGRS_BENCH_ONLY=flagship,ecs
        sel = {s.strip() for s in only.split(",") if s.strip()}
        unknown = sel - set(CONFIGS)  # any config selectable, not just compact
        if unknown or not sel:
            sys.stderr.write(
                f"GGRS_BENCH_ONLY: unknown configs {unknown or only!r}; "
                f"one of {list(CONFIGS)}\n"
            )
            raise SystemExit(2)
        names = [n for n in CONFIGS if n in sel]
    run_order = (["flagship"] if "flagship" in names else []) + [
        n for n in names if n != "flagship"
    ]
    deadline = time.monotonic() + total_budget

    def run_child(name: str) -> Tuple[str, str, str]:
        """Returns (stdout, failure_note, stderr_tail); failure_note is ""
        on a clean exit, else a one-line diagnosis (timeout or nonzero rc).

        STREAMING (the BENCH_r05 rc=124/empty-tail fix): the child's
        stdout is polled twice a second and every complete metric line is
        forwarded to OUR stdout the moment the child prints it — a driver
        that kills the orchestrator mid-child still has every measurement
        taken so far on its capture.  The child's budget is additionally
        clamped to the orchestrator's remaining total deadline, so the
        suite can never outlive its window with nothing printed.

        Child output goes to temp FILES, not pipes: a file keeps whatever
        the child printed before it hung — so a measurement that completed
        and then stalled in tunnel teardown is still salvaged.  Files are
        binary and decoded with errors='replace': a child SIGKILLed
        mid-write must not take the rest of the suite down with a
        UnicodeDecodeError."""
        import tempfile

        spec = CONFIGS[name]
        budget = min(spec[1], max(0.0, deadline - time.monotonic()))
        env = None
        if len(spec) > 2 and spec[2]:
            env = dict(os.environ)
            env.update(spec[2])
        with tempfile.TemporaryFile() as out_f, tempfile.TemporaryFile() as err_f:
            proc = subprocess.Popen(
                [sys.executable, here, name],
                stdout=out_f,
                stderr=err_f,
                cwd=os.path.dirname(here),
                env=env,
            )
            start = time.monotonic()
            streamed = 0  # bytes of the child's stdout already scanned
            pending = b""
            out_fd = out_f.fileno()

            def forward_new() -> None:
                """Scan from the last offset, print complete metric
                lines immediately (partial trailing line waits).
                os.pread, NOT seek+read: the child's stdout fd shares
                this open file description, so seeking here would move
                the offset the child writes at mid-run and corrupt its
                own stream."""
                nonlocal streamed, pending
                while True:
                    chunk = os.pread(out_fd, 1 << 16, streamed)
                    if not chunk:
                        break
                    streamed += len(chunk)
                    pending += chunk
                while b"\n" in pending:
                    line, pending = pending.split(b"\n", 1)
                    text = line.decode(errors="replace").strip()
                    if not text.startswith("{"):
                        continue
                    try:
                        json.loads(text)
                    except json.JSONDecodeError:
                        continue
                    print(text, flush=True)

            note = ""
            while True:
                forward_new()
                if proc.poll() is not None:
                    break
                if time.monotonic() - start > budget:
                    proc.kill()
                    proc.wait()
                    note = f"exceeded its {budget:.0f}s budget"
                    break
                time.sleep(0.5)
            forward_new()
            if not note and proc.returncode not in (0, None):
                note = f"exited rc={proc.returncode}"
            out_f.seek(0)
            err_f.seek(0)
            out = out_f.read().decode(errors="replace")
            err_tail = err_f.read()[-2000:].decode(errors="replace")
            return out, note, err_tail

    def report(name: str, out: str, note: str, err_tail: str) -> bool:
        """Surface every failure note (the metric lines already streamed
        to stdout while the child ran), with the child's stderr tail
        whenever something needs diagnosing."""
        parsed, skipped = parsed_by_name[name]
        ok = bool(parsed) or skipped
        if skipped and not parsed:
            sys.stderr.write(f"bench config {name!r} skipped by design\n")
        if note:
            salvage = " (metric salvaged from partial output)" if parsed \
                else ""
            sys.stderr.write(
                f"bench config {name!r} {note}{salvage}; stderr tail:\n"
                f"{err_tail}\n"
            )
        elif not ok:
            sys.stderr.write(
                f"bench config {name!r} produced no metric (rc=0); "
                f"stderr tail:\n{err_tail}\n"
            )
        return ok

    def write_artifact(results: dict, parsed_by_name: dict) -> list:
        """Write bench_out/latest.json from what has completed SO FAR and
        return the metric list.  Called after every config: the round-5
        config list runs for tens of minutes, and a driver that kills the
        orchestrator mid-run must still find every completed config's
        metrics in the artifact."""
        all_metrics = []
        for name in names:  # print order, flagship last
            if name in results:
                all_metrics.extend(parsed_by_name[name][0])
        if not all_metrics:
            return all_metrics
        artifact = {
            "schema": "ggrs_tpu bench full stream v1",
            "time_unix": int(time.time()),
            "configs_run": [n for n in names if n in results],
            "configs_pending": [n for n in names if n not in results],
            "metrics": all_metrics,
        }
        out_dir = os.path.join(os.path.dirname(here), "bench_out")
        try:
            os.makedirs(out_dir, exist_ok=True)
            tmp = os.path.join(out_dir, f".latest.{os.getpid()}.tmp")
            with open(tmp, "w") as f:
                json.dump(artifact, f, indent=1)
            os.replace(tmp, os.path.join(out_dir, "latest.json"))
        except OSError as e:  # the final print still carries the full list
            sys.stderr.write(f"bench_out/latest.json not written: {e}\n")
        return all_metrics

    any_metric = False
    all_metrics: list = []
    flagship_result: Optional[Tuple[str, str, str]] = None
    results: dict = {}
    parsed_by_name: dict = {}  # name -> (parsed metric objs, skipped flag)
    for name in run_order:
        remaining = deadline - time.monotonic()
        if remaining < 10:
            # no silent caps: a config that does not fit the window is
            # skipped LOUDLY, and the already-streamed metrics stand
            sys.stderr.write(
                f"bench config {name!r} SKIPPED: {max(0, remaining):.0f}s "
                f"left of the {total_budget:.0f}s total budget "
                "(GGRS_BENCH_TOTAL_BUDGET)\n"
            )
            continue
        result = run_child(name)
        results[name] = result
        parsed_by_name[name] = _parse_child_lines(result[0])
        # EVERY config (the flagship included) reports the moment its child
        # completes: a driver that kills the orchestrator mid-run, or whose
        # capture window closes early, still has the headline on stdout.
        # The flagship's lines are re-printed at the very end so the final
        # line keeps its headline semantics.
        if name == "flagship":
            flagship_result = result
        any_metric |= report(name, *result)
        all_metrics = write_artifact(results, parsed_by_name)

    # Canonical self-contained artifact (VERDICT r4 item 7): the driver's
    # recorded BENCH file keeps only the tail of stdout, so earlier configs'
    # metrics used to survive only in prose.  The artifact was refreshed
    # after every config above (all_metrics holds the final refresh); print
    # the complete list as one schema-shaped line right before the
    # flagship, so a tail capture of the last two lines is still the whole
    # run.
    if all_metrics:  # a total-failure run must not leave a valid metric line
        print(
            json.dumps(
                {
                    "metric": "bench_full_stream",
                    "value": len(all_metrics),
                    "unit": "metrics (complete list under 'metrics'; also "
                            "bench_out/latest.json)",
                    "vs_baseline": 1.0,
                    "metrics": all_metrics,
                }
            ),
            flush=True,
        )

    if flagship_result is not None:
        # re-print (no duplicate stderr note): the last line is the headline
        _forward_child_lines("flagship", *parsed_by_name["flagship"])
    if not any_metric:
        raise SystemExit(1)


def main(argv: list) -> None:
    # the container's sitecustomize force-registers the tunneled TPU and
    # overrides JAX_PLATFORMS at interpreter start; selecting a different
    # backend (the CPU-dispatch speculation child) must go through jax
    # config, before any computation
    forced = os.environ.get("GGRS_BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
    if len(argv) > 1:
        name = argv[1]
        if name not in CONFIGS:
            sys.stderr.write(
                f"unknown bench config {name!r}; one of {list(CONFIGS)}\n"
            )
            raise SystemExit(2)
        globals()[CONFIGS[name][0]]()
    else:
        orchestrate()


if __name__ == "__main__":
    main(sys.argv)
