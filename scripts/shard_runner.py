#!/usr/bin/env python
"""Fleet shard runner: one ``PoolShard`` serving loop as a real OS
process (DESIGN.md §17).

Spawned by ``ShardSupervisor`` (socketpair fd handed down via ``--fd``)
or started standalone for the supervisor to ADOPT over a UNIX socket:

  python scripts/shard_runner.py --uds /run/ggrs/shard0.sock

The process speaks the length-prefixed, crc32-checked frame protocol of
``ggrs_tpu.fleet.rpc``; everything else (hello/tick/admit/adopt/evict
ops, heartbeats, the SIGTERM graceful drain that leaves journals durable
before the final GOODBYE) lives in ``ggrs_tpu.fleet.proc.ShardRunner``
so the loop is importable and testable in-process too.

Exit code 0 = drained (signal or supervisor-requested shutdown);
1 = the supervisor vanished or the control stream was poisoned.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ggrs_tpu.fleet.proc import runner_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(runner_main())
