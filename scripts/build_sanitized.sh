#!/usr/bin/env bash
# Build the native cores with AddressSanitizer + UBSan and run the session
# bank's parity and fault fuzzes under them.
#
# The sanitized library lives beside the production one as
# _ggrs_codec_san.so; GGRS_NATIVE_SANITIZE=1 makes ggrs_tpu.net._native load
# (and, when stale, rebuild) that library with
# -fsanitize=address,undefined -fno-sanitize-recover=all, so any native
# heap/UB bug aborts the test run loudly instead of corrupting the bank.
# ASan must be loaded before Python, hence the LD_PRELOAD.
#
# Usage: scripts/build_sanitized.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

if ! command -v g++ >/dev/null; then
    echo "skip: no g++ toolchain" >&2
    exit 0
fi
asan_rt="$(g++ -print-file-name=libasan.so)"
if [ ! -e "$asan_rt" ]; then
    echo "skip: g++ has no libasan runtime" >&2
    exit 0
fi

out=ggrs_tpu/net/_ggrs_codec_san.so
echo "building sanitized native cores -> $out"
g++ -O1 -g -shared -fPIC -std=c++17 \
    -fsanitize=address,undefined -fno-sanitize-recover=all \
    -o "$out" \
    native/codec.cpp native/endpoint.cpp native/sync_core.cpp \
    native/session_bank.cpp native/net_batch.cpp

# detect_leaks=0: CPython itself "leaks" interned objects at exit, which is
# noise here — the target is heap corruption / UB in the native cores while
# the parity fuzz and the chaos tests drive them.
#
# The -k filter keeps the sanitized leg on the HOST-only tests: the
# batched-executor integration tests JIT through XLA, whose own compiler
# trips ASan's interceptors (an upstream finding, not ours) and aborts the
# run before the bank code under test even executes; the fused-scrub
# replay test JITs too.  The slow soak is excluded by default; pass
# "-m" "slow" to run it sanitized too.
# tests/test_fleet_proc.py is included: its shard-runner children
# inherit LD_PRELOAD/GGRS_NATIVE_SANITIZE, so the out-of-process serving
# loop exercises the SANITIZED native bank in the subprocess too.
LD_PRELOAD="$asan_rt" \
ASAN_OPTIONS="detect_leaks=0:abort_on_error=1" \
GGRS_NATIVE_SANITIZE=1 \
JAX_PLATFORMS=cpu \
python -m pytest tests/test_session_bank.py tests/test_policy_plane.py \
    tests/test_bank_faults.py \
    tests/test_obs.py tests/test_broadcast.py tests/test_replay_journal.py \
    tests/test_trace.py tests/test_desync_detection.py \
    tests/test_native_io.py tests/test_socket_datapath.py \
    tests/test_fleet.py tests/test_fleet_rpc.py tests/test_fleet_proc.py \
    tests/test_fleet_obs.py \
    -q -p no:cacheprovider -m "not slow" \
    -k "not batched_executor and not size_mismatch and not fused_scrub and not scrub_matches" "$@"
