#!/usr/bin/env bash
# Static analysis + sanitized native legs — the correctness gate for the
# crossing (DESIGN.md §20 for the static plane, §9/§15 for the dynamic).
#
# 1. ggrs-verify: the static-analysis plane (cross-language layout
#    checker, determinism lint vs its committed baseline, ownership
#    lint, tree hygiene).  Runs first and cheapest; layout drift or a
#    new determinism violation fails the build before anything compiles.
# 2. ASan+UBSan leg: builds _ggrs_codec_san.so
#    (-fsanitize=address,undefined -fno-sanitize-recover=all) and runs
#    the bank parity/fault fuzzes under it, so any native heap/UB bug
#    aborts the run loudly instead of corrupting the bank.  ASan must be
#    loaded before Python, hence the LD_PRELOAD.
# 3. TSan leg: builds _ggrs_codec_tsan.so (-fsanitize=thread) and runs
#    the tests that drive the GIL-released native I/O threads
#    (ggrs_bank_pump's recvmmsg/sendmmsg ring, the out-of-process
#    runner's serving loop).  Only the native library is instrumented,
#    so reports are races in OUR code, not CPython noise.
#
# Usage: scripts/build_sanitized.sh [extra pytest args]
#   GGRS_SKIP_VERIFY=1  skip the static gate (sanitizers only)
#   GGRS_SKIP_MODEL=1   skip the model-exploration leg (static only)
#   GGRS_SKIP_TSAN=1    skip the TSan leg (ASan only)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== ggrs-verify (static analysis plane) ==="
if [ -z "${GGRS_SKIP_VERIFY:-}" ]; then
    JAX_PLATFORMS=cpu python scripts/ggrs_verify.py
else
    echo "skipped (GGRS_SKIP_VERIFY)"
fi

# Model-exploration leg (DESIGN.md §22): breadth-first exploration of
# the §9/§16/§17 protocol machines.  HEAD models must be
# invariant-clean; the known-broken fixtures (pre-PR-11 checkpoint
# ordering, barrier-less journal, threshold-1 rebase, premature
# failover) must keep their pinned shortest counterexamples.  The whole
# catalog runs in well under the 60s wall budget — ggrs_verify prints
# the states/elapsed budget line for the record.
echo "=== ggrs-model (protocol model exploration) ==="
if [ -z "${GGRS_SKIP_MODEL:-}" ] && [ -z "${GGRS_SKIP_VERIFY:-}" ]; then
    JAX_PLATFORMS=cpu timeout -k 10 60 \
        python scripts/ggrs_verify.py --model --no-runtime
else
    echo "skipped (GGRS_SKIP_MODEL / GGRS_SKIP_VERIFY)"
fi

if ! command -v g++ >/dev/null; then
    echo "skip: no g++ toolchain" >&2
    exit 0
fi
asan_rt="$(g++ -print-file-name=libasan.so)"
if [ ! -e "$asan_rt" ]; then
    echo "skip: g++ has no libasan runtime" >&2
    exit 0
fi

out=ggrs_tpu/net/_ggrs_codec_san.so
echo "=== ASan+UBSan leg: building $out ==="
g++ -O1 -g -shared -fPIC -std=c++17 \
    -fsanitize=address,undefined -fno-sanitize-recover=all \
    -o "$out" \
    native/codec.cpp native/endpoint.cpp native/sync_core.cpp \
    native/session_bank.cpp native/net_batch.cpp

# detect_leaks=0: CPython itself "leaks" interned objects at exit, which is
# noise here — the target is heap corruption / UB in the native cores while
# the parity fuzz and the chaos tests drive them.
#
# The -k filter keeps the sanitized leg on the HOST-only tests: the
# batched-executor integration tests JIT through XLA, whose own compiler
# trips ASan's interceptors (an upstream finding, not ours) and aborts the
# run before the bank code under test even executes; the fused-scrub
# replay test JITs too.  The slow soak is excluded by default; pass
# "-m" "slow" to run it sanitized too.
# tests/test_fleet_proc.py is included: its shard-runner children
# inherit LD_PRELOAD/GGRS_NATIVE_SANITIZE, so the out-of-process serving
# loop exercises the SANITIZED native bank in the subprocess too.
LD_PRELOAD="$asan_rt" \
ASAN_OPTIONS="detect_leaks=0:abort_on_error=1" \
GGRS_NATIVE_SANITIZE=1 \
JAX_PLATFORMS=cpu \
python -m pytest tests/test_session_bank.py tests/test_policy_plane.py \
    tests/test_descriptor_plane.py \
    tests/test_bank_faults.py \
    tests/test_obs.py tests/test_broadcast.py tests/test_replay_journal.py \
    tests/test_trace.py tests/test_desync_detection.py \
    tests/test_native_io.py tests/test_socket_datapath.py \
    tests/test_net_gen2.py tests/test_decode_parallel.py \
    tests/test_fleet.py tests/test_fleet_rpc.py tests/test_fleet_proc.py \
    tests/test_fleet_link.py tests/test_fleet_obs.py \
    tests/test_ingress.py tests/test_placement.py \
    tests/test_input_plane.py \
    tests/test_timeline_slo.py \
    -q -p no:cacheprovider -m "not slow" \
    -k "not batched_executor and not size_mismatch and not fused_scrub and not scrub_matches and not device_state_bit_identical and not reaches_the_device and not plane_on_off and not plane_parity and not b64_plane and not jax_advance" "$@"

if [ -n "${GGRS_SKIP_TSAN:-}" ]; then
    echo "TSan leg skipped (GGRS_SKIP_TSAN)"
    exit 0
fi
tsan_rt="$(g++ -print-file-name=libtsan.so)"
if [ ! -e "$tsan_rt" ]; then
    echo "skip: g++ has no libtsan runtime" >&2
    exit 0
fi

out=ggrs_tpu/net/_ggrs_codec_tsan.so
echo "=== TSan leg: building $out ==="
g++ -O1 -g -shared -fPIC -std=c++17 -fsanitize=thread \
    -o "$out" \
    native/codec.cpp native/endpoint.cpp native/sync_core.cpp \
    native/session_bank.cpp native/net_batch.cpp

# The TSan leg targets the concurrency surface: the kernel-batched
# socket datapath (GIL released around recvmmsg/sendmmsg), the
# thread-ownership guard, and the subprocess shard runner (children
# inherit the preload and GGRS_NATIVE_SANITIZE=thread, so the runner's
# serving loop drives the TSan bank too).  halt_on_error aborts the
# run on the first race; second_deadlock_stack improves lock reports.
# GGRS_TPU_DECODE_BACKEND=thread forces the §24 decode plane onto real
# worker threads here, so its fan-out/merge runs under TSan even on
# builds where the runtime default would resolve serial.
LD_PRELOAD="$tsan_rt" \
TSAN_OPTIONS="halt_on_error=1:second_deadlock_stack=1" \
GGRS_NATIVE_SANITIZE=thread \
GGRS_TPU_DECODE_BACKEND=thread \
JAX_PLATFORMS=cpu \
python -m pytest tests/test_native_io.py tests/test_socket_datapath.py \
    tests/test_net_gen2.py tests/test_decode_parallel.py \
    tests/test_thread_ownership.py tests/test_fleet_proc.py \
    tests/test_fleet_link.py tests/test_descriptor_plane.py \
    tests/test_ingress.py tests/test_placement.py \
    tests/test_input_plane.py \
    tests/test_timeline_slo.py \
    -q -p no:cacheprovider -m "not slow" \
    -k "not batched_executor and not size_mismatch and not device_state_bit_identical and not reaches_the_device and not plane_on_off and not plane_parity and not b64_plane and not jax_advance" "$@"

echo "sanitized legs green (ASan+UBSan, TSan) + ggrs-verify"
