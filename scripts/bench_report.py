#!/usr/bin/env python
"""bench_report: the bench-trajectory table and regression gate
(DESIGN.md §28).

Every bench round drops a ``BENCH_rNN.json`` next to the repo docs:
``{"n": round, "cmd": [...], "rc": exit, "tail": "...", "note": "..."}``
where ``tail`` holds the run's stdout tail and each metric is one
JSON line inside it::

    {"metric": "host_bank_io_b64_tick_ms_p99", "value": 6.1,
     "unit": "ms/tick ...", "vs_baseline": 1.12}

This script normalizes those lines across all rounds into flat records
— the **normalized record schema**::

    {"round": 6,            # the file's round number (its "n")
     "metric": "...",       # the stable metric name (the join key)
     "value": 6.1,          # the reported scalar
     "unit": "...",         # free-text unit/context string
     "vs_baseline": 1.12,   # the round's own baseline ratio
     "p99": true,           # name ends in _p99 -> latency, lower-better
     "rc": 0}               # the round's exit code

— prints the per-metric trajectory (every round the metric appeared
in, oldest first, with the step-over-step delta), and **gates**: for
each ``_p99`` metric in its LATEST round, compare against the BEST
(minimum — p99s are lower-better) value from any PRIOR round reporting
the same metric name (same name = same workload = comparable).  A
latest value more than ``--threshold`` (default 15%) above that best
prior exits 1 — the CI tripwire against quietly regressing a bench a
previous PR fought for.

Rounds with ``rc != 0`` (e.g. r05's rc=124 timeout) carry no metric
lines; they are listed as data-less, never treated as regressions.

Usage:
  python scripts/bench_report.py                 # repo-root BENCH_r*.json
  python scripts/bench_report.py --dir . --threshold 0.10
  python scripts/bench_report.py --json          # machine-readable dump
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def parse_round(path: str) -> Dict[str, Any]:
    """One file -> {"round", "rc", "records": [normalized records]}."""
    with open(path) as f:
        doc = json.load(f)
    m = _ROUND_RE.search(os.path.basename(path))
    rnd = int(doc.get("n", int(m.group(1)) if m else 0))
    rc = int(doc.get("rc", 0))
    records: List[Dict[str, Any]] = []
    for line in (doc.get("tail") or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict) or "metric" not in rec:
            continue
        records.append({
            "round": rnd,
            "metric": str(rec["metric"]),
            "value": float(rec.get("value", 0.0)),
            "unit": str(rec.get("unit", "")),
            "vs_baseline": rec.get("vs_baseline"),
            "p99": str(rec["metric"]).endswith("_p99"),
            "rc": rc,
        })
    # some rounds also carry one pre-parsed record; fold it in when the
    # tail didn't already (dedup by name keeps the tail's fresher value)
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        if not any(r["metric"] == parsed["metric"] for r in records):
            records.append({
                "round": rnd,
                "metric": str(parsed["metric"]),
                "value": float(parsed.get("value", 0.0)),
                "unit": str(parsed.get("unit", "")),
                "vs_baseline": parsed.get("vs_baseline"),
                "p99": str(parsed["metric"]).endswith("_p99"),
                "rc": rc,
            })
    return {"round": rnd, "rc": rc, "path": path, "records": records}


def load_rounds(directory: str) -> List[Dict[str, Any]]:
    paths = sorted(glob.glob(os.path.join(directory, "BENCH_r*.json")))
    rounds = [parse_round(p) for p in paths]
    rounds.sort(key=lambda r: r["round"])
    return rounds


def trajectory(rounds: List[Dict[str, Any]]
               ) -> Dict[str, List[Dict[str, Any]]]:
    """Per metric: its records oldest-round-first (one per round — the
    LAST occurrence in a round wins, it is the leg the round shipped)."""
    by_metric: Dict[str, Dict[int, Dict[str, Any]]] = {}
    for rnd in rounds:
        for rec in rnd["records"]:
            by_metric.setdefault(rec["metric"], {})[rec["round"]] = rec
    return {
        m: [recs[r] for r in sorted(recs)]
        for m, recs in sorted(by_metric.items())
    }


def gate(traj: Dict[str, List[Dict[str, Any]]],
         threshold: float = 0.15) -> List[Dict[str, Any]]:
    """The regressions: p99 metrics whose latest value exceeds the best
    prior round's by more than ``threshold`` (fractional)."""
    regressions = []
    for metric, recs in traj.items():
        if not recs or not recs[-1]["p99"] or len(recs) < 2:
            continue
        latest = recs[-1]
        best_prior = min(recs[:-1], key=lambda r: r["value"])
        if best_prior["value"] <= 0:
            continue
        ratio = latest["value"] / best_prior["value"]
        if ratio > 1.0 + threshold:
            regressions.append({
                "metric": metric,
                "latest_round": latest["round"],
                "latest_value": latest["value"],
                "best_prior_round": best_prior["round"],
                "best_prior_value": best_prior["value"],
                "ratio": ratio,
            })
    return regressions


def render(rounds: List[Dict[str, Any]],
           traj: Dict[str, List[Dict[str, Any]]],
           regressions: List[Dict[str, Any]],
           threshold: float) -> str:
    lines: List[str] = []
    lines.append(f"bench trajectory — {len(rounds)} rounds, "
                 f"{len(traj)} metrics")
    dataless = [r for r in rounds if not r["records"]]
    for r in dataless:
        lines.append(f"  r{r['round']:02d}: no metrics "
                     f"(rc={r['rc']}{', timeout' if r['rc'] == 124 else ''})")
    lines.append("")
    for metric, recs in traj.items():
        tag = " [p99]" if recs[-1]["p99"] else ""
        lines.append(f"{metric}{tag}")
        prev: Optional[float] = None
        for rec in recs:
            delta = ""
            if prev is not None and prev > 0:
                pct = 100.0 * (rec["value"] - prev) / prev
                delta = f"  ({pct:+.1f}%)"
            vs = (f"  vs_baseline={rec['vs_baseline']}"
                  if rec.get("vs_baseline") is not None else "")
            lines.append(f"  r{rec['round']:02d}  "
                         f"{rec['value']:>14.3f}{delta}{vs}")
            prev = rec["value"]
        lines.append("")
    if regressions:
        lines.append(f"GATE: {len(regressions)} p99 regression(s) "
                     f"beyond {threshold:.0%} vs best prior round:")
        for reg in regressions:
            lines.append(
                f"  {reg['metric']}: r{reg['latest_round']:02d}="
                f"{reg['latest_value']:.3f} vs best "
                f"r{reg['best_prior_round']:02d}="
                f"{reg['best_prior_value']:.3f} "
                f"({(reg['ratio'] - 1) * 100:+.1f}%)"
            )
    else:
        lines.append(f"GATE: ok — no p99 metric regressed beyond "
                     f"{threshold:.0%} of its best prior round")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    default_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap.add_argument("--dir", default=default_dir,
                    help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="fractional p99 regression tolerance (default 0.15)")
    ap.add_argument("--json", action="store_true",
                    help="dump normalized records + verdict as JSON")
    args = ap.parse_args()
    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"bench_report: no BENCH_r*.json under {args.dir}",
              file=sys.stderr)
        return 2
    traj = trajectory(rounds)
    regressions = gate(traj, args.threshold)
    if args.json:
        print(json.dumps({
            "rounds": [{"round": r["round"], "rc": r["rc"],
                        "records": r["records"]} for r in rounds],
            "regressions": regressions,
            "threshold": args.threshold,
            "ok": not regressions,
        }, indent=1))
    else:
        print(render(rounds, traj, regressions, args.threshold))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
