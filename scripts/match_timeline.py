#!/usr/bin/env python
"""match_timeline: render one match's merged cross-host lifecycle
timeline (DESIGN.md §28).

Sources, freely mixed:

- ``--url BASE`` (repeatable) — a live obs endpoint serving merged
  timelines on ``/timeline`` (the supervisor's ``start_http_server``
  with ``timelines=``); one URL per host stitches a cross-host view.
- ``--artifact FILE`` (repeatable) — JSON artifacts: a raw
  ``{mid: [events]}`` export (``TimelineStore.to_dict``), a chaos
  artifact embedding a ``"timeline"``/``"timelines"`` section, or a
  ``DesyncReport`` dict whose ``"timeline"`` list is the match's life
  up to the desync.

Ingress nodes never learn match ids — they emit ROUTE_FLIP events keyed
``trace:<hex>`` on the 16-byte wire trace context.  Merging folds those
into the real match whose ``match_trace_id`` equals the hex (the whole
point of putting the hash on the wire), so a flip observed at the edge
lands inside the match's causal chain.

Usage:
  python scripts/match_timeline.py --url http://127.0.0.1:9464 --list
  python scripts/match_timeline.py --url http://h0:9464 --url http://h1:9464 -m m3
  python scripts/match_timeline.py --artifact chaos_net.json -m m0 \
      --perfetto m0.trace.json
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from pathlib import Path
from typing import Any, Dict, List

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ggrs_tpu.obs.timeline import (  # noqa: E402
    fold_trace_aliases, format_timeline, match_trace_id, merge_timelines,
    timeline_ring_events,
)
from ggrs_tpu.obs.trace import Tracer, validate_chrome_trace  # noqa: E402

Timelines = Dict[str, List[Dict[str, Any]]]


def _extract_timelines(doc: Any) -> List[Timelines]:
    """Every ``{mid: [events]}`` mapping findable in an artifact: the
    document itself, any ``timeline``/``timelines``/``merged_timeline``
    member (dict form), or a DesyncReport-style ``timeline`` list."""
    found: List[Timelines] = []
    if not isinstance(doc, dict):
        return found
    values = list(doc.values())
    if values and all(isinstance(v, list) for v in values) and any(
        isinstance(e, dict) and "ev" in e for v in values for e in v
    ):
        found.append(doc)  # already {mid: [events]}
        return found
    for key in ("timeline", "timelines", "merged_timeline"):
        sub = doc.get(key)
        if isinstance(sub, dict):
            found.extend(_extract_timelines(sub))
        elif isinstance(sub, list) and sub and isinstance(sub[0], dict):
            mid = str(sub[0].get("mid", doc.get("match_id", "?")))
            found.append({mid: sub})
    # recurse one level into nested sections (chaos artifacts nest the
    # timeline under a leg/report key)
    for v in values:
        if isinstance(v, dict) and any(
            k in v for k in ("timeline", "timelines", "merged_timeline")
        ):
            found.extend(_extract_timelines(v))
    return found


def load_sources(urls: List[str], artifacts: List[str]) -> Timelines:
    sources: List[Timelines] = []
    for base in urls:
        with urllib.request.urlopen(base.rstrip("/") + "/timeline",
                                    timeout=5.0) as r:
            sources.append(json.loads(r.read().decode()))
    for path in artifacts:
        with open(path) as f:
            doc = json.load(f)
        sources.extend(_extract_timelines(doc))
    return fold_trace_aliases(merge_timelines(*sources))


def export_perfetto(events: List[Dict[str, Any]], path: str) -> List[str]:
    """Write the match's events as a Chrome/Perfetto trace (instant
    phase on the shared ``timeline`` category) and return validation
    problems (empty = the export loads in ui.perfetto.dev)."""
    tracer = Tracer(capacity=max(len(events) + 16, 256))
    tracer.import_spans(timeline_ring_events(events))
    trace = tracer.chrome_trace()
    with open(path, "w") as f:
        json.dump(trace, f, indent=1)
    return validate_chrome_trace(trace)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--url", action="append", default=[],
                    help="live obs endpoint base URL (repeatable)")
    ap.add_argument("--artifact", action="append", default=[],
                    help="chaos/timeline JSON artifact (repeatable)")
    ap.add_argument("-m", "--match", help="match id to render")
    ap.add_argument("--list", action="store_true",
                    help="list match ids and event counts, then exit")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="also write the match as a Perfetto trace JSON")
    args = ap.parse_args()
    if not args.url and not args.artifact:
        ap.error("need at least one --url or --artifact")
    merged = load_sources(args.url, args.artifact)
    if args.list or not args.match:
        for mid in sorted(merged):
            evs = merged[mid]
            kinds = "->".join(dict.fromkeys(e.get("ev", "?") for e in evs))
            print(f"{mid:<16} {len(evs):>4} events  {kinds}")
        return 0
    events = merged.get(args.match, [])
    if not events:
        print(f"match_timeline: no events for {args.match!r} "
              f"(known: {sorted(merged)})", file=sys.stderr)
        return 1
    print(f"match {args.match} — {len(events)} events, "
          f"trace {match_trace_id(args.match):#018x}")
    for line in format_timeline(events):
        print("  " + line)
    if args.perfetto:
        problems = export_perfetto(events, args.perfetto)
        if problems:
            print(f"perfetto export INVALID: {problems}", file=sys.stderr)
            return 1
        print(f"perfetto trace written: {args.perfetto}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
