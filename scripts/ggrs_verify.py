#!/usr/bin/env python
"""ggrs-verify: run the static-analysis plane over the tree.

Five gates, all source-level (DESIGN.md §20, §22):

  layout       cross-language ABI/layout checker: native constants vs
               the Python decoders (header stride/fields, flag bits,
               error-code mirrors, RPC framing, jump offsets), plus the
               runtime ggrs_bank_hdr_stride() probe when a built native
               library is present
  determinism  AST lint over rollback-visible code (wall clock, RNG,
               set iteration, salted hash, jit float reductions,
               unpinned pickles), baseline-aware
  ownership    ThreadOwned declaration lint (_DRIVING_METHODS closed
               both ways; no Thread/Timer/submit hand-off of a driving
               method)
  transitions  ggrs-model conformance: every fleet-layer state-setter
               site performs an edge of the declared SLOT_/PROC_/
               SHARD_TRANSITIONS tables
  hygiene      no generated artifacts (__pycache__, *.pyc, *.so,
               bench_out) tracked by git; .gitignore keeps covering them

plus, with --model, the exploration leg: the §9/§16/§17 protocol
models from analysis/machines.py are explored breadth-first under a
state/time budget — HEAD models must be invariant-clean, known-broken
fixture models (the pre-PR-11 checkpoint ordering) must keep their
pinned shortest counterexamples.

Usage:
  python scripts/ggrs_verify.py                 # verify, exit 1 on new
  python scripts/ggrs_verify.py --quick         # pre-commit: no runtime
                                                # probe, no models
  python scripts/ggrs_verify.py --model         # + model exploration
  python scripts/ggrs_verify.py --model --model-budget 500000,60
  python scripts/ggrs_verify.py --baseline-update
  python scripts/ggrs_verify.py --json out.json # embeds model traces

Exit codes: 0 = clean (modulo baseline), 1 = new violations, 2 = the
tool itself could not run.  Never imports the modules it judges — a
tree broken enough not to import still gets a verdict.
"""

from __future__ import annotations

import argparse
import ctypes
import importlib.util
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
BASELINE = REPO / "ggrs_tpu/analysis/determinism_baseline.json"


def _load_analysis():
    """Load ggrs_tpu.analysis WITHOUT executing ggrs_tpu/__init__ (which
    pulls jax and the whole session surface): the verifier must run fast
    and must run on trees whose runtime packages do not import."""
    spec = importlib.util.spec_from_file_location(
        "ggrs_analysis",
        REPO / "ggrs_tpu/analysis/__init__.py",
        submodule_search_locations=[str(REPO / "ggrs_tpu/analysis")],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["ggrs_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def check_hygiene(analysis) -> list:
    """Generated artifacts must never be tracked, and the ignore rules
    that keep them out must stay in place — the analysis plane scans
    sources, and a tracked .so/.pyc makes runs irreproducible."""
    Finding = analysis.Finding
    findings = []
    try:
        tracked = subprocess.run(
            ["git", "ls-files"], cwd=REPO, capture_output=True,
            text=True, check=True,
        ).stdout.splitlines()
    except (subprocess.SubprocessError, OSError):
        return []  # not a git checkout: nothing to police
    for path in tracked:
        if (
            "__pycache__" in path
            or path.endswith((".pyc", ".so"))
            or path.startswith("bench_out/")
        ):
            findings.append(Finding(
                "hygiene/tracked-artifact", path, 0,
                "generated artifact is tracked by git",
            ))
    gitignore = (REPO / ".gitignore")
    rules = gitignore.read_text().splitlines() if gitignore.exists() else []
    for needed in ("__pycache__/", "*.pyc", "*.so", "bench_out/"):
        if needed not in rules:
            findings.append(Finding(
                "hygiene/gitignore", ".gitignore", 0,
                f"missing ignore rule {needed!r}",
            ))
    return findings


def check_runtime_probes(analysis) -> list:
    """Pin the static layout table to the runtime probes when a built
    native library is on disk.  Loaded via ctypes straight from the .so
    — no package import — and skipped silently when there is nothing
    built (the static checks already ran)."""
    Finding = analysis.Finding
    findings = []
    header = analysis.static_bank_header()
    # production library only: the sanitizer variants (_san/_tsan) abort
    # any process that dlopens them without their runtime preloaded
    for name in ("_ggrs_codec.so",):
        lib_path = REPO / "ggrs_tpu/net" / name
        if not lib_path.exists():
            continue
        try:
            lib = ctypes.CDLL(str(lib_path))
        except OSError:
            findings.append(Finding(
                "layout/runtime-probe", f"ggrs_tpu/net/{name}", 0,
                "library exists but does not load (stale build?)",
            ))
            continue
        if not hasattr(lib, "ggrs_bank_hdr_stride"):
            continue  # pre-header library: the loader rebuilds it
        lib.ggrs_bank_hdr_stride.restype = ctypes.c_int
        stride = int(lib.ggrs_bank_hdr_stride())
        if stride != header["stride"]:
            findings.append(Finding(
                "layout/runtime-probe", f"ggrs_tpu/net/{name}", 0,
                f"ggrs_bank_hdr_stride() = {stride} != static contract "
                f"{header['stride']}",
            ))
        # descriptor plane (§21) + datapath gen 2 (§23): record strides
        # and stat-table widths straight from the built library
        for sym, want in (
            ("ggrs_bank_req_stride", analysis.layout.LAYOUT_REQ_STRIDE),
            ("ggrs_bank_stage_stride",
             analysis.layout.LAYOUT_STAGE_STRIDE),
            ("ggrs_net_recv_stride", analysis.layout.LAYOUT_RECV_STRIDE),
            ("ggrs_net_route_stride",
             analysis.layout.LAYOUT_ROUTE_STRIDE),
            ("ggrs_net_fd_stride", analysis.layout.LAYOUT_FD_STRIDE),
        ):
            if not hasattr(lib, sym):
                continue  # pre-descriptor library: the loader rebuilds it
            fn = getattr(lib, sym)
            fn.restype = ctypes.c_int
            got = int(fn())
            if got != want:
                findings.append(Finding(
                    "layout/runtime-probe", f"ggrs_tpu/net/{name}", 0,
                    f"{sym}() = {got} != static contract {want}",
                ))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", type=Path, default=BASELINE)
    ap.add_argument(
        "--baseline-update", action="store_true",
        help="rewrite the baseline from the current tree and exit 0",
    )
    ap.add_argument(
        "--json", type=Path, default=None, metavar="PATH",
        help="write a machine-readable verdict artifact",
    )
    ap.add_argument(
        "--no-runtime", action="store_true",
        help="skip the runtime-probe cross-check even if a .so exists",
    )
    ap.add_argument(
        "--quick", action="store_true",
        help="pre-commit mode: layout + lints only (no runtime probe, "
             "no model exploration)",
    )
    ap.add_argument(
        "--model", action="store_true",
        help="also explore the §9/§16/§17 protocol models "
             "(analysis/machines.py catalog)",
    )
    ap.add_argument(
        "--model-budget", default="200000,30", metavar="STATES[,SECONDS]",
        help="per-model exploration budget (default: %(default)s)",
    )
    args = ap.parse_args(argv)

    try:
        budget = args.model_budget.split(",")
        model_states = int(budget[0])
        model_seconds = float(budget[1]) if len(budget) > 1 else 30.0
    except (ValueError, IndexError):
        print(f"ggrs-verify: bad --model-budget {args.model_budget!r} "
              "(want STATES[,SECONDS])", file=sys.stderr)
        return 2

    try:
        analysis = _load_analysis()
    except Exception as e:  # the tool must fail distinguishably
        print(f"ggrs-verify: cannot load the analysis package: {e}",
              file=sys.stderr)
        return 2

    sections = {
        "layout": list(analysis.check_layout(REPO)),
        "determinism": list(analysis.lint_determinism(REPO)),
        "ownership": list(analysis.lint_ownership(REPO)),
        "transitions": list(analysis.lint_transitions(REPO)),
        "hygiene": check_hygiene(analysis),
    }
    if not args.no_runtime and not args.quick:
        sections["layout"] += check_runtime_probes(analysis)

    model_results = None
    if args.model and not args.quick:
        model_findings, model_results = analysis.check_models(
            REPO, max_states=model_states, max_seconds=model_seconds,
        )
        sections["model"] = model_findings
        for r in model_results:
            # "ok" here means MET EXPECTATION: fixture models are
            # supposed to produce their pinned counterexample, and a
            # fixture that explores clean is as broken as a HEAD model
            # that does not (check_models emits the finding either way)
            met = (r["kind"] == "clean") == (r["expect"] == "clean")
            kind = r["kind"]
            if kind != "clean" and r["expect"] == "counterexample":
                kind += "(expected)"
            print(
                f"model {'ok  ' if met else 'FAIL'} "
                f"{r['model']:<30s} ({r['section']}) "
                f"{kind:<21s} {r['states']:>6d} states  "
                f"depth {r['depth']:>2d}  {r['elapsed_s']*1000:7.1f} ms"
            )
        print(
            f"model leg: {len(model_results)} models, "
            f"{sum(r['states'] for r in model_results)} states, "
            f"{sum(r['elapsed_s'] for r in model_results):.2f}s elapsed "
            f"(budget: {model_states} states / {model_seconds:g}s "
            "per model)"
        )

    # only the determinism lint is baseline-eligible: layout/ownership/
    # transitions/hygiene/model drift is always a hard failure (there is
    # no "legacy" ABI skew or phantom transition to burn down — skew IS
    # the bug)
    det = sections["determinism"]
    hard = [
        f for k, v in sections.items() if k != "determinism" for f in v
    ]
    if args.baseline_update:
        analysis.write_baseline(
            args.baseline, analysis.Baseline.from_findings(det)
        )
        print(f"baseline updated: {args.baseline} "
              f"({len(det)} entries)")
        # hard findings are never baseline-eligible: blessing the
        # determinism set must not hide ABI/ownership/hygiene drift
        for f in hard:
            print(f"FAIL {f.render()}")
        if hard:
            print(f"ggrs-verify: FAIL ({len(hard)} non-baselineable "
                  "findings remain)")
        return 1 if hard else 0
    baseline = analysis.load_baseline(args.baseline)
    new_det, legacy_det = baseline.split(det)

    for f in hard + new_det:
        print(f"FAIL {f.render()}")
    for f in legacy_det:
        print(f"legacy {f.render()}")

    verdict = "PASS" if not hard and not new_det else "FAIL"
    counts = {k: len(v) for k, v in sections.items()}
    summary = (
        f"{counts['layout']} layout, {len(new_det)} new + "
        f"{len(legacy_det)} legacy determinism, "
        f"{counts['ownership']} ownership, "
        f"{counts['transitions']} transitions, "
        f"{counts['hygiene']} hygiene"
    )
    if model_results is not None:
        summary += f", {counts['model']} model"
    print(f"ggrs-verify: {verdict} ({summary})")
    if args.json is not None:
        artifact = {
            "verdict": verdict,
            "counts": counts,
            "new": [f._asdict() for f in hard + new_det],
            "legacy": [f._asdict() for f in legacy_det],
        }
        if model_results is not None:
            # per-model verdicts WITH counterexample traces: the JSON
            # artifact is the replayable record of what exploration saw
            artifact["models"] = model_results
        args.json.parent.mkdir(parents=True, exist_ok=True)
        args.json.write_text(json.dumps(artifact, indent=2) + "\n")
    return 0 if verdict == "PASS" else 1


if __name__ == "__main__":
    raise SystemExit(main())
