#!/usr/bin/env python
"""fleet_top: a live per-shard terminal dashboard over the merged fleet
metrics (DESIGN.md §18).

Points at a supervisor's ``obs.start_http_server`` endpoint — the one
serving ``supervisor.merged_registry()`` on ``/metrics.json`` and
``supervisor.healthz`` on ``/healthz`` — and renders, refreshing in
place:

- the fleet header: tick, overall verdict, matches placed/pending/lost;
- one row per shard: backend, lifecycle state, matches (bank/adopted),
  heartbeat age, watchdog stage, restarts, ingress routes terminating
  on the shard (when a §26 placement healthz is being rendered), tick
  p99;
- per-shard span-phase p99s estimated from the harvested
  ``ggrs_fleet_span_seconds{shard,name}`` histogram — the "which phase
  eats the budget" view ROADMAP item 3 wants;
- the fleet counters (admissions, migrations, failovers, lost) and the
  harvest plane's own health (snapshots merged, dups, gaps, ferried
  forensics);
- the §28 SLO plane: per-shard budget compliance from the harvested
  ``ggrs_slo_*`` counters (the SLO column), the supervisor's
  multi-window burn-rate verdict from ``healthz["slo"]``, and a match
  timeline footer (the last lifecycle events per match) when the
  endpoint also serves ``/timeline``.

Usage:
  python scripts/fleet_top.py --url http://127.0.0.1:9464
  python scripts/fleet_top.py --url http://127.0.0.1:9464 --once  # one frame

``render()`` is a pure function over the two JSON documents, so tests
drive it from captured snapshots without a server.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ggrs_tpu.obs.fleet_obs import histogram_quantile  # noqa: E402


def fetch(url: str, timeout: float = 3.0) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _fetch_healthz(base: str, timeout: float = 3.0) -> Dict[str, Any]:
    # /healthz answers 503 (with the same JSON body) when the fleet is
    # unhealthy — that is a datum, not a fetch failure
    try:
        return fetch(base + "/healthz", timeout)
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read().decode())
        except Exception:
            return {"ok": False, "error": str(e)}


def _fmt_age(age: Optional[float]) -> str:
    if age is None:
        return "-"
    if age < 10:
        return f"{age * 1000:.0f}ms"
    return f"{age:.1f}s"


def _fmt_ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.2f}"


def _span_p99s(metrics: Dict[str, Any]
               ) -> Dict[str, List[Tuple[str, float, int]]]:
    """Per shard: [(span name, p99 ms, count)] from the harvested
    ``ggrs_fleet_span_seconds`` histogram, largest p99 first."""
    fam = metrics.get("ggrs_fleet_span_seconds")
    out: Dict[str, List[Tuple[str, float, int]]] = {}
    if not fam:
        return out
    for sample in fam.get("samples", ()):
        labels = sample.get("labels", {})
        shard = labels.get("shard", "?")
        name = labels.get("name", "?")
        buckets = sample.get("buckets", ())
        uppers = [b["le"] for b in buckets if b["le"] != "+Inf"]
        cums = [b["count"] for b in buckets]
        p99 = histogram_quantile(0.99, uppers, cums)
        if p99 is None:
            continue
        out.setdefault(shard, []).append(
            (name, p99 * 1000.0, sample.get("count", 0))
        )
    for shard in out:
        out[shard].sort(key=lambda t: -t[1])
    return out


def _slo_by_shard(metrics: Dict[str, Any]) -> Dict[str, Tuple[int, int]]:
    """Per shard: (ticks, breaches) summed across tiers from the
    harvested ``ggrs_slo_*`` counter families."""
    out: Dict[str, List[int]] = {}
    for name, idx in (("ggrs_slo_ticks_total", 0),
                      ("ggrs_slo_breaches_total", 1)):
        fam = metrics.get(name)
        if not fam:
            continue
        for sample in fam.get("samples", ()):
            shard = sample.get("labels", {}).get("shard", "?")
            out.setdefault(shard, [0, 0])[idx] += int(sample.get("value", 0))
    return {s: (t[0], t[1]) for s, (t) in out.items()}


def _fmt_slo(stats: Optional[Tuple[int, int]]) -> str:
    if not stats or stats[0] <= 0:
        return "-"
    ticks, breaches = stats
    return f"{100.0 * (1.0 - breaches / ticks):.2f}%"


def _slo_header(slo: Dict[str, Any]) -> str:
    """One line: verdict level plus each tier's worst-window burn."""
    parts = [f"slo: {slo.get('level', '?')}"]
    for tier, t in sorted((slo.get("tiers") or {}).items()):
        burns = t.get("burn") or {}
        worst = max(burns.values()) if burns else 0.0
        parts.append(
            f"{tier}={t.get('level', '?')} burn_max={worst:.2f} "
            f"({int(t.get('breaches', 0))}/{int(t.get('ticks', 0))} breached)"
        )
    return "  ".join(parts)


def _timeline_footer(timelines: Dict[str, List[Dict[str, Any]]],
                     max_matches: int = 8,
                     max_events: int = 10) -> List[str]:
    """Compact per-match lifecycle rows from merged §28 timelines:
    newest matches first, each as ``mid: EV@origin -> EV@origin ...``."""
    lines = ["match timelines (latest events):"]
    def newest(evs: List[Dict[str, Any]]) -> int:
        return max((e.get("ts_ns", 0) for e in evs), default=0)
    mids = sorted(timelines, key=lambda m: -newest(timelines[m]))
    for mid in mids[:max_matches]:
        evs = timelines[mid][-max_events:]
        chain = " -> ".join(
            f"{e.get('ev', '?')}@{e.get('origin') or '?'}" for e in evs
        )
        lines.append(f"  {mid:<14} {chain}")
    if len(mids) > max_matches:
        lines.append(f"  ... and {len(mids) - max_matches} more matches")
    return lines


def _counter_total(metrics: Dict[str, Any], name: str) -> int:
    fam = metrics.get(name)
    if not fam:
        return 0
    return int(sum(s.get("value", 0) for s in fam.get("samples", ())))


def render(healthz: Dict[str, Any], metrics: Dict[str, Any],
           phases_per_shard: int = 4,
           timelines: Optional[Dict[str, List[Dict[str, Any]]]] = None
           ) -> str:
    """One dashboard frame as text (pure; no I/O)."""
    lines: List[str] = []
    ok = healthz.get("ok")
    verdict = "OK" if ok else "DEGRADED"
    lines.append(
        f"ggrs fleet_top — tick {healthz.get('tick', '?')}  "
        f"[{verdict}]  matches={healthz.get('matches', '?')} "
        f"pending={healthz.get('pending_admissions', 0)} "
        f"lost={healthz.get('lost_matches', 0)}  "
        f"last_tick={_fmt_age(healthz.get('last_tick_age_s'))}"
    )
    slo = healthz.get("slo")
    if slo:
        lines.append(_slo_header(slo))
    lines.append("")
    header = (
        f"{'SHARD':<10} {'BACKEND':<8} {'STATE':<9} {'OK':<3} "
        f"{'MATCHES':<9} {'HB AGE':<8} {'WATCHDOG':<11} {'RST':<4} "
        f"{'LINK':<14} {'INGRESS':<8} {'P99 MS':<8} {'SLO':<8}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    shards = healthz.get("shards", {})
    proc = healthz.get("proc", {})
    slo_shards = _slo_by_shard(metrics)
    for sid in sorted(shards):
        h = shards[sid]
        p = proc.get(sid, {})
        link = p.get("link") or h.get("link")
        if link:
            link_col = f"{link.get('state', '?')}/e{link.get('epoch', 0)}"
            if link.get("reconnects"):
                link_col += f"+r{link['reconnects']}"
        else:
            link_col = "-"
        matches = f"{h.get('matches', 0)}"
        if "bank_matches" in h:
            matches += (f" ({h.get('bank_matches', 0)}b/"
                        f"{h.get('adopted_matches', 0)}a)")
        lines.append(
            f"{sid:<10} {h.get('backend', 'inproc'):<8} "
            f"{h.get('state', '?'):<9} "
            f"{'y' if h.get('ok') else 'N':<3} {matches:<9} "
            f"{_fmt_age(p.get('heartbeat_age_s', h.get('heartbeat_age_s'))):<8} "
            f"{p.get('watchdog', h.get('watchdog', '-')) or '-':<11} "
            f"{str(p.get('restarts', h.get('restarts', 0))):<4} "
            f"{link_col:<14} "
            f"{str(h.get('ingress_routes', '-')):<8} "
            f"{_fmt_ms(h.get('tick_p99_ms')):<8} "
            f"{_fmt_slo(slo_shards.get(sid)):<8}"
        )
    p99s = _span_p99s(metrics)
    if p99s:
        lines.append("")
        lines.append("phase p99 (harvested spans, ms):")
        for shard in sorted(p99s):
            tops = ", ".join(
                f"{name}={p99:.2f}"
                for name, p99, _count in p99s[shard][:phases_per_shard]
            )
            lines.append(f"  {shard:<10} {tops}")
    ing = healthz.get("ingress")
    if ing:
        lines.append("")
        fwd = sum(ing.get("forwarded", {}).values())
        dropped = sum(ing.get("dropped", {}).values())
        lines.append(
            "ingress {}: public={} routes={} flips={} fwd={} "
            "dropped={} route_epoch={}".format(
                ing.get("name", "?"),
                ":".join(str(p) for p in ing.get("public", ())) or "-",
                ing.get("routes", 0),
                ing.get("flips", 0),
                fwd,
                dropped,
                healthz.get("route_epoch", "-"),
            )
        )
    lines.append("")
    lines.append(
        "fleet: admissions={} migrations={} failovers={} lost={} | "
        "harvest: snapshots={} dups={} gaps={} forensics={}".format(
            _counter_total(metrics, "ggrs_fleet_admissions_total"),
            _counter_total(metrics, "ggrs_fleet_migrations_total"),
            _counter_total(metrics, "ggrs_fleet_failovers_total"),
            _counter_total(metrics, "ggrs_fleet_matches_lost_total"),
            _counter_total(metrics, "ggrs_fleet_obs_snapshots_total"),
            _counter_total(metrics, "ggrs_fleet_obs_snapshot_dups_total"),
            _counter_total(metrics, "ggrs_fleet_obs_snapshot_gaps_total"),
            _counter_total(metrics, "ggrs_fleet_obs_forensics_total"),
        )
    )
    if timelines:
        lines.append("")
        lines.extend(_timeline_footer(timelines))
    elif healthz.get("timeline_matches"):
        lines.append("")
        lines.append(
            f"timelines: {healthz['timeline_matches']} matches tracked "
            f"(serve /timeline or use scripts/match_timeline.py to view)"
        )
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--url", default="http://127.0.0.1:9464",
                    help="base URL of the supervisor's obs HTTP server")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (CI/scripting)")
    ap.add_argument("--phases", type=int, default=4, metavar="N",
                    help="top-N phases per shard in the p99 table")
    args = ap.parse_args()
    base = args.url.rstrip("/")
    while True:
        try:
            healthz = _fetch_healthz(base)
            metrics = fetch(base + "/metrics.json")
        except Exception as e:
            frame = f"fleet_top: cannot reach {base}: {e}"
        else:
            try:
                timelines = fetch(base + "/timeline")
            except Exception:
                timelines = None  # endpoint optional (older servers: 404)
            frame = render(healthz, metrics, phases_per_shard=args.phases,
                           timelines=timelines)
        if args.once:
            print(frame)
            return 0
        # clear + home, then the frame — refresh in place like top(1)
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
