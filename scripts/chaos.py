#!/usr/bin/env python
"""Pool-scale chaos CLI for the supervised session bank (DESIGN.md §9).

Thin front-end over ``ggrs_tpu.chaos`` — the SAME driver the test suite
uses (tests/test_bank_faults.py), so the script and the tests exercise one
code path.  For each selected fault class it runs a fault-free CONTROL leg
and a CHAOS leg, then verifies the blast radius: every non-targeted slot's
wire bytes, request lists, and events must be bit-identical between the two
legs, and the crossing count must stay one native crossing per pool tick.
Each scenario ends with a metrics + flight-recorder summary (faults by
code, evictions, survivor counters, the target slot's last 32 recorded
events) instead of discarding that state — DESIGN.md §12.

With ``--artifact-dir``, every scenario additionally writes a
machine-readable JSON artifact (digest + verdict + any DesyncReport
path) for CI consumption — DESIGN.md §14.

Fault classes (all driven through the pool's real tick path):
  native-error  simulated native slot fault (ctrl-op channel)
  desync        desync-class invariant fault (BANK_ERR_SYNC) on the bank —
                the quarantine now yields a DesyncReport artifact — plus a
                forensic leg on the reference detection path: a state
                fault seeded at a known frame must bisect to EXACTLY that
                first divergent frame in both peers' reports
  blackout      the target's peer goes permanently silent
  malformed     burst of truncated/corrupted datagrams into the target
  fuzz          seeded random junk datagrams into the target
  lockstep      lockstep-demotion leg (DESIGN.md §27): a live native slot
                is demoted to the lockstep tier mid-run — survivors must
                stay bit-identical to control, the demoted slot must land
                evicted+lockstep with exactly one adoption load, zero
                saves, and CONFIRMED-only advances
  spectator     broadcast leg: a hub-fanned match with live viewers and a
                journal is chaos-killed with its native harvest DEAD; the
                slot must recover from the journal tail, the viewers must
                keep following, and the in-bank side matches must stay
                bit-identical to control (ends with the hub's metrics
                digest — DESIGN.md §13)
  socket        batched-datapath leg (real loopback UDP, native_io=True —
                DESIGN.md §15): an ENOBUFS/EAGAIN storm on the target's
                sendmmsg path must count as loss without faulting the
                slot, a fatal EPERM must fault exactly that slot
                (BANK_ERR_IO) and evict it onto the Python socket path —
                survivors' wire bytes bit-identical to control either way
  proc          out-of-process leg (DESIGN.md §17): s1 is a REAL
                subprocess (scripts/shard_runner.py) behind the
                supervisor RPC — SIGKILL mid-traffic must be detected
                within the heartbeat deadline with every match
                journal-recovered and zero orphans, SIGSTOP must
                escalate SIGTERM -> drain deadline -> SIGKILL before the
                same recovery, and a 5x kill storm must exhaust the
                restart budget instead of crash-looping; every artifact
                records its FleetTuning knobs
  net           multi-host fleet link leg (DESIGN.md §25): the proc
                topology with the supervisor<->runner control plane on
                the authenticated TCP link — a severed or half-open
                link must RESUME inside the reconnect window with zero
                failovers, hostile dribble against the listener
                (garbage / slowloris / truncated auth) is refused and
                counted without touching the served link, a SIGKILLed
                runner journal-fails-over bit-identically to control,
                and a runner resurrected after its window expired is
                fenced at handshake by the bumped epoch and exits;
                ends with a cross-host placement leg (DESIGN.md §26):
                killing a whole host fails every match over to the
                survivor host behind UNCHANGED virtual endpoints
  shard         fleet leg (DESIGN.md §16): a two-shard ShardSupervisor
                (B = --fleet-matches journaled matches per shard, default
                32) runs three scenarios — kill-a-shard (every affected
                match journal-recovers onto the survivor within bounded
                lag; the surviving shard's matches bit-identical to a
                fault-free control), drain-under-load (admission closes,
                every match migrates off, the shard retires), and
                migrate-under-loss (a live migration under seeded
                loss/dup/reorder keeps the peer connected and
                desync-free, spectators resume from their ack window)
  all           every class, sequentially

Usage:
  JAX_PLATFORMS=cpu python scripts/chaos.py --matches 4 --ticks 400
  python scripts/chaos.py --fault blackout --ticks 600 --seed 7

Exit code 0 = blast radius contained in every leg; 1 = violation.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ggrs_tpu.chaos import (  # noqa: E402
    MALFORMED_BURST,
    blast_radius_violations,
    drive_broadcast,
    drive_chaos,
    drive_desync_forensics,
    drive_dispatch_chaos,
    drive_socket_chaos,
)
from ggrs_tpu.net import _native  # noqa: E402
from ggrs_tpu.obs import (  # noqa: E402
    Tracer,
    fleet_metrics_digest,
    json_snapshot,
    validate_chrome_trace,
)
from ggrs_tpu.obs.slo import (  # noqa: E402
    BurnRateEngine,
    ShardSloMeter,
    SloPolicy,
)
from ggrs_tpu.obs.timeline import (  # noqa: E402
    EV_ADMIT,
    EV_DEMOTE_LOCKSTEP,
    EV_FAILOVER,
    EV_MIGRATE_BEGIN,
    EV_MIGRATE_COMMIT,
    EV_ROUTE_FLIP,
    TimelineStore,
    first_occurrence_order,
    fold_trace_aliases,
    merge_timelines,
    timeline_ring_events,
)


def _fleet_trace_artifact(artifact_dir, name: str, tracer):
    """Write one scenario's Perfetto export beside its JSON artifact and
    return ``{"trace_path":..., "trace_spans":..., "trace_problems":...}``
    for embedding (DESIGN.md §18).  The export is schema-validated here
    (eps widened for imported cross-process spans) so a torn trace shows
    up in CI, not in a ui.perfetto.dev tab weeks later."""
    if artifact_dir is None or tracer is None:
        return {}
    out = Path(artifact_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = tracer.write(out / f"{name}.trace.json")
    trace = tracer.chrome_trace()
    problems = validate_chrome_trace(trace, eps_us=50.0)
    if problems:
        print(f"  trace validation ({name}): {len(problems)} problems, "
              f"e.g. {problems[0]}")
    else:
        print(f"  trace: {path} ({len(trace['traceEvents'])} events, "
              "schema-valid)")
    return {
        "trace_path": str(path),
        "trace_spans": len(trace["traceEvents"]),
        "trace_problems": problems[:8],
    }


def _placement_timelines(ctx) -> dict:
    """The cross-host merged match timelines of a placement-fleet run
    (DESIGN.md §28): the placement plane's own store, each host
    supervisor's harvested store (origin prefixed with the host id so
    the merged view shows WHICH machine saw each event), and the
    ingress node's trace-keyed ROUTE_FLIP events folded onto their
    matches via the wire trace context."""
    sources = [ctx["placement"].timelines.to_dict()]
    for hid, sup in ctx["hosts"].items():
        exported = sup.fleet_obs.timelines.to_dict()
        sources.append({
            mid: [dict(e, origin=f"{hid}/{e.get('origin') or '?'}")
                  for e in evs]
            for mid, evs in exported.items()
        })
    ing: dict = {}
    for ev in ctx["ingress"].drain_timeline():
        ing.setdefault(ev["mid"], []).append(ev)
    sources.append(ing)
    return fold_trace_aliases(merge_timelines(*sources))


def _timeline_trace_artifact(artifact_dir, name: str, timelines: dict):
    """ONE Perfetto export for a merged timeline view — every match's
    lifecycle events re-emitted as instants through the §18 Tracer path
    — schema-validated in CI like the span exports.  Returns the
    embedding dict (empty without --artifact-dir)."""
    if artifact_dir is None or not timelines:
        return {}
    events = [ev for evs in timelines.values() for ev in evs]
    tracer = Tracer(capacity=max(256, len(events) + 16))
    tracer.import_spans(timeline_ring_events(events))
    out = Path(artifact_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = tracer.write(out / f"{name}.timeline.trace.json")
    trace = tracer.chrome_trace()
    problems = validate_chrome_trace(trace, eps_us=50.0)
    if problems:
        print(f"  timeline trace validation ({name}): "
              f"{len(problems)} problems, e.g. {problems[0]}")
    else:
        print(f"  timeline trace: {path} "
              f"({len(trace['traceEvents'])} events, schema-valid)")
    return {
        "timeline_trace_path": str(path),
        "timeline_trace_events": len(trace["traceEvents"]),
        "timeline_trace_problems": problems[:8],
    }


def _write_artifact(artifact_dir, name: str, payload: dict):
    """One machine-readable JSON artifact per scenario (CI consumption):
    digest + verdict + any DesyncReport path, alongside the stdout
    digest.  Returns the path, or None when no --artifact-dir was given."""
    if artifact_dir is None:
        return None
    out = Path(artifact_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"{name}.json"
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, default=str)
    print(f"  artifact: {path}")
    return path


def _metrics_summary(chaos) -> str:
    """Per-scenario metrics digest (DESIGN.md §12): faults by code,
    supervision flow, crossing budget, and survivor counters — the state
    a plain pass/fail verdict used to discard."""
    reg = chaos["registry"]
    lines = []
    fam = {f.name: f for f in reg.families()}
    faults = fam.get("ggrs_pool_slot_faults_total")
    if faults is not None and faults.children:
        by_code = ", ".join(
            f"code {labels['code']}: {int(child.value)}"
            for labels, child in faults.samples()
        )
        lines.append(f"  metrics: faults by code: {by_code or 'none'}")
    else:
        lines.append("  metrics: faults by code: none")
    lines.append(
        "  metrics: evictions={} eviction_failures={} ticks={} "
        "crossings(tick/harvest/stats)={}/{}/{}".format(
            int(reg.value("ggrs_pool_evictions_total") or 0),
            int(reg.value("ggrs_pool_eviction_failures_total") or 0),
            int(reg.value("ggrs_pool_ticks_total") or 0),
            int(reg.value("ggrs_pool_crossings_total", kind="tick") or 0),
            int(reg.value("ggrs_pool_crossings_total", kind="harvest") or 0),
            int(reg.value("ggrs_pool_crossings_total", kind="stats") or 0),
        )
    )
    lines.append(
        "  metrics: survivor counters: requests save/load/advance = "
        "{}/{}/{}, rollbacks={}".format(
            int(reg.value("ggrs_pool_requests_total", kind="save") or 0),
            int(reg.value("ggrs_pool_requests_total", kind="load") or 0),
            int(reg.value("ggrs_pool_requests_total", kind="advance") or 0),
            int(reg.value("ggrs_pool_rollbacks_total") or 0),
        )
    )
    states = fam.get("ggrs_pool_slot_state")
    if states is not None:
        occupancy = ", ".join(
            f"{labels['state']}={int(child.value)}"
            for labels, child in states.samples()
            if child.value
        )
        lines.append(f"  metrics: slot states: {occupancy}")
    return "\n".join(lines)


def _fuzz_bytes(seed: int, i: int, k: int) -> bytes:
    rng = random.Random(seed * 7919 + i * 31 + k)
    return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 40)))


FAULTS = {
    "native-error": dict(
        inject=lambda i, ctx: (
            ctx["pool"].inject_slot_error(ctx["target"]) if i == 60 else None
        ),
    ),
    "desync": dict(
        inject=lambda i, ctx: (
            ctx["pool"].inject_slot_error(
                ctx["target"], _native.BANK_ERR_SYNC
            )
            if i == 60
            else None
        ),
    ),
    "blackout": dict(ext_alive=lambda i: i < 80, retire=True),
    "malformed": dict(
        inject=lambda i, ctx: (
            [
                ctx["pool"].inject_datagram(ctx["target"], "X", junk)
                for junk in MALFORMED_BURST
            ]
            if 50 <= i < 60
            else None
        ),
    ),
    "fuzz": dict(
        inject=lambda i, ctx: (
            [
                ctx["pool"].inject_datagram(
                    ctx["target"], "X", _fuzz_bytes(ctx["seed"], i, k)
                )
                for k in range(3)
            ]
            if 40 <= i < 140
            else None
        ),
    ),
}


def verify_leg(name: str, matches: int, ticks: int, seed: int,
               artifact_dir=None) -> bool:
    spec = FAULTS[name]
    retire = spec.get("retire", False)
    control = drive_chaos(ticks, n_matches=matches, seed=seed, retire=retire)
    chaos = drive_chaos(
        ticks, n_matches=matches, seed=seed,
        inject=spec.get("inject"),
        ext_alive=spec.get("ext_alive"),
        retire=retire,
    )
    target = chaos["target"]
    violations = blast_radius_violations(chaos, control)
    pool = chaos["pool"]
    print(f"--- {name} ---")
    print(f"  target slot {target}: state={chaos['states'][target]}, "
          f"frame={chaos['frames'][target]}, ext peer frame="
          f"{chaos['ext'].current_frame}")
    for f in pool.fault_log(target):
        print(f"    fault@tick {f.tick}: code={f.code} {f.detail}")
    print(f"  crossings={pool.crossings} harvests={pool.harvests} "
          f"stat_crossings={pool.stat_crossings} "
          f"fastpath_slot_ticks={pool.fast_slot_ticks}")
    print(_metrics_summary(chaos))
    dump = pool.flight_dump(target, last=32)
    print(f"  flight recorder (target slot {target}, last 32 events):")
    print("\n".join(f"  {line}" for line in dump.splitlines()))
    report = pool.desync_report(target)
    report_path = None
    if report is not None:
        # the desync-class fault left a forensic artifact, not a bare event
        print("  " + report.summary().replace("\n", "\n  "))
        if artifact_dir is not None:
            out = Path(artifact_dir)
            out.mkdir(parents=True, exist_ok=True)
            report_path = report.write(out / f"{name}.desync_report.json")
            print(f"  desync report: {report_path}")
    if name == "desync":
        violations += _verify_desync_forensics(ticks, seed, artifact_dir)
    verdict = not violations
    _write_artifact(artifact_dir, name, {
        "scenario": name,
        "verdict": "PASS" if verdict else "FAIL",
        "violations": violations,
        "target_slot": target,
        "target_state": chaos["states"][target],
        "target_frame": chaos["frames"][target],
        "fault_log": [
            {"tick": f.tick, "code": f.code, "detail": f.detail}
            for f in pool.fault_log(target)
        ],
        "crossings": {"tick": pool.crossings, "harvest": pool.harvests,
                      "stats": pool.stat_crossings},
        # vectorized policy plane (DESIGN.md §19) + descriptor plane
        # (§21): how much of the run the quiet fast path served — fault
        # ticks and their neighbors must take the slow reference decoder,
        # survivors stay fast — and how many plan-tick slots needed the
        # eager per-slot decoder
        "fastpath": {"slot_ticks": pool.fast_slot_ticks,
                     "all_fast_ticks": pool.fast_ticks,
                     "plan_ticks": getattr(pool, "plan_ticks", 0),
                     "desc_slow_slots": getattr(
                         pool, "desc_slow_slots", 0)},
        "desync_report": str(report_path) if report_path else None,
        "metrics": json_snapshot(chaos["registry"]),
    })
    if violations:
        print("  BLAST RADIUS VIOLATED:")
        for v in violations:
            print(f"    {v}")
        return False
    print(f"  OK: {len(chaos['states']) - 1} surviving slots bit-identical "
          "to control")
    return True


def _verify_desync_forensics(ticks: int, seed: int, artifact_dir=None):
    """The forensic leg of the desync scenario: the REFERENCE detection
    path (two Python sessions, interval-1 checksum exchange) with a state
    fault seeded at a known frame — the resulting DesyncReport's
    first-divergent-frame bisection must land exactly on it."""
    from ggrs_tpu.obs import Tracer

    fault_frame = max(20, min(60, ticks // 3))
    run = drive_desync_forensics(
        max(ticks, fault_frame + 60), fault_frame=fault_frame, seed=seed,
        interval=1, tracer=Tracer(),
    )
    violations = []
    print(f"  forensic leg: state fault seeded at frame {fault_frame} "
          f"(checksum interval 1)")
    for side, reports in (("A", run["reports_a"]), ("B", run["reports_b"])):
        if not reports:
            violations.append(f"peer {side} produced no DesyncReport")
            continue
        r = reports[0]
        print(f"  peer {side}: " + r.summary().replace("\n", "\n  "))
        if r.first_divergent_frame != fault_frame:
            violations.append(
                f"peer {side}: first divergent frame "
                f"{r.first_divergent_frame} != fault frame {fault_frame}"
            )
    if run["reports_a"] and run["reports_b"]:
        # both ends' recorder dumps ride one artifact
        report = run["reports_a"][0]
        report.remote_recorder_dump = run["recorders"][1].dump(32)
        if artifact_dir is not None:
            out = Path(artifact_dir)
            out.mkdir(parents=True, exist_ok=True)
            path = report.write(out / "desync.forensic_report.json")
            print(f"  forensic report: {path}")
    return violations


def verify_lockstep_leg(matches: int, ticks: int, seed: int,
                        artifact_dir=None) -> bool:
    """The lockstep-demotion scenario (DESIGN.md §27): a live native slot
    is demoted to the lockstep tier mid-run — the pool's load-shed path.
    The demoted slot must land evicted with ``max_prediction=0``, replay
    its confirmed prefix through EXACTLY ONE adoption load, never save or
    load again, advance only CONFIRMED inputs, and keep making frames;
    every OTHER slot must stay bit-identical to a fault-free control leg."""
    from ggrs_tpu.core import InputStatus
    from ggrs_tpu.parallel.host_bank import SLOT_EVICTED

    demote_at = max(20, min(60, ticks // 3))

    # §28 riders on the chaos leg: the pool's timeline sink (slot-keyed
    # lifecycle events) and a self-contained SLO pipeline — meter fed
    # from real inter-tick wall time + the demoted slot's confirmed
    # lag, burn engine over windows sized to the run
    import time

    from ggrs_tpu.obs.registry import Registry

    timelines = TimelineStore()
    slo_reg = Registry()
    policy = SloPolicy(windows=(("16t", 16), ("64t", max(64, ticks // 2))))
    meter = ShardSloMeter(slo_reg, policy=policy)
    burn = BurnRateEngine(policy=policy)
    last_ns = [0]
    tick_box = [0]

    def inject(i, ctx):
        pool = ctx["pool"]
        tick_box[0] = i
        if i == 0:
            pool.timeline_sink = lambda etype, slot, detail: (
                timelines.record(etype, f"slot{slot}", origin="pool",
                                 tick=tick_box[0], detail=detail))
        now = time.perf_counter_ns()
        if last_ns[0]:
            meter.observe_rollback((now - last_ns[0]) / 1e6)
        last_ns[0] = now
        if pool.lockstep_slots():
            lag = max(0, ctx["ext"].current_frame
                      - pool.current_frame(ctx["target"]))
            meter.observe_lockstep(lag)
        burn.update(i, slo_reg)
        if i == demote_at:
            ctx["resume_frame"] = pool.demote_to_lockstep(ctx["target"])

    control = drive_chaos(ticks, n_matches=matches, seed=seed)
    chaos = drive_chaos(ticks, n_matches=matches, seed=seed, inject=inject)
    target = chaos["target"]
    pool = chaos["pool"]
    resume = chaos.get("resume_frame")
    violations = list(blast_radius_violations(chaos, control))

    print("--- lockstep ---")
    print(f"  target slot {target}: demoted at tick {demote_at}, resume "
          f"frame {resume}, state={chaos['states'][target]}, "
          f"frame={chaos['frames'][target]}, ext peer frame="
          f"{chaos['ext'].current_frame}")

    if chaos["states"][target] != SLOT_EVICTED:
        violations.append(
            f"demoted slot state {chaos['states'][target]!r}, expected "
            f"evicted-to-python ({SLOT_EVICTED!r})"
        )
    if not pool.in_lockstep(target):
        violations.append("pool does not report the target in lockstep")
    if pool.lockstep_slots() != {target: demote_at}:
        violations.append(
            f"lockstep_slots() = {pool.lockstep_slots()!r}, expected "
            f"{{{target}: {demote_at}}}"
        )
    if not resume or resume <= 0:
        violations.append(f"demotion returned resume frame {resume!r}")
    elif chaos["frames"][target] <= resume:
        violations.append(
            f"demoted slot stuck: frame {chaos['frames'][target]} <= "
            f"resume frame {resume}"
        )

    # post-demotion request discipline: one adoption load, zero saves,
    # real progress, and every advance carries CONFIRMED inputs only
    post = [r for tick_reqs in chaos["reqs"][target][demote_at:]
            for r in tick_reqs]
    loads = sum(1 for r in post if r[0] == "LoadGameState")
    saves = sum(1 for r in post if r[0] == "SaveGameState")
    advs = [r for r in post if r[0] == "adv"]
    predicted = sum(
        1 for r in advs
        for _, status in r[1] if status != InputStatus.CONFIRMED
    )
    print(f"  post-demotion requests: {loads} loads (adoption), {saves} "
          f"saves, {len(advs)} advances ({predicted} non-CONFIRMED inputs)")
    if loads != 1:
        violations.append(f"{loads} post-demotion loads, expected exactly "
                          "the 1 adoption load")
    if saves:
        violations.append(f"{saves} post-demotion saves, expected 0 "
                          "(lockstep never snapshots)")
    if not advs:
        violations.append("demoted slot produced no post-demotion advances")
    if predicted:
        violations.append(
            f"{predicted} post-demotion inputs advanced non-CONFIRMED "
            "(lockstep must never run predicted inputs)"
        )
    print(f"  crossings={pool.crossings} harvests={pool.harvests} "
          f"stat_crossings={pool.stat_crossings} "
          f"fastpath_slot_ticks={pool.fast_slot_ticks}")
    print(_metrics_summary(chaos))

    # §28: the pool's timeline seam must have emitted the demotion
    demote_events = [
        e for e in timelines.timeline(f"slot{target}")
        if e["ev"] == EV_DEMOTE_LOCKSTEP
    ]
    if not demote_events:
        violations.append(
            "timeline sink recorded no DEMOTE_LOCKSTEP for the target"
        )
    slo_verdict = burn.verdict()
    tier_levels = ", ".join(
        f"{t}={v['level']}" for t, v in slo_verdict["tiers"].items())
    print(f"  slo: level={slo_verdict['level']} tiers=[{tier_levels}]")

    verdict = not violations
    _write_artifact(artifact_dir, "lockstep", {
        "scenario": "lockstep",
        "verdict": "PASS" if verdict else "FAIL",
        "violations": violations,
        "target_slot": target,
        "demoted_at_tick": demote_at,
        "resume_frame": resume,
        "target_state": chaos["states"][target],
        "target_frame": chaos["frames"][target],
        "post_demotion": {"loads": loads, "saves": saves,
                          "advances": len(advs),
                          "non_confirmed_inputs": predicted},
        "crossings": {"tick": pool.crossings, "harvest": pool.harvests,
                      "stats": pool.stat_crossings},
        # §28 riders: the pool-seam timeline and the run's SLO verdict
        "timeline": timelines.to_dict(),
        "slo": slo_verdict,
        "metrics": json_snapshot(chaos["registry"]),
    })
    if violations:
        print("  BLAST RADIUS VIOLATED:")
        for v in violations:
            print(f"    {v}")
        return False
    print(f"  OK: {len(chaos['states']) - 1} surviving slots bit-identical "
          "to control; demoted slot lockstep-clean")
    return True


def verify_broadcast_leg(matches: int, ticks: int, seed: int,
                         artifact_dir=None) -> bool:
    """The broadcast scenario: chaos-kill a hub-fanned, journaled match
    whose native harvest is dead; verify journal recovery, viewer
    continuity, and survivor bit-identity — then print the hub's metrics
    digest (DESIGN.md §13) instead of discarding it."""
    import tempfile

    from ggrs_tpu.parallel.host_bank import SLOT_EVICTED, SLOT_NATIVE

    # clamp inside the run: the kill must actually fire and leave room to
    # observe the recovery, whatever --ticks was passed
    kill_at = min(max(40, ticks // 3), max(1, ticks - 20))

    def inject(i, ctx):
        if i == kill_at:
            ctx["pool"].inject_slot_error(ctx["target"])

    with tempfile.TemporaryDirectory() as tmp:
        control = drive_broadcast(
            ticks, use_hub=True, seed=seed, n_spectators=2,
            n_side_matches=matches,
            journal_path=f"{tmp}/control.ggjl",
        )
        chaos = drive_broadcast(
            ticks, use_hub=True, seed=seed, n_spectators=2,
            n_side_matches=matches,
            journal_path=f"{tmp}/chaos.ggjl",
            inject=inject, sabotage_harvest=True, scrape_every=8,
        )
    pool = chaos["pool"]
    print("--- spectator ---")
    print(f"  target slot 0: state={chaos['states'][0]}, "
          f"frame={chaos['frames'][0]}, ext peer frame="
          f"{chaos['peer_frame']}, viewers at "
          f"{[f[-1] for f in chaos['viewer_frames']]}")
    for f in pool.fault_log(0):
        print(f"    fault@tick {f.tick}: code={f.code} {f.detail}")
    violations = []
    if chaos["states"][0] != SLOT_EVICTED:
        violations.append(
            f"target never recovered: state {chaos['states'][0]}"
        )
    if not any("journal tail" in f.detail for f in pool.fault_log(0)):
        violations.append("recovery did not come from the journal")
    for vf in chaos["viewer_frames"]:
        if vf[-1] < vf[kill_at] + (ticks - kill_at) // 2:
            violations.append("a viewer stalled after the kill")
    for idx in range(1, 1 + 2 * matches):
        if chaos["states"][idx] != SLOT_NATIVE:
            violations.append(f"slot {idx} left native")
        for field in ("reqs", "events"):
            if chaos[field][idx] != control[field][idx]:
                violations.append(f"slot {idx}: {field} diverged")
    for k in range(2 * matches):
        if chaos["side_wire"][k] != control["side_wire"][k]:
            violations.append(f"side socket {k}: wire diverged")
    print("  hub metrics digest:")
    print(chaos["hub"].metrics_digest())
    _write_artifact(artifact_dir, "spectator", {
        "scenario": "spectator",
        "verdict": "PASS" if not violations else "FAIL",
        "violations": violations,
        "target_state": chaos["states"][0],
        "target_frame": chaos["frames"][0],
        "fault_log": [
            {"tick": f.tick, "code": f.code, "detail": f.detail}
            for f in pool.fault_log(0)
        ],
        "metrics": json_snapshot(chaos["registry"]),
        "desync_report": None,
    })
    if violations:
        print("  BROADCAST SCENARIO VIOLATED:")
        for v in violations:
            print(f"    {v}")
        return False
    print(f"  OK: journal recovery + {2 * matches} surviving slots "
          "bit-identical to control")
    return True


def verify_socket_leg(matches: int, ticks: int, seed: int,
                      artifact_dir=None) -> bool:
    """The batched-datapath scenario (DESIGN.md §15): errno storms on the
    target slot's sendmmsg path, a fault-free control leg, and per-leg
    verification that the blast radius stayed ≤ 1 slot with survivors'
    wire bytes (captured at the NetBatch tee, exact send order)
    bit-identical to control."""
    import errno as _errno

    from ggrs_tpu.net import _native as _nat

    ticks = max(ticks, 160)
    print("--- socket ---")
    try:
        control = drive_socket_chaos(ticks, n_matches=matches, seed=seed)
    except RuntimeError as e:
        # no recvmmsg/sendmmsg on this platform / library: the fallback
        # matrix says the Python shuttle serves — nothing to storm
        print(f"  skip: {e}")
        return True

    def storm_transient(i, ctx):
        if 40 <= i < 60:
            ctx["pool"].inject_socket_errno(
                ctx["target"], _errno.ENOBUFS, 4
            )
        elif 60 <= i < 70:
            ctx["pool"].inject_socket_errno(
                ctx["target"], _errno.EAGAIN, 4
            )

    def storm_fatal(i, ctx):
        if i == 50:
            ctx["pool"].inject_socket_errno(ctx["target"], _errno.EPERM, 1)

    violations = []
    legs = {}
    for name, storm in (("transient", storm_transient),
                        ("fatal", storm_fatal)):
        chaos = drive_socket_chaos(
            ticks, n_matches=matches, seed=seed, inject=storm
        )
        legs[name] = chaos
        target = chaos["target"]
        pool = chaos["pool"]
        for f in pool.fault_log(target):
            print(f"    [{name}] fault@tick {f.tick}: code={f.code} "
                  f"{f.detail}")
        if name == "transient":
            if chaos["states"][target] != "native":
                violations.append(
                    f"transient storm faulted the slot: "
                    f"{chaos['states'][target]}"
                )
            if chaos["io"]["send_errors"] < 20:
                violations.append(
                    "transient storm left no send_errors trace "
                    f"({chaos['io']['send_errors']})"
                )
        else:
            if chaos["states"][target] != "evicted":
                violations.append(
                    f"fatal errno did not evict: {chaos['states'][target]}"
                )
            if not any(f.code == _nat.BANK_ERR_IO
                       for f in pool.fault_log(target)):
                violations.append("fault log missing BANK_ERR_IO")
        if chaos["frames"][target] < ticks - 80:
            violations.append(
                f"{name}: target stalled at frame {chaos['frames'][target]}"
            )
        for idx in range(target):
            if chaos["states"][idx] != "native":
                violations.append(f"{name}: survivor slot {idx} left native")
            if chaos["wire"][idx] != control["wire"][idx]:
                violations.append(
                    f"{name}: survivor slot {idx} wire diverged "
                    f"({len(chaos['wire'][idx])} vs "
                    f"{len(control['wire'][idx])} datagrams)"
                )
            if chaos["reqs"][idx] != control["reqs"][idx]:
                violations.append(f"{name}: survivor slot {idx} reqs diverged")
        print(f"  [{name}] target state={chaos['states'][target]} "
              f"frame={chaos['frames'][target]} "
              f"io={{recv_calls: {chaos['io']['recv_calls']}, "
              f"send_calls: {chaos['io']['send_calls']}, "
              f"send_errors: {chaos['io']['send_errors']}}}")

    # --- shared dispatch socket leg (DESIGN.md §23): a fatal errno on
    # the SHARED fd must fault exactly the owning slot — the record's,
    # not the fd's — while every co-tenant stays native and bit-identical
    # (peer-observed bytes) to a fault-free dispatch control
    try:
        d_control = drive_dispatch_chaos(ticks, n_matches=matches,
                                         seed=seed)
    except RuntimeError as e:
        print(f"  [dispatch_fatal] skip: {e}")
        d_control = None
    if d_control is not None:
        def dispatch_storm(i, ctx):
            # record 0 of tick 50's send table = the target slot's (the
            # table is packed in slot order; slot 0 sends every tick)
            if i == 50:
                ctx["lib"].ggrs_net_inject_table_errno(_errno.EPERM, 0, 1)

        d_chaos = drive_dispatch_chaos(
            ticks, n_matches=matches, seed=seed, inject=dispatch_storm
        )
        legs["dispatch_fatal"] = d_chaos
        target = d_chaos["target"]
        pool = d_chaos["pool"]
        for f in pool.fault_log(target):
            print(f"    [dispatch_fatal] fault@tick {f.tick}: "
                  f"code={f.code} {f.detail}")
        if d_chaos["states"][target] != "evicted":
            violations.append(
                "dispatch_fatal: shared-fd fatal did not evict the "
                f"owner: {d_chaos['states'][target]}"
            )
        if not any(f.code == _nat.BANK_ERR_IO
                   for f in pool.fault_log(target)):
            violations.append("dispatch_fatal: fault log missing "
                              "BANK_ERR_IO")
        if d_chaos["frames"][target] < ticks - 80:
            violations.append(
                "dispatch_fatal: target stalled at frame "
                f"{d_chaos['frames'][target]}"
            )
        for idx in range(1, matches + 1):
            if d_chaos["states"][idx] != "native":
                violations.append(
                    f"dispatch_fatal: co-tenant slot {idx} left native: "
                    f"{d_chaos['states'][idx]}"
                )
            if d_chaos["wire"][idx] != d_control["wire"][idx]:
                violations.append(
                    f"dispatch_fatal: co-tenant slot {idx} wire diverged "
                    f"({len(d_chaos['wire'][idx])} vs "
                    f"{len(d_control['wire'][idx])} datagrams)"
                )
            if d_chaos["reqs"][idx] != d_control["reqs"][idx]:
                violations.append(
                    f"dispatch_fatal: co-tenant slot {idx} reqs diverged"
                )
        if d_chaos["pool"].crossings != ticks:
            violations.append(
                f"dispatch_fatal: crossing count "
                f"{d_chaos['pool'].crossings} != {ticks} pool ticks"
            )
        drain = d_chaos["io"]["drain"]
        dec = d_chaos["io"]["decode"]
        print(f"  [dispatch_fatal] target state="
              f"{d_chaos['states'][target]} "
              f"frame={d_chaos['frames'][target]} fds={d_chaos['hub_fds']} "
              f"drain={{datagrams: {drain['datagrams']}, "
              f"unroutable: {drain['unroutable']}, "
              f"crossings: {drain['crossings']}}} "
              f"gso={d_chaos['io']['gso']} "
              f"decode={{backend: {dec['backend']}, "
              f"parallel_ticks: {dec['parallel_ticks']}, "
              f"jobs: {dec['jobs']}}}")
    verdict = not violations
    _write_artifact(artifact_dir, "socket", {
        "scenario": "socket",
        "verdict": "PASS" if verdict else "FAIL",
        "violations": violations,
        "target_slot": control["target"],
        "legs": {
            name: {
                "target_state": leg["states"][leg["target"]],
                "target_frame": leg["frames"][leg["target"]],
                "io": leg["io"],
                "fault_log": [
                    {"tick": f.tick, "code": f.code, "detail": f.detail}
                    for f in leg["pool"].fault_log(leg["target"])
                ],
            }
            for name, leg in legs.items()
        },
        # §24 decode-plane posture under fault load (each leg's full
        # counters also ride along in legs[*].io.decode)
        "decode_plane": legs["fatal"]["io"]["decode"],
        "metrics": json_snapshot(legs["fatal"]["registry"]),
        "desync_report": None,
    })
    if violations:
        print("  SOCKET SCENARIO VIOLATED:")
        for v in violations:
            print(f"    {v}")
        return False
    print(f"  OK: storms contained; {control['target']} surviving slots "
          "bit-identical to control")
    return True


def verify_fleet_leg(matches_per_shard: int, ticks: int, seed: int,
                     artifact_dir=None) -> bool:
    """The fleet scenarios (DESIGN.md §16), over ``drive_fleet_chaos`` —
    the SAME driver tests/test_fleet.py pins.  Three sub-scenarios, each a
    control/chaos pair with its own JSON verdict:

    - ``shard_kill``: one of two shards dies mid-tick; every affected
      match must journal-recover onto the survivor within bounded lag,
      with the surviving shard's matches bit-identical to control.
    - ``shard_drain``: graceful drain under load; every match migrates
      off a bounded few per tick and the shard retires.
    - ``shard_migrate``: a live migration under seeded loss/dup/reorder;
      the migrated match's peer stays connected and desync-free, the
      untouched matches stay bit-identical to their lossy control, and
      the spectator resumes from its ack window (stream never resets).
    """
    from ggrs_tpu.chaos import (
        drive_fleet_chaos,
        fleet_recovery_violations,
        fleet_survivor_violations,
    )

    p = matches_per_shard
    ticks = max(96, min(ticks, 240))  # bounded: B is the scale knob here
    survivors = [f"m{k}" for k in range(p)]           # pinned to s0
    affected = [f"m{k}" for k in range(p, 2 * p)]     # pinned to s1
    ok = True

    def fleet_digest(ctx) -> dict:
        reg = ctx["registry"]
        return {
            "locations": ctx["locations"],
            "lost": ctx["lost"],
            "healthz": {
                k: v for k, v in ctx["healthz"].items() if k != "shards"
            },
            "migrations": {
                labels["reason"]: int(child.value)
                for f in reg.families()
                if f.name == "ggrs_fleet_migrations_total"
                for labels, child in f.samples()
            },
            "failovers": int(
                reg.value("ggrs_fleet_failovers_total") or 0
            ),
        }

    def report(name: str, violations, ctx, extra=None,
               tracer=None) -> bool:
        digest = fleet_digest(ctx)
        print(f"  [{name}] locations: "
              f"{sum(1 for s in ctx['locations'].values() if s == 's0')} "
              f"on s0, lost={len(ctx['lost'])}, "
              f"migrations={digest['migrations']}")
        _write_artifact(artifact_dir, name, {
            "scenario": name,
            "verdict": "PASS" if not violations else "FAIL",
            "violations": violations,
            "matches_per_shard": p,
            "ticks": ticks,
            **digest,
            **(extra or {}),
            "fleet_obs": fleet_metrics_digest(ctx["sup"]),
            **_fleet_trace_artifact(artifact_dir, name, tracer),
            "metrics": json_snapshot(ctx["sup"].merged_registry()),
        })
        if violations:
            print(f"  {name.upper()} VIOLATED:")
            for v in violations:
                print(f"    {v}")
            return False
        return True

    print("--- shard ---")
    print(f"  two shards x {p} journaled matches, {ticks} ticks")
    control = drive_fleet_chaos(ticks, matches_per_shard=p, seed=seed)

    # 1. kill-a-shard: crash failover from the durable journals alone
    def kill(i, ctx):
        if i == ticks // 2:
            ctx["sup"].kill("s1")

    tr = Tracer(capacity=16384) if artifact_dir is not None else None
    chaos = drive_fleet_chaos(
        ticks, matches_per_shard=p, seed=seed, inject=kill, tracer=tr
    )
    violations = fleet_survivor_violations(chaos, control, survivors)
    violations += fleet_recovery_violations(
        chaos, affected, dead_shards=["s1"]
    )
    recovered = sum(
        1 for m in affected if chaos["locations"][m] not in (None, "s1")
    )
    lag = max(
        (chaos["peer_frames"][m] - (chaos["frames"][m] or 0)
         for m in affected), default=0,
    )
    print(f"  [shard_kill] s1 killed @tick {ticks // 2}: {recovered}/{p} "
          f"matches journal-recovered onto s0, max lag {lag} frames")
    ok &= report("shard_kill", violations, chaos,
                 extra={"recovered": recovered, "max_lag_frames": lag},
                 tracer=tr)

    # 2. drain-under-load: admission off, migrate all, retire
    def drain(i, ctx):
        if i == ticks // 3:
            ctx["sup"].drain("s1")

    tr = Tracer(capacity=16384) if artifact_dir is not None else None
    chaos = drive_fleet_chaos(
        ticks, matches_per_shard=p, seed=seed, inject=drain, tracer=tr
    )
    violations = fleet_survivor_violations(chaos, control, survivors)
    violations += fleet_recovery_violations(chaos, affected)
    state = chaos["sup"].shards["s1"].state
    if state != "retired":
        violations.append(f"drained shard is {state}, not retired")
    print(f"  [shard_drain] s1 drained @tick {ticks // 3}: shard {state}, "
          f"{sum(1 for m in affected if chaos['locations'][m] == 's0')}/{p} "
          "matches migrated to s0")
    ok &= report("shard_drain", violations, chaos,
                 extra={"drained_shard_state": state}, tracer=tr)

    # 3. migrate-under-loss: live migration on a lossy wire + spectators
    lossy = dict(latency_ticks=1, loss=0.05, duplicate=0.02, reorder=0.05)
    lossy_control = drive_fleet_chaos(
        ticks, matches_per_shard=p, seed=seed, fault_cfg=dict(lossy),
        n_spectators=2,
    )

    def migrate(i, ctx):
        if i == ticks // 3:
            ctx["sup"].migrate("m0")

    tr = Tracer(capacity=16384) if artifact_dir is not None else None
    chaos = drive_fleet_chaos(
        ticks, matches_per_shard=p, seed=seed, inject=migrate,
        fault_cfg=dict(lossy), n_spectators=2, tracer=tr,
    )
    untouched = [m for m in chaos["match_ids"] if m != "m0"]
    violations = fleet_survivor_violations(chaos, lossy_control, untouched)
    violations += fleet_recovery_violations(chaos, ["m0"])
    if chaos["locations"]["m0"] == lossy_control["locations"]["m0"]:
        violations.append("m0 never moved")
    # spectator continuity: the stream resumes from the ack window — it
    # never resets/regresses and advances well past the migration tick
    viewer_tips = []
    for v, stream in enumerate(chaos["viewer_streams"]):
        frames = [f for f, _ in stream]
        if frames != sorted(set(frames)):
            violations.append(f"viewer {v} stream reset/regressed")
        if not frames or frames[-1] < ticks // 3 + 8:
            violations.append(
                f"viewer {v} stalled at {frames[-1] if frames else None}"
            )
        viewer_tips.append(frames[-1] if frames else None)
    print(f"  [shard_migrate] m0 -> {chaos['locations']['m0']} under "
          f"loss/dup/reorder; viewers at {viewer_tips}")
    ok &= report("shard_migrate", violations, chaos,
                 extra={"migrated_to": chaos["locations"]["m0"],
                        "viewer_tips": viewer_tips}, tracer=tr)
    if ok:
        print(f"  OK: {p}-per-shard fleet survived kill, drain, and "
              "lossy migration")
    return ok


def verify_proc_leg(matches_per_shard: int, ticks: int, seed: int,
                    artifact_dir=None) -> bool:
    """The out-of-process scenarios (DESIGN.md §17), over
    ``drive_proc_fleet`` — the SAME driver tests/test_fleet_proc.py
    pins.  Shard ``s0`` serves in-process, ``s1`` is a real subprocess
    (scripts/shard_runner.py); every scenario is verified against a
    fault-free proc-backend control and every artifact records the
    ``FleetTuning`` knobs it ran with (round-trippable JSON):

    - ``proc_sigkill``: SIGKILL the shard subprocess mid-traffic; death
      must be detected within the heartbeat deadline, every match must
      re-adopt from its durable journal onto the survivor, the
      surviving shard's peer-observed wire must be bit-identical to
      control, and zero orphan processes/fds may remain.
    - ``proc_sigstop``: SIGSTOP (a hang, not a death) until the
      watchdog escalates SIGTERM → drain deadline → SIGKILL, then the
      same recovery contract — wedged ≠ dead, and failover only after
      confirmed death.
    - ``proc_restart_storm``: kill the same shard 5× fast; the
      jittered-backoff restart policy must respawn it at most
      ``restart_max`` times inside the storm window and then leave it
      dead, with every match still recovered and nothing leaked.
    """
    import os
    import signal
    import time

    from ggrs_tpu.chaos import (
        drive_proc_fleet,
        fleet_recovery_violations,
        fleet_survivor_violations,
    )
    from ggrs_tpu.fleet import FleetTuning, SHARD_DEAD

    p = matches_per_shard
    ticks = max(120, min(ticks, 240))
    tuning = FleetTuning(
        heartbeat_interval_s=0.05, heartbeat_deadline_s=0.5,
        rpc_timeout_s=0.75, drain_deadline_s=0.4,
        spawn_timeout_s=120.0, restart_max=0,
    )
    survivors = [f"m{k}" for k in range(p)]           # pinned to s0
    affected = [f"m{k}" for k in range(p, 2 * p)]     # pinned to s1
    ok = True

    def report(name, violations, ctx, extra=None, tracer=None) -> bool:
        reg = ctx["registry"]
        _write_artifact(artifact_dir, name, {
            "scenario": name,
            "verdict": "PASS" if not violations else "FAIL",
            "violations": violations,
            "matches_per_shard": p,
            "ticks": ticks,
            "tuning": tuning.as_dict(),
            "locations": ctx["locations"],
            "lost": ctx["lost"],
            "healthz": {
                k: v for k, v in ctx["healthz"].items() if k != "shards"
            },
            "s1": ctx["healthz"]["shards"]["s1"],
            "watchdog": {
                stage: int(reg.value(
                    "ggrs_fleet_proc_watchdog_total",
                    shard="s1", stage=stage) or 0)
                for stage in ("sigterm", "sigkill")
            },
            "restarts": int(reg.value(
                "ggrs_fleet_proc_restarts_total", shard="s1") or 0),
            **(extra or {}),
            "fleet_obs": fleet_metrics_digest(ctx["sup"]),
            **_fleet_trace_artifact(artifact_dir, name, tracer),
            "metrics": json_snapshot(ctx["sup"].merged_registry()),
        })
        if violations:
            print(f"  {name.upper()} VIOLATED:")
            for v in violations:
                print(f"    {v}")
            return False
        return True

    print("--- proc ---")
    print(f"  s0 in-process + s1 subprocess x {p} journaled matches, "
          f"{ticks} ticks")
    control = drive_proc_fleet(
        ticks, matches_per_shard=p, seed=seed, backend="proc",
        tuning=tuning,
    )
    control["sup"].close()

    # 1. SIGKILL mid-traffic: crash detection + journal failover
    timing = {}

    def sigkill(i, ctx):
        sup = ctx["sup"]
        if i == ticks // 2:
            timing["pid"] = sup.shards["s1"].pid
            timing["killed_at"] = time.monotonic()
            os.kill(timing["pid"], signal.SIGKILL)
        elif "killed_at" in timing and "detected_at" not in timing:
            if sup.shards["s1"].state == SHARD_DEAD:
                timing["detected_at"] = time.monotonic()

    tr = Tracer(capacity=16384) if artifact_dir is not None else None
    chaos = drive_proc_fleet(
        ticks, matches_per_shard=p, seed=seed, backend="proc",
        tuning=tuning, inject=sigkill, tracer=tr,
    )
    chaos["sup"].close()
    violations = fleet_survivor_violations(chaos, control, survivors)
    violations += fleet_recovery_violations(
        chaos, affected, dead_shards=["s1"]
    )
    detect_s = (
        timing.get("detected_at", float("inf")) - timing["killed_at"]
    )
    if detect_s > tuning.heartbeat_deadline_s:
        violations.append(
            f"death detected in {detect_s:.2f}s > heartbeat deadline "
            f"{tuning.heartbeat_deadline_s}s"
        )
    orphans = chaos["sup"].shards["s1"].orphan_count()
    if orphans:
        violations.append(f"{orphans} orphan runner processes")
    if os.path.exists(f"/proc/{timing['pid']}"):
        violations.append(f"killed runner pid {timing['pid']} not reaped")
    recovered = sum(
        1 for m in affected if chaos["locations"][m] not in (None, "s1")
    )
    print(f"  [proc_sigkill] pid {timing['pid']} SIGKILLed @tick "
          f"{ticks // 2}: detected in {detect_s * 1000:.0f} ms, "
          f"{recovered}/{p} matches journal-recovered, {orphans} orphans")
    ok &= report("proc_sigkill", violations, chaos, extra={
        "recovered": recovered,
        "detect_seconds": detect_s,
        "orphans": orphans,
    }, tracer=tr)

    # 2. SIGSTOP: a hang — watchdog escalation, then the same recovery.
    # tick_sleep stretches real time so the (wall-clock) escalation
    # deadlines can pass without the logical clock outrunning the
    # peers' disconnect timeout.
    def sigstop(i, ctx):
        if i == ticks // 3:
            os.kill(ctx["sup"].shards["s1"].pid, signal.SIGSTOP)

    chaos = drive_proc_fleet(
        ticks, matches_per_shard=p, seed=seed, backend="proc",
        tuning=tuning, inject=sigstop, tick_sleep_s=0.02,
    )
    chaos["sup"].close()
    reg = chaos["registry"]
    violations = fleet_survivor_violations(chaos, control, survivors)
    violations += fleet_recovery_violations(
        chaos, affected, dead_shards=["s1"]
    )
    sigterms = int(reg.value("ggrs_fleet_proc_watchdog_total",
                             shard="s1", stage="sigterm") or 0)
    sigkills = int(reg.value("ggrs_fleet_proc_watchdog_total",
                             shard="s1", stage="sigkill") or 0)
    if not sigterms:
        violations.append("watchdog never escalated to SIGTERM")
    if not sigkills:
        violations.append("watchdog never escalated to SIGKILL")
    orphans = chaos["sup"].shards["s1"].orphan_count()
    if orphans:
        violations.append(f"{orphans} orphan runner processes")
    print(f"  [proc_sigstop] hang @tick {ticks // 3}: escalation "
          f"sigterm={sigterms} sigkill={sigkills}, "
          f"{sum(1 for m in affected if chaos['locations'][m] == 's0')}"
          f"/{p} matches recovered")
    ok &= report("proc_sigstop", violations, chaos, extra={
        "sigterms": sigterms, "sigkills": sigkills, "orphans": orphans,
    })

    # 2b. harvest overhead: the SAME topology with the runner-side obs
    # harvest compiled out (obs_harvest=0) — the runner tick p99 delta
    # prices the piggyback (<5% target, informational: recorded in the
    # artifact, not asserted, because CI boxes jitter)
    from ggrs_tpu.fleet import FleetTuning as _FT
    off = drive_proc_fleet(
        ticks, matches_per_shard=p, seed=seed, backend="proc",
        tuning=_FT.from_dict({**tuning.as_dict(), "obs_harvest": 0}),
    )
    off["sup"].close()
    on_p99 = control["healthz"]["shards"]["s1"].get("tick_p99_ms") or 0.0
    off_p99 = off["healthz"]["shards"]["s1"].get("tick_p99_ms") or 0.0
    pct = (100.0 * (on_p99 - off_p99) / off_p99) if off_p99 else None
    print(f"  [proc_harvest] s1 tick p99: harvest-on {on_p99:.2f} ms vs "
          f"harvest-off {off_p99:.2f} ms "
          f"({'n/a' if pct is None else f'{pct:+.1f}%'}, target <5%)")
    _write_artifact(artifact_dir, "proc_harvest_overhead", {
        "scenario": "proc_harvest_overhead",
        "verdict": "INFO",
        "tick_p99_ms_harvest_on": on_p99,
        "tick_p99_ms_harvest_off": off_p99,
        "overhead_pct": pct,
        "fleet_obs": fleet_metrics_digest(control["sup"]),
    })

    # 3. restart storm: kill the same shard 5x fast; the backoff
    # restart policy must respawn at most restart_max times, then stay
    # dead — a crash loop must not melt the host
    storm_tuning = FleetTuning(
        heartbeat_interval_s=0.05, heartbeat_deadline_s=0.5,
        rpc_timeout_s=0.75, drain_deadline_s=0.3,
        spawn_timeout_s=120.0,
        restart_backoff_s=0.05, restart_max=2, restart_window_s=60.0,
    )
    kills = {"n": 0}

    def storm(i, ctx):
        s1 = ctx["sup"].shards["s1"]
        if i >= ticks // 3 and kills["n"] < 5 and s1.pid and s1._alive():
            kills["n"] += 1
            os.kill(s1.pid, signal.SIGKILL)

    chaos = drive_proc_fleet(
        max(ticks, 240), matches_per_shard=min(p, 4), seed=seed,
        backend="proc", tuning=storm_tuning, inject=storm,
        tick_sleep_s=0.01,
    )
    chaos["sup"].close()
    s1 = chaos["sup"].shards["s1"]
    storm_affected = [
        m for m in chaos["match_ids"]
        if m not in [f"m{k}" for k in range(min(p, 4))]
    ]
    violations = fleet_recovery_violations(
        chaos, storm_affected, dead_shards=["s1"]
    )
    if s1.restarts != storm_tuning.restart_max:
        violations.append(
            f"{s1.restarts} restarts != storm budget "
            f"{storm_tuning.restart_max}"
        )
    if s1.state != SHARD_DEAD:
        violations.append(f"stormed shard is {s1.state}, not dead")
    orphans = s1.orphan_count()
    if orphans:
        violations.append(f"{orphans} orphan runner processes")
    print(f"  [proc_restart_storm] {kills['n']} kills: {s1.restarts} "
          f"restarts (budget {storm_tuning.restart_max}), final state "
          f"{s1.state}, {orphans} orphans")
    ok &= report("proc_restart_storm", violations, chaos, extra={
        "kills": kills["n"], "tuning": storm_tuning.as_dict(),
        "orphans": orphans,
    })
    if ok:
        print(f"  OK: {p}-per-shard subprocess fleet survived SIGKILL, "
              "SIGSTOP escalation, and a restart storm")
    return ok


def verify_net_leg(matches_per_shard: int, ticks: int, seed: int,
                   artifact_dir=None) -> bool:
    """The multi-host fleet link scenarios (DESIGN.md §25), over
    ``drive_proc_fleet(backend="tcp")`` — the proc topology with the
    supervisor↔runner control plane on the authenticated TCP link.
    Every scenario is judged against a fault-free tcp-backend control:

    - ``net_sever``/``net_half_open``: cut the established link (full
      shutdown / write-half only) mid-traffic; the runner must RESUME
      inside the reconnect window with ZERO failovers — the severed
      shard's matches never leave it, the link epoch never moves, and
      the untouched shard stays bit-identical to control.
    - ``net_dribble``: adversarial connections against the live
      listener (garbage-before-magic, slowloris, truncated-then-EOF)
      must each be refused and counted WITHOUT touching the served
      link — the whole fleet stays bit-identical to control.
    - ``net_host_kill``: SIGKILL the runner; a reaped local child is
      confirmed-dead immediately (no window), every match
      journal-recovers onto the survivor, survivors bit-identical to
      control — §16 failover unchanged by the TCP transport.
    - ``net_fence``: SIGSTOP the runner AND sever the link so the
      window expires; failover must wait for the expiry (zero
      failovers while the window is open), the dead incarnation is
      fenced rather than signalled, and when the old runner RESURRECTS
      it must be refused at handshake (HS_REFUSED_FENCE) and exit of
      its own accord.
    - ``net_placement_host_kill``: the §26 placement plane — kill one
      of two HOSTS behind the ingress; every match journal-fails-over
      cross-host onto the survivor, the route epoch is minted past the
      dead host, the ingress flips every affected route, and players +
      viewers keep streaming on the SAME virtual endpoints with the
      untouched host bit-identical to a fault-free control.
    """
    import os
    import signal
    import socket as _socket
    import time

    from ggrs_tpu.chaos import (
        drive_proc_fleet,
        fleet_recovery_violations,
        fleet_survivor_violations,
    )
    from ggrs_tpu.fleet import FleetTuning, SHARD_DEAD

    p = matches_per_shard
    ticks = max(120, min(ticks, 240))
    tuning = FleetTuning(
        heartbeat_interval_s=0.05, heartbeat_deadline_s=0.5,
        rpc_timeout_s=0.75, drain_deadline_s=0.4,
        spawn_timeout_s=120.0, restart_max=0,
        link_auth_token="chaos-net-token",
        link_reconnect_window_s=0.6, link_backoff_s=0.01,
        link_handshake_timeout_s=0.3,
    )
    survivors = [f"m{k}" for k in range(p)]           # pinned to s0
    affected = [f"m{k}" for k in range(p, 2 * p)]     # pinned to s1
    ok = True

    def link_of(ctx):
        return ctx["healthz"]["shards"]["s1"].get("link") or {}

    def report(name, violations, ctx, extra=None) -> bool:
        _write_artifact(artifact_dir, name, {
            "scenario": name,
            "verdict": "PASS" if not violations else "FAIL",
            "violations": violations,
            "matches_per_shard": p,
            "ticks": ticks,
            "tuning": tuning.as_dict(),
            "locations": ctx["locations"],
            "lost": ctx["lost"],
            "link": link_of(ctx),
            "failovers": int(
                ctx["registry"].value("ggrs_fleet_failovers_total") or 0
            ),
            **(extra or {}),
            "fleet_obs": fleet_metrics_digest(ctx["sup"]),
            "metrics": json_snapshot(ctx["sup"].merged_registry()),
        })
        if violations:
            print(f"  {name.upper()} VIOLATED:")
            for v in violations:
                print(f"    {v}")
            return False
        return True

    print("--- net ---")
    print(f"  s0 in-process + s1 subprocess over authenticated TCP x "
          f"{p} journaled matches, {ticks} ticks")
    control = drive_proc_fleet(
        ticks, matches_per_shard=p, seed=seed, backend="tcp",
        tuning=tuning,
    )
    control["sup"].close()

    # 1 + 2. sever the established link (full, then write-half only):
    # the runner must resume inside the window with zero failovers
    for name, how in (("net_sever", "rdwr"), ("net_half_open", "wr")):
        def sever(i, ctx, how=how):
            if i == ticks // 2:
                ctx["sup"].shards["s1"].chaos_sever_link(how)

        chaos = drive_proc_fleet(
            ticks, matches_per_shard=p, seed=seed, backend="tcp",
            tuning=tuning, inject=sever, tick_sleep_s=0.005,
        )
        chaos["sup"].close()
        violations = fleet_survivor_violations(chaos, control, survivors)
        link = link_of(chaos)
        failovers = int(
            chaos["registry"].value("ggrs_fleet_failovers_total") or 0
        )
        if failovers:
            violations.append(
                f"{failovers} failovers despite an open reconnect window"
            )
        moved = [m for m in affected if chaos["locations"][m] != "s1"]
        if moved:
            violations.append(f"matches left the severed shard: {moved}")
        if chaos["lost"]:
            violations.append(f"matches lost: {chaos['lost']}")
        if not link.get("reconnects"):
            violations.append("link never recorded a resume")
        if link.get("window_expiries"):
            violations.append(
                f"{link['window_expiries']} window expiries on a "
                "recoverable sever"
            )
        if link.get("epoch") != 1:
            violations.append(
                f"epoch moved to {link.get('epoch')} without a failover"
            )
        print(f"  [{name}] link cut ({how}) @tick {ticks // 2}: "
              f"state={link.get('state')} epoch={link.get('epoch')} "
              f"reconnects={link.get('reconnects')} "
              f"failovers={failovers}")
        ok &= report(name, violations, chaos)

    # 3. dribble: adversarial connections against the live listener —
    # refused and counted, the served link untouched, fleet
    # bit-identical to control
    dribble_socks = []

    def dribble(i, ctx):
        if i != ticks // 3:
            return
        addr = ctx["sup"].shards["s1"]._link.address
        garbage = _socket.create_connection(addr, timeout=2.0)
        garbage.sendall(b"GET /healthz HTTP/1.1\r\n\r\n")
        dribble_socks.append(garbage)
        slow = _socket.create_connection(addr, timeout=2.0)
        slow.sendall(b"GA")  # the magic, then... nothing
        dribble_socks.append(slow)
        trunc = _socket.create_connection(addr, timeout=2.0)
        trunc.sendall(b"GA\x01\x00")  # a valid prefix, then EOF
        trunc.close()

    try:
        chaos = drive_proc_fleet(
            ticks, matches_per_shard=p, seed=seed, backend="tcp",
            tuning=tuning, inject=dribble, tick_sleep_s=0.005,
        )
    finally:
        for s in dribble_socks:
            try:
                s.close()
            except OSError:
                pass
    chaos["sup"].close()
    violations = fleet_survivor_violations(
        chaos, control, survivors + affected
    )
    link = link_of(chaos)
    refusals = link.get("refusals") or {}
    for reason in ("garbage", "timeout", "eof"):
        if not refusals.get(reason):
            violations.append(f"no {reason!r} refusal recorded")
    if link.get("reconnects"):
        violations.append(
            "dribble connections disturbed the established link"
        )
    failovers = int(
        chaos["registry"].value("ggrs_fleet_failovers_total") or 0
    )
    if failovers:
        violations.append(f"{failovers} failovers from unauthenticated "
                          "dribble traffic")
    print(f"  [net_dribble] 3 hostile conns @tick {ticks // 3}: "
          f"refusals={refusals} failovers={failovers}")
    ok &= report("net_dribble", violations, chaos, extra={
        "refusals": refusals,
    })

    # 4. host kill: SIGKILL over TCP — §16 journal failover must be
    # transport-agnostic (a reaped local child needs no window)
    timing = {}

    def host_kill(i, ctx):
        sup = ctx["sup"]
        if i == ticks // 2:
            timing["pid"] = sup.shards["s1"].pid
            timing["killed_at"] = time.monotonic()
            os.kill(timing["pid"], signal.SIGKILL)
        elif "killed_at" in timing and "detected_at" not in timing:
            if sup.shards["s1"].state == SHARD_DEAD:
                timing["detected_at"] = time.monotonic()

    chaos = drive_proc_fleet(
        ticks, matches_per_shard=p, seed=seed, backend="tcp",
        tuning=tuning, inject=host_kill,
    )
    chaos["sup"].close()
    violations = fleet_survivor_violations(chaos, control, survivors)
    violations += fleet_recovery_violations(
        chaos, affected, dead_shards=["s1"]
    )
    detect_s = (
        timing.get("detected_at", float("inf")) - timing["killed_at"]
    )
    if detect_s > tuning.heartbeat_deadline_s:
        violations.append(
            f"death detected in {detect_s:.2f}s > heartbeat deadline "
            f"{tuning.heartbeat_deadline_s}s"
        )
    orphans = chaos["sup"].shards["s1"].orphan_count()
    if orphans:
        violations.append(f"{orphans} orphan runner processes")
    recovered = sum(
        1 for m in affected if chaos["locations"][m] not in (None, "s1")
    )
    print(f"  [net_host_kill] pid {timing['pid']} SIGKILLed @tick "
          f"{ticks // 2}: detected in {detect_s * 1000:.0f} ms, "
          f"{recovered}/{p} matches journal-recovered, {orphans} orphans")
    ok &= report("net_host_kill", violations, chaos, extra={
        "recovered": recovered, "detect_seconds": detect_s,
        "orphans": orphans,
    })

    # 5. fence: stop the runner AND cut the link; the window must
    # expire before failover, the incarnation is fenced (not
    # signalled), and its resurrected self is refused at handshake
    fence = {}

    def fence_inject(i, ctx):
        s1 = ctx["sup"].shards["s1"]
        if i == ticks // 3:
            fence["pid"] = s1.pid
            fence["proc"] = s1._proc
            os.kill(fence["pid"], signal.SIGSTOP)
            s1.chaos_sever_link()
            return
        if "pid" not in fence:
            return
        if "resurrected" not in fence and s1.state == SHARD_DEAD:
            # confirmed dead via window expiry — bring the old
            # incarnation back from suspension: it must be fenced
            os.kill(fence["pid"], signal.SIGCONT)
            fence["resurrected"] = i
        if "resurrected" in fence:
            s1._link.pump()  # judge the stale runner's redials

    chaos = drive_proc_fleet(
        ticks, matches_per_shard=min(p, 4), seed=seed, backend="tcp",
        tuning=tuning, inject=fence_inject, tick_sleep_s=0.02,
    )
    s1 = chaos["sup"].shards["s1"]
    # the fenced runner exits on its own once refused; give it a beat
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        s1._link.pump()
        if fence.get("proc") is not None and fence["proc"].poll() is not None:
            break
        time.sleep(0.02)
    chaos["sup"].close()
    fence_affected = [
        m for m in chaos["match_ids"]
        if m not in [f"m{k}" for k in range(min(p, 4))]
    ]
    violations = fleet_recovery_violations(
        chaos, fence_affected, dead_shards=["s1"]
    )
    link = link_of(chaos)
    refusals = link.get("refusals") or {}
    if not link.get("window_expiries"):
        violations.append("reconnect window never expired")
    if (link.get("epoch") or 0) < 2:
        violations.append(
            f"epoch {link.get('epoch')} not bumped past the fenced "
            "incarnation"
        )
    if not refusals.get("fence"):
        violations.append("resurrected stale runner was never "
                          "fence-refused at handshake")
    exit_code = fence["proc"].poll() if fence.get("proc") else None
    if exit_code != 1:
        violations.append(
            f"fenced runner exit code {exit_code!r} (want 1: refused "
            "and exited on its own)"
        )
    fence_exit = chaos["healthz"]["shards"]["s1"].get("exit") or ""
    if "fenced" not in fence_exit:
        violations.append(
            f"exit reason {fence_exit!r} does not record the fence"
        )
    orphans = s1.orphan_count()
    if orphans:
        violations.append(f"{orphans} orphan runner processes")
    print(f"  [net_fence] SIGSTOP+sever @tick {ticks // 3}: window "
          f"expiries={link.get('window_expiries')} "
          f"epoch={link.get('epoch')} fence refusals="
          f"{refusals.get('fence', 0)} runner exit={exit_code}")
    ok &= report("net_fence", violations, chaos, extra={
        "refusals": refusals, "runner_exit": exit_code,
        "tuning": tuning.as_dict(),
    })

    # 6. cross-host placement (DESIGN.md §26): kill a whole HOST of the
    # two-host placement fleet mid-traffic — every match on it must
    # journal-fail-over ACROSS hosts onto the survivor while players and
    # viewers keep talking to the SAME virtual endpoints (the ingress
    # flips routes; no client ever re-addresses), the untouched host's
    # matches bit-identical to a fault-free control, zero orphans
    from ggrs_tpu.chaos import drive_placement_fleet

    pp = min(p, 2)
    pticks = max(32, min(ticks, 48))
    kill_tick = pticks // 2
    spectate = f"m{pp}"  # a viewer ON the doomed host's match
    p_control = drive_placement_fleet(
        pticks, matches_per_host=pp, seed=seed, n_spectators=2,
        spectate_match=spectate,
    )
    p_control["close"]()

    def kill_h1(i, ctx):
        if i == kill_tick:
            ctx["placement"].kill_host("h1")

    chaos = drive_placement_fleet(
        pticks, matches_per_host=pp, seed=seed, n_spectators=2,
        spectate_match=spectate, inject=kill_h1,
    )
    chaos["close"]()
    h0_matches = [f"m{k}" for k in range(pp)]
    h1_matches = [f"m{k}" for k in range(pp, 2 * pp)]
    violations = fleet_survivor_violations(chaos, p_control, h0_matches)
    violations += fleet_recovery_violations(chaos, h1_matches)
    for mid in h1_matches:
        loc = chaos["locations"][mid]
        if loc is None or loc[0] == "h1":
            violations.append(f"{mid}: not failed over cross-host ({loc})")
    # the public contract: virtual endpoints NEVER change — same vport
    # per match as the fault-free control, peers/viewers never re-aim
    if chaos["vports"] != p_control["vports"]:
        violations.append(
            f"virtual endpoints changed across the host kill: "
            f"{chaos['vports']} vs control {p_control['vports']}"
        )
    hz = chaos["healthz"]
    if (hz.get("route_epoch") or 0) < 2:
        violations.append(
            f"route epoch {hz.get('route_epoch')} not minted past the "
            "dead host (a stale h1 write could still flip a route)"
        )
    flips = int(
        chaos["registry"].value("ggrs_ingress_route_flips_total") or 0
    )
    if flips < len(h1_matches):
        violations.append(
            f"{flips} ingress route flips < {len(h1_matches)} failovers"
        )
    failovers = int(
        chaos["registry"].value("ggrs_placement_host_failovers_total") or 0
    )
    if failovers != len(h1_matches):
        violations.append(
            f"{failovers} host failovers != {len(h1_matches)} affected"
        )
    for v, stream in enumerate(chaos["viewer_streams"]):
        frames = [f for f, _ in stream]
        if frames != sorted(set(frames)):
            violations.append(f"viewer {v} stream reset/regressed")
        if not frames or frames[-1] < kill_tick + 4:
            violations.append(
                f"viewer {v} stalled at {frames[-1] if frames else None} "
                "after the host kill"
            )
    # §28: every failed-over match's merged timeline must carry the
    # FAILOVER event after its ADMIT — the causal record of the kill
    kill_timelines = _placement_timelines(chaos)
    for mid in h1_matches:
        if not first_occurrence_order(
            kill_timelines.get(mid, []), EV_ADMIT, EV_FAILOVER
        ):
            violations.append(
                f"{mid}: merged timeline missing ADMIT -> FAILOVER "
                f"({[e['ev'] for e in kill_timelines.get(mid, [])]})"
            )
    print(f"  [net_placement_host_kill] h1 killed @tick {kill_tick}: "
          f"{sum(1 for m in h1_matches if chaos['locations'][m] and chaos['locations'][m][0] != 'h1')}"
          f"/{len(h1_matches)} matches failed over cross-host, "
          f"route_epoch={hz.get('route_epoch')} flips={flips} "
          f"viewers at {[s[-1][0] if s else None for s in chaos['viewer_streams']]}")
    _write_artifact(artifact_dir, "net_placement_host_kill", {
        "scenario": "net_placement_host_kill",
        "verdict": "PASS" if not violations else "FAIL",
        "violations": violations,
        "matches_per_host": pp,
        "ticks": pticks,
        "locations": {m: list(v) if v else None
                      for m, v in chaos["locations"].items()},
        "vports": chaos["vports"],
        "lost": chaos["lost"],
        "route_epoch": hz.get("route_epoch"),
        "flips": flips,
        "failovers": failovers,
        "healthz": {k: v for k, v in hz.items() if k != "shards"},
        "timeline": kill_timelines,
        "slo": hz.get("slo"),
        **_timeline_trace_artifact(artifact_dir, "net_placement_host_kill",
                                   kill_timelines),
        "metrics": json_snapshot(chaos["registry"]),
    })
    if violations:
        print("  NET_PLACEMENT_HOST_KILL VIOLATED:")
        for v in violations:
            print(f"    {v}")
        ok = False

    # 7. cross-host live migration (§26 + §28): migrate one live match
    # h1 -> h0 mid-traffic; beyond the §26 contract (peer/viewers never
    # re-aim, survivors bit-identical), the §28 acceptance is causal:
    # ONE merged timeline — stitched from both hosts, the placement
    # plane, and the ingress's trace-keyed flip — must read
    # ADMIT -> MIGRATE_BEGIN -> ROUTE_FLIP -> MIGRATE_COMMIT in order,
    # and its Perfetto re-emission must schema-validate
    mig_mid = f"m{pp}"  # pinned to h1
    mig_tick = pticks // 3

    def migrate_m(i, ctx):
        if i == mig_tick:
            ctx["placement"].migrate(mig_mid, reason="chaos")

    chaos = drive_placement_fleet(
        pticks, matches_per_host=pp, seed=seed, n_spectators=2,
        spectate_match=spectate, inject=migrate_m,
    )
    chaos["close"]()
    untouched = [m for m in chaos["match_ids"] if m != mig_mid]
    violations = fleet_survivor_violations(chaos, p_control, untouched)
    violations += fleet_recovery_violations(chaos, [mig_mid])
    mig_loc = chaos["locations"][mig_mid]
    if mig_loc is None or mig_loc[0] != "h0":
        violations.append(
            f"{mig_mid}: not serving on h0 after migration ({mig_loc})"
        )
    if chaos["vports"] != p_control["vports"]:
        violations.append("virtual endpoints changed across the migration")
    mig_timelines = _placement_timelines(chaos)
    mig_events = mig_timelines.get(mig_mid, [])
    if not first_occurrence_order(
        mig_events, EV_ADMIT, EV_MIGRATE_BEGIN, EV_ROUTE_FLIP,
        EV_MIGRATE_COMMIT,
    ):
        violations.append(
            f"{mig_mid}: merged timeline out of causal order: "
            f"{[e['ev'] for e in mig_events]}"
        )
    origins = {e.get("origin", "").split("/")[0] for e in mig_events}
    if not {"h1", "placement"} <= origins:
        violations.append(
            f"{mig_mid}: timeline not cross-source (origins {origins})"
        )
    trace_info = _timeline_trace_artifact(
        artifact_dir, "net_placement_migrate", mig_timelines)
    if trace_info.get("timeline_trace_problems"):
        violations.append(
            "timeline Perfetto export failed schema validation: "
            f"{trace_info['timeline_trace_problems'][:2]}"
        )
    print(f"  [net_placement_migrate] {mig_mid} h1 -> "
          f"{mig_loc[0] if mig_loc else '?'} @tick {mig_tick}: "
          f"{len(mig_events)} timeline events "
          f"({' -> '.join(dict.fromkeys(e['ev'] for e in mig_events))})")
    _write_artifact(artifact_dir, "net_placement_migrate", {
        "scenario": "net_placement_migrate",
        "verdict": "PASS" if not violations else "FAIL",
        "violations": violations,
        "matches_per_host": pp,
        "ticks": pticks,
        "migrated": mig_mid,
        "migrated_to": list(mig_loc) if mig_loc else None,
        "locations": {m: list(v) if v else None
                      for m, v in chaos["locations"].items()},
        "vports": chaos["vports"],
        "lost": chaos["lost"],
        "timeline": mig_timelines,
        "slo": chaos["healthz"].get("slo"),
        **trace_info,
        "metrics": json_snapshot(chaos["registry"]),
    })
    if violations:
        print("  NET_PLACEMENT_MIGRATE VIOLATED:")
        for v in violations:
            print(f"    {v}")
        ok = False

    if ok:
        print(f"  OK: {p}-per-shard TCP fleet resumed severed links "
              "with zero failovers, shrugged off hostile dribble, "
              "failed over a killed host bit-identically, fenced a "
              "resurrected stale runner, and failed a dead HOST over "
              "cross-host behind unchanged virtual endpoints")
    return ok


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--matches", type=int, default=4,
                    help="in-bank 2-peer matches (default 4 -> B=9 slots)")
    ap.add_argument("--ticks", type=int, default=300)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--fault", choices=[*FAULTS, "lockstep", "spectator",
                                        "socket", "shard", "proc", "net",
                                        "all"],
                    default="all")
    ap.add_argument("--fleet-matches", type=int, default=32, metavar="B",
                    help="matches per shard for --fault shard (default 32; "
                         "the acceptance floor)")
    ap.add_argument("--artifact-dir", default=None, metavar="DIR",
                    help="write one machine-readable JSON artifact per "
                         "scenario (digest + verdict + DesyncReport paths)")
    args = ap.parse_args()

    names = (
        [*FAULTS, "lockstep", "spectator", "socket", "shard", "proc", "net"]
        if args.fault == "all"
        else [args.fault]
    )
    ok = True
    for name in names:
        if name == "lockstep":
            ok &= verify_lockstep_leg(
                args.matches, args.ticks, args.seed,
                artifact_dir=args.artifact_dir,
            )
        elif name == "proc":
            ok &= verify_proc_leg(
                args.fleet_matches, args.ticks, args.seed,
                artifact_dir=args.artifact_dir,
            )
        elif name == "net":
            ok &= verify_net_leg(
                min(args.fleet_matches, 8), args.ticks, args.seed,
                artifact_dir=args.artifact_dir,
            )
        elif name == "spectator":
            ok &= verify_broadcast_leg(
                min(args.matches, 2), args.ticks, args.seed,
                artifact_dir=args.artifact_dir,
            )
        elif name == "socket":
            ok &= verify_socket_leg(
                min(args.matches, 3), args.ticks, args.seed,
                artifact_dir=args.artifact_dir,
            )
        elif name == "shard":
            ok &= verify_fleet_leg(
                args.fleet_matches, args.ticks, args.seed,
                artifact_dir=args.artifact_dir,
            )
        else:
            ok &= verify_leg(name, args.matches, args.ticks, args.seed,
                             artifact_dir=args.artifact_dir)
    print("chaos verdict:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
