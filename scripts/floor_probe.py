"""Floor probe (VERDICT r4 item 2): what does a BARE lax.scan(advance) cost?

Measures, on the same chip with the same completion fence as bench.py:
  1. bare      — jit(lax.scan(advance)) alone: no ring, no digest, no history
  2. +digest   — bare plus the 4-lane checksum per step
  3. +ring     — bare plus digest plus the state-ring save per step
  4. flagship  — the full steady replay program (DeviceSyncTestSession path)

All variants run the same number of advance() steps per dispatch and the
same number of dispatches, so the per-step deltas attribute the flagship's
overhead.  If (1) is already below the 100k resim-frames/sec north star,
the serial scan step IS the floor and the target re-scopes to the batch
axis with this as evidence; if (1) clears 100k, the extras are the gap and
must be shaved.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from bench import enter_honest_timing_mode, REPEATS
from ggrs_tpu.games import BoxGame
from ggrs_tpu.ops.checksum import checksum_device, CHECKSUM_LANES
from ggrs_tpu.ops.ring import DeviceStateRing
from ggrs_tpu.sessions import DeviceSyncTestSession

D = 8                    # flagship check distance
TICKS_PER_DISPATCH = 1024
DISPATCHES = 8
PLAYERS = 2

# flagship steady tick = d resim advances + 1 live advance; count d "resim
# frames" per tick.  Bare variants run the same TOTAL advance steps per
# dispatch as the flagship's (d+1)*ticks, credited at the same d-per-tick
# rate, so per-step work is identical and only the extras differ.
STEPS_PER_DISPATCH = (D + 1) * TICKS_PER_DISPATCH


def main() -> None:
    game = BoxGame(PLAYERS)
    init = game.init_state()
    rng = np.random.default_rng(7)

    def staged_inputs(n):
        return jnp.asarray(rng.integers(0, 16, size=(n, PLAYERS), dtype=np.uint8))

    # ---- variant builders: (state-carry, inputs) -> state-carry -------------
    def bare_body(st, inp):
        return game.advance(st, inp), None

    def digest_body(carry, inp):
        st, acc = carry
        st = game.advance(st, inp)
        return (st, acc ^ checksum_device(st)), None

    ring = DeviceStateRing(D + 2)

    def ring_body(carry, xs):
        st, rbufs = carry
        inp, f = xs
        st = game.advance(st, inp)
        cs = checksum_device(st)
        rbufs = ring.save(rbufs, f, st, cs)
        return (st, rbufs), None

    bare = jax.jit(lambda st, inps: jax.lax.scan(bare_body, st, inps)[0])
    digest = jax.jit(
        lambda c, inps: jax.lax.scan(digest_body, c, inps)[0]
    )
    ringp = jax.jit(lambda c, xs: jax.lax.scan(ring_body, c, xs)[0])

    frames = jnp.arange(STEPS_PER_DISPATCH, dtype=jnp.int32)
    inps = staged_inputs(STEPS_PER_DISPATCH)

    st0 = jax.tree_util.tree_map(jnp.asarray, init)
    acc0 = jnp.zeros((CHECKSUM_LANES,), jnp.uint32)
    rbufs0 = ring.init(init)

    # flagship program via the session, exactly as bench.py drives it
    sess = DeviceSyncTestSession(
        game.advance, init, jnp.zeros((PLAYERS,), jnp.uint8),
        check_distance=D, max_prediction=D,
    )
    tick_inps = staged_inputs(TICKS_PER_DISPATCH)

    # ---- honest mode FIRST, then warm up with real fences ------------------
    # (deferring the first D2H past a pile of enqueued warmup work makes the
    # eventual fence surface async errors far from their source)
    enter_honest_timing_mode()
    jax.block_until_ready(bare(st0, inps))
    jax.block_until_ready(digest((st0, acc0), inps))
    jax.block_until_ready(ringp((st0, rbufs0), (inps, frames)))
    sess.run_ticks(tick_inps, check=False)
    sess.run_ticks(tick_inps, check=False)
    sess.block_until_ready()

    def timed(fn) -> float:
        best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            out = None
            for _ in range(DISPATCHES):
                out = fn()
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        return best

    results = {}
    t = timed(lambda: bare(st0, inps))
    results["bare"] = t
    t = timed(lambda: digest((st0, acc0), inps))
    results["digest"] = t
    t = timed(lambda: ringp((st0, rbufs0), (inps, frames)))
    results["ring"] = t

    def flagship_pass():
        sess.run_ticks(tick_inps, check=False)
        return None

    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        for _ in range(DISPATCHES):
            flagship_pass()
        sess.block_until_ready()
        best = min(best, time.perf_counter() - t0)
    results["flagship"] = best

    total_steps = DISPATCHES * STEPS_PER_DISPATCH
    resim_credit = DISPATCHES * TICKS_PER_DISPATCH * D  # what bench.py counts
    print(f"backend={jax.default_backend()} device={jax.devices()[0].device_kind}")
    for name, dt in results.items():
        steps_ps = total_steps / dt
        resim_ps = resim_credit / dt
        us = dt / total_steps * 1e6
        print(
            f"{name:10s} {dt*1e3:9.1f} ms  {us:7.3f} us/advance-step  "
            f"{steps_ps:10.0f} steps/s  -> {resim_ps:10.0f} resim-credit f/s"
        )
    print(
        "verdict: bare scan resim-credit "
        f"{resim_credit / results['bare']:.0f} f/s vs 100k north star"
    )
    sess.verify()
    print("desync gate green")


if __name__ == "__main__":
    main()
